#!/usr/bin/env python
"""Routing benchmark: PUBLISH routes/sec + p99 match latency vs CPU baseline.

Implements the five configs of BASELINE.json. The reference publishes no
routing-match microbenchmark (BASELINE.md), so the baseline is our own CPU
``DefaultRouter``-equivalent (the TopicTree trie oracle, mirroring
`/root/reference/rmqtt/src/router.rs:174-265` + `trie.rs:288-408`), measured
on the *same* filter set over a topic subsample; the TPU side runs the
batched automaton matcher end-to-end (host encode → kernel → fid decode).

Prints ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
Per-config detail goes to stderr.

Usage:
  python bench.py              # default: configs 1-3 (headline = config 3)
  python bench.py --full       # adds configs 4-5 (10M subs; slower build)
  python bench.py --smoke      # tiny config 1 only (CI / CPU-friendly)
  python bench.py --config N   # run just config N (headline = it)
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------- generators


def gen_exact(rng, n):
    """Config 1: exact-match filters, no wildcards (depth 3-5)."""
    filters = set()
    while len(filters) < n:
        depth = rng.randint(3, 5)
        filters.add("/".join(f"l{d}n{rng.randrange(max(4, n >> (8 - d)))}" for d in range(depth)))
    return sorted(filters)


def gen_single_plus(rng, n):
    """Config 2: single-level '+' wildcards (depth 3-5, one + each)."""
    filters = set()
    while len(filters) < n:
        depth = rng.randint(3, 5)
        levels = [f"l{d}n{rng.randrange(max(4, n >> (8 - d)))}" for d in range(depth)]
        levels[rng.randrange(depth)] = "+"
        filters.add("/".join(levels))
    return sorted(filters)


VOCAB6 = [50, 80, 100, 150, 200, 400]  # per-level vocabulary of the 6-level tree


def _tree_topic(rng, depth=6):
    return "/".join(f"v{d}_{rng.randrange(VOCAB6[d])}" for d in range(depth))


def gen_mixed(rng, n, shared_frac=0.0):
    """Configs 3/4: mixed +/# wildcards over a 6-level topic tree."""
    filters = set()
    while len(filters) < n:
        depth = rng.randint(2, 6)
        levels = [f"v{d}_{rng.randrange(VOCAB6[d])}" for d in range(depth)]
        r = rng.random()
        if r < 0.35:  # sprinkle +
            for _ in range(rng.randint(1, 2)):
                levels[rng.randrange(depth)] = "+"
        if r >= 0.25 and r < 0.55:
            levels[-1] = "#"
        f = "/".join(levels)
        if shared_frac and rng.random() < shared_frac:
            f = "$share/g%d/%s" % (rng.randrange(16), f)
        filters.add(f)
    return sorted(filters)


def gen_topics_uniform(rng, n, depth=6):
    return [_tree_topic(rng, depth) for _ in range(n)]


def gen_topics_zipf(rng, n, depth=6, a=1.3):
    """Zipf-skewed publish stream over the topic tree (config 4)."""
    nprng = np.random.default_rng(rng.randrange(2**31))
    out = []
    for _ in range(n):
        ranks = nprng.zipf(a, size=depth)
        out.append("/".join(f"v{d}_{(int(ranks[d]) - 1) % VOCAB6[d]}" for d in range(depth)))
    return out


# ---------------------------------------------------------------- measurement


def build_tpu_table(filters, kind="dense"):
    from rmqtt_tpu.core.topic import parse_shared

    if kind == "dense":
        from rmqtt_tpu.ops.encode import FilterTable

        table = FilterTable()
    else:
        from rmqtt_tpu.ops.partitioned import PartitionedTable

        table = PartitionedTable()
    fids = {}
    t0 = time.perf_counter()
    for f in filters:
        _, stripped = parse_shared(f)
        fids[table.add(stripped)] = stripped
    log(f"  {kind} table build: {len(filters)} filters in {time.perf_counter() - t0:.2f}s "
        f"(L={table.max_levels}, vocab={len(table.tokens)})")
    return table, fids


def build_cpu_tree(filters):
    from rmqtt_tpu.core.topic import parse_shared
    from rmqtt_tpu.core.trie import TopicTree

    tree = TopicTree()
    t0 = time.perf_counter()
    for i, f in enumerate(filters):
        _, stripped = parse_shared(f)
        tree.insert(stripped, i)
    log(f"  trie build: {time.perf_counter() - t0:.2f}s")
    return tree


def make_matcher(table):
    from rmqtt_tpu.ops.encode import FilterTable
    from rmqtt_tpu.ops.match import TpuMatcher
    from rmqtt_tpu.ops.partitioned import PartitionedMatcher

    return TpuMatcher(table) if isinstance(table, FilterTable) else PartitionedMatcher(table)


def measure_tpu(matcher, topics, batch_size, warmup=2, min_batches=8, pipeline_depth=3):
    """End-to-end topics/sec + per-batch latency through the batched matcher.

    Throughput is measured PIPELINED when the matcher supports
    submit/complete (jax dispatch is async, so batch N+1's host encode
    overlaps batch N's device compute — essential when dispatch latency is
    high, e.g. the ~68ms tunnel); latency percentiles come from serial
    round trips."""
    batches = [topics[i : i + batch_size] for i in range(0, len(topics), batch_size)]
    batches = [b for b in batches if len(b) == batch_size]
    if len(batches) < warmup + min_batches:
        batches = batches * ((warmup + min_batches) // max(1, len(batches)) + 1)
    # warmup (compile)
    t0 = time.perf_counter()
    try:
        for b in batches[:warmup]:
            matcher.match(b)
    except Exception as e:
        # round 2's cfg4 died here on-chip (10M-sub table → one huge
        # device_put/compile → "TPU backend setup/compile error"): retry
        # once with the table split into bounded segments before giving up
        if not hasattr(matcher, "_seg_bytes") or matcher._segments is not None:
            raise
        log(f"  warmup failed ({type(e).__name__}: {e}); retrying with a "
            f"segmented device table")
        matcher._seg_bytes = min(matcher._seg_bytes, 128 << 20)
        matcher._dev_version = -1
        matcher._dev_arrays = None
        for b in batches[:warmup]:
            matcher.match(b)
    log(f"  tpu warmup/compile: {time.perf_counter() - t0:.2f}s")
    # latency: serial round trips on a few batches
    lat = []
    for b in batches[warmup : warmup + max(4, min_batches // 2)]:
        t1 = time.perf_counter()
        matcher.match(b)
        lat.append(time.perf_counter() - t1)
    # throughput: pipelined over all measurement batches
    routes = 0
    done = 0
    work = batches[warmup:]
    t_start = time.perf_counter()
    if hasattr(matcher, "match_submit"):
        from collections import deque

        pending = deque()
        for b in work:
            pending.append((len(b), matcher.match_submit(b)))
            if len(pending) >= pipeline_depth:
                n, h = pending.popleft()
                rows = matcher.match_complete(h)
                routes += sum(len(r) for r in rows)
                done += n
        while pending:
            n, h = pending.popleft()
            rows = matcher.match_complete(h)
            routes += sum(len(r) for r in rows)
            done += n
    else:
        for b in work:
            rows = matcher.match(b)
            routes += sum(len(r) for r in rows)
            done += len(b)
    total = time.perf_counter() - t_start
    return {
        "topics_per_sec": done / total,
        "routes_per_sec": routes / total,
        "routes": routes,
        "topics": done,
        "p50_ms": float(np.percentile(lat, 50) * 1e3),
        "p99_ms": float(np.percentile(lat, 99) * 1e3),
        "batch_size": batch_size,
        "pipelined": hasattr(matcher, "match_submit"),
    }


def build_native_trie(filters):
    """C++ trie (runtime/topics.cc) — the honest native CPU baseline."""
    from rmqtt_tpu import runtime
    from rmqtt_tpu.core.topic import parse_shared

    if not runtime.available():
        return None
    t0 = time.perf_counter()
    trie = runtime.NativeTrie()
    for i, f in enumerate(filters):
        _, stripped = parse_shared(f)
        trie.add(stripped, i)
    log(f"  native trie build: {time.perf_counter() - t0:.2f}s")
    return trie


def measure_cpu_native(trie, topics, sample, time_budget_s=20.0):
    sub = topics[:sample]
    t0 = time.perf_counter()
    routes = 0
    done = 0
    step = 512
    for i in range(0, len(sub), step):
        rows = trie.match_batch(sub[i : i + step])
        routes += sum(len(r) for r in rows)
        done += len(rows)
        if time.perf_counter() - t0 > time_budget_s:
            break
    total = time.perf_counter() - t0
    return {"topics_per_sec": done / total, "routes_per_sec": routes / total,
            "topics": done, "routes": routes}


def measure_cpu(tree, topics, sample, time_budget_s=20.0):
    """CPU trie matches/sec over a subsample of the same topic stream."""
    sub = topics[:sample]
    t0 = time.perf_counter()
    routes = 0
    done = 0
    for topic in sub:
        for _f, vals in tree.matches(topic):
            routes += len(vals)
        done += 1
        if time.perf_counter() - t0 > time_budget_s:
            break
    total = time.perf_counter() - t0
    return {
        "topics_per_sec": done / total,
        "routes_per_sec": routes / total,
        "topics": done,
        "routes": routes,
    }


def spot_check(matcher, fids, tree, topics, n=32):
    """Correctness: TPU fids ≡ trie values on a topic sample."""
    sample = topics[:n]
    rows = matcher.match(sample)
    for topic, row in zip(sample, rows):
        tpu_filters = sorted(fids[fid] for fid in row.tolist())
        cpu_filters = sorted(
            fids_str for _lv, vals in tree.matches(topic) for fids_str in ["/".join(_lv)] * len(vals)
        )
        assert tpu_filters == cpu_filters, f"mismatch on {topic!r}:\n{tpu_filters}\nvs\n{cpu_filters}"
    log(f"  spot check: {n} topics agree with CPU oracle")


# ---------------------------------------------------------------- configs


_PROFILE_DIR = None  # set by main --profile; traces the DEVICE phase only


class _DeviceProfile:
    """Profile just the measured device phase — a trace spanning the
    minutes of data generation / CPU baselines would bury the kernels.
    Profiler failures (unwritable dir, double-start) must never fail the
    bench: they log and measurement continues untraced."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._cm = None

    def __enter__(self):
        if _PROFILE_DIR is None:
            return self
        try:
            import jax

            self._cm = jax.profiler.trace(f"{_PROFILE_DIR}/{self.name}")
            self._cm.__enter__()
        except Exception as e:
            log(f"profiler unavailable ({e}); continuing without trace")
            self._cm = None
        return self

    def __exit__(self, *exc):
        if self._cm is not None:
            try:
                self._cm.__exit__(*exc)
            except Exception as e:
                log(f"profiler stop failed ({e})")
        return False


def _device_profile(name):
    return _DeviceProfile(name)


def run_config(name, filters, topics, batch_size, cpu_sample, retained=None):
    log(f"[{name}] {len(filters)} subs, {len(topics)} publish topics")
    tree = build_cpu_tree(filters)
    cpu = measure_cpu(tree, topics, cpu_sample)
    native = build_native_trie(filters)
    cpu_native = measure_cpu_native(native, topics, cpu_sample * 4) if native else None
    # ≤2M subs: keep the C++ trie — the hybrid router-level measurement
    # reuses it as the side mirror (the deployed XlaRouter holds both).
    # Above that, free it before the big device-table builds (round-2 OOM
    # guard); the router-level figure is then derived from measured rates
    # instead of holding trie+table resident twice in this one process.
    keep_side = native is not None and len(filters) <= 2_000_000
    if not keep_side:
        del native
        native = None
    variants = {}
    kinds = ("partitioned", "dense")
    if len(filters) > 2_000_000:
        # dense scans every filter row per topic: at 10M subs one batch costs
        # minutes on the measured tunnel (~230s warmup batch) and it can never
        # beat the partitioned automaton there — skip it instead of burning
        # most of the bench budget on a known-losing variant
        kinds = ("partitioned",)
    for kind in kinds:
        table, fids = build_tpu_table(filters, kind)
        # ONE matcher (and one device table upload) per variant: spot check,
        # measurement and the retained interleave all share it
        matcher = make_matcher(table)
        spot_check(matcher, fids, tree, topics)
        with _device_profile(f"{name}_{kind}"):
            variants[kind] = measure_tpu(matcher, topics, batch_size)
            if retained is not None and kind == kinds[-1]:
                variants["retained"] = run_retained(matcher, retained, topics)
        if kind == "partitioned":
            # ROUTER-LEVEL measurement: the XlaRouter as deployed races the
            # host trie mirror against the device per regime (ops/hybrid.py)
            # — this is the number a broker user actually gets, reported as
            # the headline alongside the raw device figure
            if keep_side:
                variants["hybrid"] = measure_hybrid(matcher, native, topics,
                                                    batch_size)
            elif cpu_native is not None:
                # 10M-sub configs: derive the deployed choice from the two
                # measured rates (see keep_side above)
                dev = dict(variants[kind])
                dev_wins = dev["topics_per_sec"] >= cpu_native["topics_per_sec"]
                if not dev_wins:
                    dev.update({k2: cpu_native[k2] for k2 in
                                ("topics_per_sec", "routes_per_sec")})
                dev["hybrid_choice"] = "device" if dev_wins else "side(derived)"
                variants["hybrid"] = dev
            if _ON_TPU:
                # the stream sweep measures DEVICE dispatch overlap (the
                # burst-p99 artifact); on the CPU fallback it only burns
                # the snapshot run's budget
                stream = measure_stream(matcher, topics)
                if stream is not None:
                    variants["stream"] = stream
            # analytic HBM model against THIS table + topic stream, embedded
            # next to the measured rate so every artifact carries its own
            # modeled-vs-measured delta (roofline claim checkable per run)
            try:
                from rmqtt_tpu.bench.roofline_model import model_table
                from rmqtt_tpu.core.topic import split_levels

                ncs = [len(table._candidates_for(split_levels(tp)))
                       for tp in topics[:2048]]
                variants["roofline_model"] = model_table(
                    table, ncs,
                    measured_topics_per_sec=variants[kind]["topics_per_sec"])
            except Exception as e:  # the bench must not die on the model
                log(f"  roofline model skipped: {e}")
        del table, fids, matcher
    best_kind = max(kinds, key=lambda k: variants[k]["topics_per_sec"])
    tpu = variants[best_kind]
    # the honest baseline is the native (C++) trie when the toolchain exists
    baseline = cpu_native or cpu
    res = {
        "name": name,
        "tpu": tpu,
        "tpu_backend": best_kind,
        "variants": variants,
        "cpu": cpu,
        "cpu_native": cpu_native,
        "baseline_kind": "cpu_native" if cpu_native else "cpu_python",
        "speedup": tpu["topics_per_sec"] / baseline["topics_per_sec"],
    }
    hyb = variants.get("hybrid")
    if hyb is not None:
        res["router"] = hyb
        res["router_speedup"] = hyb["topics_per_sec"] / baseline["topics_per_sec"]
    if "stream" in variants:
        res["stream"] = variants.pop("stream")
    if "retained" in variants:
        res["retained"] = variants.pop("retained")
    if "roofline_model" in variants:
        res["roofline_model"] = variants.pop("roofline_model")
    nat = f" native {cpu_native['topics_per_sec']:.0f}" if cpu_native else ""
    rtr = (f" | router(hybrid→{hyb.get('hybrid_choice')}) "
           f"{hyb['topics_per_sec']:.0f} topics/s "
           f"{res['router_speedup']:.2f}x" if hyb else "")
    log(
        f"[{name}] TPU[{best_kind}] {tpu['topics_per_sec']:.0f} topics/s "
        f"({tpu['routes_per_sec']:.0f} routes/s, p50 {tpu['p50_ms']:.1f}ms "
        f"p99 {tpu['p99_ms']:.1f}ms) | CPU {cpu['topics_per_sec']:.0f}{nat} topics/s "
        f"| speedup {res['speedup']:.2f}x vs {res['baseline_kind']}{rtr}"
    )
    return res


# set once in main() from the probe + resolved platform (single source of
# truth; run_config must not re-touch the backend to learn it)
_ON_TPU = False


def measure_stream(matcher, topics, micro_sizes=(2048, 4096), depth=3,
                   min_batches=24):
    """Burst p99 under a CONTINUOUS pipelined micro-batch stream (VERDICT
    r3 item 3): instead of one serial batch-sized dispatch (sum of stages —
    258.7ms standing at cfg3/16K), micro-batches stream through
    submit/complete with ``depth`` in flight, so per-batch latency tends to
    the slowest stage. Per-batch latency = submit→complete wall time while
    the pipeline is kept full; reports the best micro size by p99."""
    if not hasattr(matcher, "match_submit"):
        return None
    from collections import deque

    best = None
    for micro in micro_sizes:
        stream = [topics[i:i + micro] for i in range(0, len(topics), micro)]
        stream = [b for b in stream if len(b) == micro]
        if not stream:
            continue
        while len(stream) < min_batches + depth:
            stream = stream + stream
        stream = stream[: min_batches + depth]
        matcher.match(stream[0])  # warm this shape
        lat = []
        pending = deque()
        t_all = time.perf_counter()
        for b in stream:
            pending.append((time.perf_counter(), len(b), matcher.match_submit(b)))
            if len(pending) >= depth:
                t_sub, _n, h = pending.popleft()
                matcher.match_complete(h)
                lat.append(time.perf_counter() - t_sub)
        while pending:
            t_sub, _n, h = pending.popleft()
            matcher.match_complete(h)
            lat.append(time.perf_counter() - t_sub)
        total = time.perf_counter() - t_all
        rec = {
            "micro_batch": micro,
            "depth": depth,
            "stream_topics_per_sec": round(len(stream) * micro / total, 1),
            "stream_p50_ms": round(float(np.percentile(lat, 50) * 1e3), 2),
            "stream_p99_ms": round(float(np.percentile(lat, 99) * 1e3), 2),
        }
        log(f"  stream micro={micro} depth={depth}: "
            f"{rec['stream_topics_per_sec']:.0f} topics/s, "
            f"p50 {rec['stream_p50_ms']}ms p99 {rec['stream_p99_ms']}ms")
        if best is None or rec["stream_p99_ms"] < best["stream_p99_ms"]:
            best = rec
    return best


def measure_hybrid(matcher, side, topics, batch_size):
    """The router-level number: AdaptiveHybrid (host C++ trie vs device
    kernel, measured per regime) over the same stream — plus the 1-topic
    p99 the sub-threshold path guarantees. ``side`` is the baseline's
    already-built NativeTrie (fid value spaces differ from the device
    table's; only match COUNTS and rates matter here — correctness of both
    engines is pinned by spot_check and the differential suite)."""
    from rmqtt_tpu.ops.hybrid import AdaptiveHybrid

    hybrid = AdaptiveHybrid(side, matcher, probe_every=16)
    out = measure_tpu(hybrid, topics, batch_size, warmup=1)
    out["hybrid_choice"] = hybrid.choice or "device"
    # small-batch latency: the deployed router's 1-topic publish path
    lat1 = []
    for t in topics[:64]:
        t1 = time.perf_counter()
        hybrid.match([t])
        lat1.append(time.perf_counter() - t1)
    out["p99_1topic_ms"] = float(np.percentile(lat1, 99) * 1e3)
    return out


def run_retained(matcher, retained_topics, publish_topics):
    """Config 5 extra: concurrent retained-scan (SUBSCRIBE) + publish routing.

    The scan side runs the PARTITIONED inverse matcher (ops/retained_part,
    VERDICT r4 item 3): a realistic subscriber mix — mostly prefix filters
    that prune to a few partition chunks, a tail of broad multi-wildcard
    filters that genuinely scan everything — pipelined against the publish
    stream so scan dispatch overlaps publish compute."""
    from rmqtt_tpu.ops.retained_part import PartitionedRetainedScanner, RetainedTable

    rt = RetainedTable()
    t0 = time.perf_counter()
    for t in retained_topics:
        rt.add(t)
    log(f"  retained table: {len(retained_topics)} topics in {time.perf_counter() - t0:.2f}s "
        f"({rt.nchunks} chunks)")
    scanner = PartitionedRetainedScanner(rt)
    # subscriber filter mix: 70% device/prefix-scoped (the reference's
    # retained replay is per-subscription, e.g. home/+/temp), 20% mid-tree
    # wildcards, 10% broad
    rng = random.Random(5)
    sub_filters = []
    for _ in range(512):
        r = rng.random()
        if r < 0.7:
            f = f"v0_{rng.randrange(VOCAB6[0])}/v1_{rng.randrange(VOCAB6[1])}/+"
            if rng.random() < 0.5:
                f += "/#"
        elif r < 0.9:
            f = f"v0_{rng.randrange(VOCAB6[0])}/+/+/#"
        else:
            f = "/".join(["+"] * rng.randint(1, 4)) + "/#"
        sub_filters.append(f)
    pb, sb = 1024, 64
    scanner.scan(sub_filters[:sb])
    matcher.match(publish_topics[:pb])  # warm
    t0 = time.perf_counter()
    rounds = 8

    def scan_slice(r):
        lo = (r * sb) % (len(sub_filters) - sb)
        return sub_filters[lo: lo + sb]

    for r in range(rounds):
        ph = matcher.match_submit(publish_topics[r * pb: (r + 1) * pb]) \
            if hasattr(matcher, "match_submit") else None
        sh = scanner.scan_submit(scan_slice(r))
        if ph is None:
            matcher.match(publish_topics[r * pb: (r + 1) * pb])
        else:
            matcher.match_complete(ph)
        scanner.scan_complete(sh)
    total = time.perf_counter() - t0
    # the interleaved figure above couples scans to the publish matcher's
    # round time (on the CPU fallback the publish side dominates by ~10x);
    # a scan-only phase isolates the retained path itself
    t1 = time.perf_counter()
    for r in range(rounds):
        scanner.scan_complete(scanner.scan_submit(scan_slice(r)))
    scan_only = time.perf_counter() - t1
    return {
        "publish_topics_per_sec": rounds * pb / total,
        "subscribe_scans_per_sec": rounds * sb / total,
        "scan_only_scans_per_sec": rounds * sb / scan_only,
        "scan_backend": "partitioned",
    }


def run_cache_config(name, rng, reduced):
    """Config 6: the epoch-versioned match-result cache on the CPU/native
    router path under zipf-skewed publish traffic (the hot-topic regime the
    cache targets) — cache-on vs cache-off topics/s with hit rate, plus the
    uniform miss-heavy stream to bound the cache's overhead. Runs entirely
    host-side: the number is provable without a TPU window (VERDICT r5)."""
    from rmqtt_tpu.core.topic import parse_shared
    from rmqtt_tpu.router.base import Id, SubscriptionOptions
    from rmqtt_tpu.router.cache import MatchCache, cached_matches_raw

    n_filters, n_topics, pool_size = (
        (50_000, 40_000, 10_000) if reduced else (200_000, 100_000, 20_000))
    capacity = 8192
    try:
        from rmqtt_tpu import runtime

        native = runtime.available()
    except Exception:
        native = False
    if native:
        from rmqtt_tpu.router.native import NativeRouter as R

        kind = "native"
    else:
        from rmqtt_tpu.router.default import DefaultRouter as R

        kind = "python"
    router = R()
    # topic pool first: the $share work queues subscribe to CONCRETE pool
    # topics (the realistic shared-sub shape — wildcard-$share correctness
    # rides the property suite, broad-shared device perf rides cfg4)
    pool = sorted({_tree_topic(rng) for _ in range(pool_size)})
    n_shared = n_filters // 50  # 2% shared work-queue subscriptions
    filters = gen_mixed(rng, n_filters - n_shared)
    filters += [f"$share/g{rng.randrange(8)}/{rng.choice(pool)}"
                for _ in range(n_shared)]
    t0 = time.perf_counter()
    for i, f in enumerate(filters):
        grp, stripped = parse_shared(f)
        router.add(stripped, Id(1, f"c{i}"),
                   SubscriptionOptions(qos=1, shared_group=grp))
    log(f"[{name}] {kind} router: {n_filters} subs in {time.perf_counter() - t0:.2f}s")
    # daemon GC hygiene: the ~10^6-object subscription table must not be
    # re-scanned by every gen-2 collection the measurement loops trigger —
    # without the freeze, GC artifacts (not routing work) dominate the
    # cached-vs-uncached comparison
    import gc

    gc.collect()
    gc.freeze()
    # zipf-ranked hot-key stream over the pool (a=1.3: ~94% of the mass
    # inside the cache capacity) + a uniform miss-heavy stream
    nprng = np.random.default_rng(rng.randrange(2**31))
    ranks = (nprng.zipf(1.3, size=n_topics).astype(np.int64) - 1) % len(pool)
    zipf_topics = [pool[i] for i in ranks]
    uniform_topics = gen_topics_uniform(rng, n_topics)

    def run_once(topics, cached, budget_s):
        cache = MatchCache(router.epochs, capacity=capacity) if cached else None
        t1 = time.perf_counter()
        routes = done = 0
        for t in topics:
            if cache is not None:
                rel = router.collapse(cached_matches_raw(router, cache, None, t))
            else:
                rel = router.matches(None, t)
            routes += sum(len(v) for v in rel.values())
            done += 1
            if done % 4096 == 0 and time.perf_counter() - t1 > budget_s:
                break
        total = time.perf_counter() - t1
        rec = {"topics_per_sec": round(done / total, 1),
               "routes_per_sec": round(routes / total, 1), "topics": done}
        if cache is not None:
            rec["hit_rate"] = round(cache.hits / max(1, cache.hits + cache.misses), 4)
            rec["evictions"] = cache.evictions
        return rec

    def run(topics, cached, budget_s=8.0, reps=2):
        # best-of-N: the cached-vs-uncached ratio is the artifact — machine
        # noise between two 8-second windows must not masquerade as cache
        # overhead (or speedup)
        recs = [run_once(topics, cached, budget_s) for _ in range(reps)]
        return max(recs, key=lambda r: r["topics_per_sec"])

    run(uniform_topics[:2000], False, budget_s=5.0, reps=1)  # warm caches
    zipf_on = run(zipf_topics, True)
    zipf_off = run(zipf_topics, False)
    uni_on = run(uniform_topics, True)
    uni_off = run(uniform_topics, False)
    res = {
        "name": name,
        "router": kind,
        "subs": n_filters,
        "cache_capacity": capacity,
        "zipf": {
            "cached": zipf_on,
            "uncached": zipf_off,
            "speedup_cached": round(
                zipf_on["topics_per_sec"] / zipf_off["topics_per_sec"], 2),
        },
        "uniform_miss": {
            "cached": uni_on,
            "uncached": uni_off,
            # >1 means the cache costs throughput on all-miss traffic;
            # the acceptance bound is <= 1.05 (no >5% regression)
            "overhead_ratio": round(
                uni_off["topics_per_sec"] / max(1e-9, uni_on["topics_per_sec"]), 3),
        },
        **({"reduced_sizes": True} if reduced else {}),
    }
    log(f"[{name}] zipf: cached {zipf_on['topics_per_sec']:.0f} topics/s "
        f"(hit {zipf_on['hit_rate']:.1%}) vs uncached "
        f"{zipf_off['topics_per_sec']:.0f} → {res['zipf']['speedup_cached']:.2f}x | "
        f"uniform miss overhead {res['uniform_miss']['overhead_ratio']:.3f}x")
    return res


def run_telemetry_config(name, rng, reduced):
    """Config 7: latency-telemetry overhead (broker/telemetry.py) on the
    REAL publish path.

    Runs an in-process broker (real sockets, real sessions, the deployed
    RoutingService + match cache) with one QoS0 publisher → one subscriber
    over a rotating topic set, telemetry OFF vs ON in interleaved windows,
    and reports the throughput delta. This is the path every telemetry
    stage actually instruments — a stripped router-only loop triples the
    apparent relative cost because it deletes most of the per-publish work
    the substrate's ~1-2µs rides on. The enabled windows' p50/p99 for
    publish e2e and the match stage ride into the bench JSON so
    BENCH_*.json rounds carry a latency trajectory, not just throughput.

    Also reports the raw substrate cost per op (tight-loop microbench of
    one clock pair + one recorder call) for transparency."""
    import asyncio

    from rmqtt_tpu.broker.codec import MqttCodec, packets as pk
    from rmqtt_tpu.broker.context import BrokerConfig, ServerContext
    from rmqtt_tpu.broker.server import MqttBroker
    from rmqtt_tpu.broker.telemetry import Telemetry

    msgs = 6_000 if reduced else 15_000
    ntopics = 64  # rotating topics: exercises both cache-hit and miss paths
    payload = b"x" * 64

    async def _read_until(reader, codec, ptype):
        while True:
            data = await reader.read(4096)
            if not data:
                raise ConnectionError(f"peer closed before {ptype.__name__}")
            for p in codec.feed(data):
                if isinstance(p, ptype):
                    return p

    async def _connect(port, cid):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        codec = MqttCodec()
        writer.write(codec.encode(pk.Connect(client_id=cid, keepalive=600)))
        await writer.drain()
        await _read_until(reader, codec, pk.Connack)
        return reader, writer, codec

    async def _pipe(enable):
        """Broker + 1 subscriber + 1 publisher; → (burst fn, broker)."""
        b = MqttBroker(ServerContext(BrokerConfig(
            port=0, telemetry_enable=enable, allow_anonymous=True)))
        await b.start()
        sr, sw, scodec = await _connect(b.port, f"c7-sub-{enable}")
        sw.write(scodec.encode(pk.Subscribe(1, [("bench/#", pk.SubOpts(qos=0))])))
        await sw.drain()
        await _read_until(sr, scodec, pk.Suback)
        _pr, pw, pcodec = await _connect(b.port, f"c7-pub-{enable}")
        frames = [pcodec.encode(pk.Publish(
            topic=f"bench/t{i}", payload=payload, qos=0))
            for i in range(ntopics)]

        async def burst(n):
            """Blast n publishes, drain n deliveries; → elapsed seconds."""
            t0 = time.perf_counter()
            sent = 0
            got = 0
            deadline = time.monotonic() + 60.0
            while sent < n:
                k = min(64, n - sent)
                pw.write(b"".join(
                    frames[(sent + j) % ntopics] for j in range(k)))
                sent += k
                if pw.transport.get_write_buffer_size() > 1 << 18:
                    await pw.drain()
                while got < sent - 2048:
                    data = await asyncio.wait_for(
                        sr.read(1 << 16), deadline - time.monotonic())
                    if not data:
                        raise ConnectionError("subscriber closed")
                    got += sum(1 for p in scodec.feed(data)
                               if isinstance(p, pk.Publish))
            await pw.drain()
            while got < sent:
                data = await asyncio.wait_for(
                    sr.read(1 << 16), deadline - time.monotonic())
                if not data:
                    raise ConnectionError("subscriber closed")
                got += sum(1 for p in scodec.feed(data)
                           if isinstance(p, pk.Publish))
            return time.perf_counter() - t0

        return burst, b

    async def _measure():
        """BOTH brokers live at once; off/on bursts alternate back-to-back
        so host-load drift on this shared-core machine — far larger than
        the effect under test across whole-broker windows — hits both
        conditions equally and cancels in the ratio (the artifact)."""
        burst_off, b_off = await _pipe(False)
        burst_on, b_on = await _pipe(True)
        try:
            await burst_off(1024)  # warm: codec, cache, deliver path
            await burst_on(1024)
            # small bursts = fine-grained pairing: host-load drift on this
            # shared core moves ±10% between half-second windows, so the
            # pair must fit well inside one
            per = 256
            pairs = []
            done = 0
            while done < msgs:
                # order-symmetric QUAD (off,on,on,off): each condition runs
                # once in each position, and taking the min of its two
                # bursts filters one-sided load spikes before the ratio is
                # formed — the estimator that finally resolves a ~1-2%
                # effect under this host's ±10% half-second drift
                t_off1 = await burst_off(per)
                t_on1 = await burst_on(per)
                t_on2 = await burst_on(per)
                t_off2 = await burst_off(per)
                pairs.append((min(t_off1, t_off2), min(t_on1, t_on2)))
                done += 2 * per
            med_ratio = float(np.median([tn / tf for tf, tn in pairs]))
            best_off = min(tf for tf, _ in pairs)
            tps_off = per / best_off
            return tps_off, tps_off / med_ratio, b_on.ctx.telemetry
        finally:
            await b_off.stop()
            await b_on.stop()

    tps_off, tps_on, tele_on = asyncio.run(_measure())
    overhead = (tps_off - tps_on) / tps_off

    # substrate microbench: one clock pair + one fast-recorder call
    sub_tele = Telemetry(enabled=True)
    rec = sub_tele.recorder("publish.e2e")
    pcns = time.perf_counter_ns
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        ts = pcns()
        rec(pcns() - ts)
    per_record_ns = (time.perf_counter() - t0) / n * 1e9

    res = {
        "name": name,
        "path": "broker_e2e_qos0_pipe",
        "msgs_per_window": msgs,
        "msgs_per_sec_off": round(tps_off, 1),
        "msgs_per_sec_on": round(tps_on, 1),
        # may be slightly negative (noise floor); the bound is one-sided
        "overhead_pct": round(100.0 * overhead, 2),
        "target_overhead_pct": 3.0,
        "substrate_ns_per_record": round(per_record_ns, 1),
        "latency_ms": {
            "match_p50": tele_on.p_ms("routing.match", 0.50),
            "match_p99": tele_on.p_ms("routing.match", 0.99),
            "e2e_p50": tele_on.p_ms("publish.e2e", 0.50),
            "e2e_p99": tele_on.p_ms("publish.e2e", 0.99),
        },
        "samples": tele_on.hist("publish.e2e").count,
        **({"reduced_sizes": True} if reduced else {}),
    }
    log(f"[{name}] broker pipe: off {tps_off:.0f} vs on {tps_on:.0f} msg/s "
        f"→ overhead {res['overhead_pct']:.2f}% "
        f"(substrate {per_record_ns:.0f}ns/record) | e2e p50 "
        f"{res['latency_ms']['e2e_p50']}ms p99 {res['latency_ms']['e2e_p99']}ms")
    return res


def run_overload_config(name, rng, reduced):
    """Config 8: overload soak (broker/overload.py) — a QoS0 publisher
    outruns a paced subscriber 10:1 through a real broker, controller OFF
    vs ON.

    OFF: the slow consumer's deliver queue grows toward its (large) cap for
    the whole soak, and the surviving traffic's e2e latency is dominated by
    queue dwell — the throughput-cliff shape the edge-broker benchmark
    study attributes to unmanaged queue growth. ON: the watermark machine
    trips ELEVATED, QoS0 to the backlogged consumer is shed at the slow-
    consumer fraction, the queue stays pinned near the shed threshold, and
    delivered messages keep a bounded p99. Records goodput, shed counts by
    reason, peak queue depth and delivered-traffic p50/p99 for both runs."""
    import asyncio
    import struct

    from rmqtt_tpu.broker.codec import MqttCodec, packets as pk
    from rmqtt_tpu.broker.context import BrokerConfig, ServerContext
    from rmqtt_tpu.broker.fitter import FitterConfig
    from rmqtt_tpu.broker.server import MqttBroker

    pub_rate = 1000 if reduced else 2000  # publisher msgs/s
    sub_rate = pub_rate / 10.0  # subscriber paced 10:1 behind
    soak_s = 3.0 if reduced else 6.0
    mqueue = 10_000  # large cap: OFF-run growth is visible, not clipped early
    # ~1KB frames: the 10:1 deficit (several MB over the soak) must exceed
    # what kernel socket buffers can absorb, or the backlog never reaches
    # the broker's deliver queue and the controller has nothing to bound
    pad = b"p" * 1016

    async def _connect(port, cid, rcvbuf=None):
        import socket as _s

        sk = _s.socket()
        if rcvbuf:
            # shrink the client's receive window BEFORE connect: kernel
            # socket buffers otherwise absorb megabytes of backlog and the
            # latency under test (broker-side queue dwell) never shows
            sk.setsockopt(_s.SOL_SOCKET, _s.SO_RCVBUF, rcvbuf)
        sk.setblocking(False)
        await asyncio.get_running_loop().sock_connect(sk, ("127.0.0.1", port))
        reader, writer = await asyncio.open_connection(sock=sk)
        codec = MqttCodec(pk.V311)
        writer.write(codec.encode(pk.Connect(client_id=cid, keepalive=600)))
        await writer.drain()
        while True:
            data = await reader.read(4096)
            if not data:
                raise ConnectionError("no CONNACK")
            if codec.feed(data):
                return reader, writer, codec

    async def soak(enable):
        kw = dict(port=0, fitter=FitterConfig(max_mqueue=mqueue, max_inflight=64))
        if enable:
            kw.update(
                overload_enable=True, overload_sample_interval=0.05,
                # aggregate occupancy over ~2 sessions * 10k cap: ELEVATED
                # once the sub's backlog passes ~80 items. The watermark sits
                # BELOW the shed floor (100 items = 0.005 occupancy), so while
                # shedding holds the queue at the floor the state stays
                # pinned ELEVATED instead of flapping through its clear band
                overload_mqueue_elevated=0.004, overload_mqueue_critical=0.9,
                overload_shed_slow_fraction=0.01,  # slow = >100 queued
                overload_hold=2,
            )
        b = MqttBroker(ServerContext(BrokerConfig(**kw)))
        await b.start()
        sid = f"c8-sub-{enable}"
        sr, sw, sc = await _connect(b.port, sid, rcvbuf=32 * 1024)
        sw.write(sc.encode(pk.Subscribe(1, [("ov8/#", pk.SubOpts(qos=0))])))
        await sw.drain()
        # shrink the broker→subscriber send buffer too (same for both runs):
        # the backlog must land in the broker's deliver queue, the thing the
        # controller manages, not in invisible kernel buffering
        import socket as _s

        srv = b.ctx.registry.get(sid)
        srv_sock = srv.state.writer.get_extra_info("socket")
        if srv_sock is not None:
            srv_sock.setsockopt(_s.SOL_SOCKET, _s.SO_SNDBUF, 32 * 1024)
        pr, pw, pcodec = await _connect(b.port, f"c8-pub-{enable}")
        lat = []
        received = [0]
        peak_q = [0]
        stop = asyncio.Event()

        async def sub_loop():
            # paced consumer: sleep per processed publish → TCP backpressure
            # stalls the broker's deliver loop, the 10:1 deficit lands in
            # the broker-side deliver queue (the scenario under test)
            while not stop.is_set():
                try:
                    data = await asyncio.wait_for(sr.read(4096), 0.25)
                except asyncio.TimeoutError:
                    continue
                if not data:
                    return
                n = 0
                now = time.perf_counter()
                for p in sc.feed(data):
                    if isinstance(p, pk.Publish):
                        lat.append(now - struct.unpack("d", p.payload[:8])[0])
                        n += 1
                if n:
                    received[0] += n
                    await asyncio.sleep(n / sub_rate)

        async def sampler():
            while not stop.is_set():
                s = b.ctx.registry.get(sid)
                if s is not None:
                    peak_q[0] = max(peak_q[0], len(s.deliver_queue))
                await asyncio.sleep(0.05)

        tasks = [asyncio.get_running_loop().create_task(sub_loop()),
                 asyncio.get_running_loop().create_task(sampler())]
        sent = 0
        burst = 20
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < soak_s:
            for _ in range(burst):
                payload = struct.pack("d", time.perf_counter()) + pad
                pw.write(pcodec.encode(pk.Publish(topic="ov8/t", payload=payload)))
            sent += burst
            await pw.drain()
            await asyncio.sleep(burst / pub_rate)
        elapsed = time.perf_counter() - t0
        await asyncio.sleep(0.5)  # grace: in-flight deliveries land
        stop.set()
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        m = b.ctx.metrics.to_json()
        ctrl = b.ctx.overload
        res = {
            "sent": sent,
            "received": received[0],
            "goodput_msgs_per_sec": round(received[0] / elapsed, 1),
            "peak_sub_queue_depth": peak_q[0],
            "delivered_p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 1) if lat else None,
            "delivered_p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 1) if lat else None,
            "dropped_by_reason": {
                k[len("messages.dropped."):]: v for k, v in m.items()
                if k.startswith("messages.dropped.")
            },
            "dropped_total": m.get("messages.dropped", 0),
            "overload_state_final": ctrl.state.name,
            "overload_transitions": ctrl.transitions,
        }
        for w in (sw, pw):
            try:
                w.close()
            except Exception:
                pass
        await b.stop()
        return res

    off = asyncio.run(soak(False))
    on = asyncio.run(soak(True))
    res = {
        "name": name,
        "pub_rate": pub_rate,
        "sub_rate": sub_rate,
        "soak_s": soak_s,
        "max_mqueue": mqueue,
        "controller_off": off,
        "controller_on": on,
        # the two acceptance numbers: ON bounds the backlog (memory) and
        # the surviving traffic's tail where OFF lets both grow all soak
        "queue_depth_ratio_off_over_on": round(
            off["peak_sub_queue_depth"] / max(1, on["peak_sub_queue_depth"]), 2),
        "p99_ratio_off_over_on": round(
            (off["delivered_p99_ms"] or 0) / max(0.001, on["delivered_p99_ms"] or 0.001), 2),
        **({"reduced_sizes": True} if reduced else {}),
    }
    log(f"[{name}] OFF: peak queue {off['peak_sub_queue_depth']} "
        f"p99 {off['delivered_p99_ms']}ms goodput {off['goodput_msgs_per_sec']}/s | "
        f"ON: peak queue {on['peak_sub_queue_depth']} "
        f"p99 {on['delivered_p99_ms']}ms goodput {on['goodput_msgs_per_sec']}/s "
        f"shed {on['dropped_by_reason'].get('shed_qos0', 0)} "
        f"→ queue ratio {res['queue_depth_ratio_off_over_on']}x, "
        f"p99 ratio {res['p99_ratio_off_over_on']}x")
    return res


def run_churn_config(name, rng, reduced):
    """Config 9: churn soak — sustained subscribe/unsubscribe concurrent
    with the cfg3 publish mix through the partitioned matcher.

    Three legs:
      free   — no churn: the baseline match p50/p99;
      churn  — K mutations between every batch, DELTA refresh (the
               tentpole): per-mutation upload bytes must be O(dirty
               chunks), and p99 must hold within ~2x of the free leg;
      full   — same churn with delta uploads disabled: every mutation
               costs a full table repack + re-upload (the pre-delta
               cliff this PR removes), measured for the comparison.
    Emits upload_bytes_per_mutation + the delta-vs-full reduction factor
    into the bench JSON (acceptance: ≥10x at the bench table size)."""
    from rmqtt_tpu.ops.partitioned import PartitionedMatcher, pack_device_rows

    n, nt, bs = (50_000, 4_096, 512) if reduced else (100_000, 6_144, 1024)
    muts_per_batch = 16
    filters = gen_mixed(rng, n)
    topics = gen_topics_uniform(rng, nt)
    log(f"[{name}] {n} subs, churn {muts_per_batch} ops/batch, batch {bs}")
    table, fids = build_tpu_table(filters, "partitioned")
    matcher = make_matcher(table)
    # a reserve of fresh filters so churn adds are as varied as the table
    fset = set(filters)
    reserve = [f for f in gen_mixed(rng, n // 10) if f not in fset]
    # live fid pool for O(1) random removal (swap-pop) — a list(fids) per
    # mutation would put O(table) host work inside the measured loop
    fid_pool = list(fids)
    batches = [topics[i : i + bs] for i in range(0, len(topics), bs)]
    batches = [b for b in batches if len(b) == bs]

    def _measure(leg_batches, mutate):
        lat = []
        mutations = 0
        bytes0 = matcher.upload_bytes
        t0 = time.perf_counter()
        for b in leg_batches:
            mutations += mutate()
            t1 = time.perf_counter()
            matcher.match(b)
            lat.append(time.perf_counter() - t1)
        wall = time.perf_counter() - t0
        lat.sort()
        return {
            "p50_ms": round(lat[len(lat) // 2] * 1e3, 2),
            "p99_ms": round(lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e3, 2),
            "topics_per_sec": round(len(leg_batches) * bs / wall, 1),
            "mutations": mutations,
            "mutation_rate_per_sec": round(mutations / wall, 1),
            "upload_bytes": matcher.upload_bytes - bytes0,
        }

    def _paired_measure(leg_batches, mutate):
        """Interleaved (churned, churn-free) matches in ONE window, order
        alternating per pair — cfg7's order-symmetric estimator: a host-
        noise stall lands on both series equally, so the churn-vs-free
        ratio reflects churn cost, not scheduler luck. The churned match
        runs right after `mutate()` (pending delta refresh); its partner
        sees a clean table."""
        lf: list = []
        lc: list = []
        ratios = []
        mutations = 0
        bytes0 = matcher.upload_bytes
        t0 = time.perf_counter()
        for i, b in enumerate(leg_batches):
            def one(lat_list, mut):
                nonlocal mutations
                if mut:
                    mutations += mutate()
                t1 = time.perf_counter()
                matcher.match(b)
                lat_list.append(time.perf_counter() - t1)
            if i % 2:
                one(lf, False)
                one(lc, True)
            else:
                one(lc, True)
                one(lf, False)
            ratios.append(lc[-1] / max(1e-9, lf[-1]))
        wall = time.perf_counter() - t0
        lf.sort()
        lc.sort()
        ratios.sort()

        def p(lat, q):
            return round(lat[min(len(lat) - 1, int(len(lat) * q))] * 1e3, 2)

        return {
            "free_p50_ms": p(lf, 0.5), "free_p99_ms": p(lf, 0.99),
            "p50_ms": p(lc, 0.5), "p99_ms": p(lc, 0.99),
            "median_pair_ratio": round(ratios[len(ratios) // 2], 2),
            "topics_per_sec": round(2 * len(leg_batches) * bs / wall, 1),
            "mutations": mutations,
            "mutation_rate_per_sec": round(mutations / wall, 1),
            "upload_bytes": matcher.upload_bytes - bytes0,
        }

    def no_churn():
        return 0

    def churn():
        k = 0
        for _ in range(muts_per_batch // 2):
            if reserve:
                f = reserve.pop()
                fid_pool.append(table.add(f))
                fids[fid_pool[-1]] = f
                k += 1
            i = rng.randrange(len(fid_pool))
            fid_pool[i], fid_pool[-1] = fid_pool[-1], fid_pool[i]
            fid = fid_pool.pop()
            table.remove(fid)
            reserve.append(fids.pop(fid))
            k += 1
        return k

    # warmup (compile) then the three legs on the same table
    for b in batches[:2]:
        matcher.match(b)
    loop_batches = batches[2:]
    while len(loop_batches) < 32:  # p99 over a handful of batches is noise
        loop_batches = loop_batches + batches[2:]
    free = _measure(loop_batches, no_churn)
    # a few churned warm batches absorb the NC-regrowth recompiles (the
    # sticky candidate-count cap crosses pow2 tiers as churn adds chunks)
    # so the churn leg's p99 measures churn, not one-off jit flips
    for wb in loop_batches[:4]:
        churn()
        matcher.match(wb)
    d0, f0, c0 = matcher.delta_uploads, matcher.full_uploads, table.compactions
    churn_res = _paired_measure(loop_batches, churn)
    churn_res["delta_uploads"] = matcher.delta_uploads - d0
    churn_res["full_uploads"] = matcher.full_uploads - f0
    churn_res["compactions"] = table.compactions - c0
    full_table_bytes = pack_device_rows(table).nbytes
    per_mut = churn_res["upload_bytes"] / max(1, churn_res["mutations"])
    churn_res["upload_bytes_per_mutation"] = round(per_mut, 1)
    # the pre-delta cliff: disable delta uploads, every mutation → full
    # repack + upload (fewer batches — it is exactly as slow as it sounds)
    matcher.delta_enabled = False
    churn()
    matcher.match(loop_batches[0])
    cliff = _measure(loop_batches[: max(4, len(loop_batches) // 4)], churn)
    cliff["upload_bytes_per_mutation"] = round(
        cliff["upload_bytes"] / max(1, cliff["mutations"]), 1)
    matcher.delta_enabled = True
    res = {
        "name": name,
        "table_size": len(fids),
        "full_table_bytes": full_table_bytes,
        "free": free,
        "churn_delta": churn_res,
        "churn_full_refresh": cliff,
        "upload_bytes_per_mutation": churn_res["upload_bytes_per_mutation"],
        "delta_reduction_x": round(
            cliff["upload_bytes_per_mutation"]
            / max(1.0, churn_res["upload_bytes_per_mutation"]), 1),
        # within-window comparison (the paired leg's own free series), so
        # host-load drift between legs can't fake or mask a cliff
        "p99_churn_over_free": round(
            churn_res["p99_ms"] / max(0.001, churn_res["free_p99_ms"]), 2),
        "median_pair_ratio": churn_res["median_pair_ratio"],
        "p99_full_over_free": round(
            cliff["p99_ms"] / max(0.001, free["p99_ms"]), 2),
        **({"reduced_sizes": True} if reduced else {}),
    }
    log(f"[{name}] free p99 {free['p99_ms']}ms | churn(delta) p99 "
        f"{churn_res['p99_ms']}ms ({res['p99_churn_over_free']}x in-window, "
        f"median pair ratio {churn_res['median_pair_ratio']}x) "
        f"{churn_res['upload_bytes_per_mutation']}B/mutation | "
        f"churn(full) p99 {cliff['p99_ms']}ms ({res['p99_full_over_free']}x) "
        f"{cliff['upload_bytes_per_mutation']}B/mutation → "
        f"{res['delta_reduction_x']}x less upload traffic")
    return res


def run_smallbatch_config(name, rng, reduced):
    """Config 11: the cfg1 small-batch regime, attributable PER STAGE.

    cfg1's standing 0.06x on chip is a single ratio — it cannot say whether
    the loss sits in host encode, device dispatch, result fetch or host
    decode. This config drives MICRO-batches (16 topics, the cfg1 shape)
    through two matchers over ONE table — the fused match→compact→decode
    pipeline vs the unfused words+host-decode path — as cfg7-style
    order-symmetric pairs (order alternates per pair, so a host-noise
    stall lands on both legs equally), with ``stage_timing`` accumulating
    encode/dispatch/fetch/decode wall ns inside each matcher. Emits
    per-leg p50/p99, per-stage shares, and the fused/unfused median pair
    ratio: the DECODE share collapsing on the fused leg is the acceptance
    evidence that host decode left the per-batch path."""
    import os

    from rmqtt_tpu.ops.partitioned import PartitionedMatcher

    n, pairs, bs = (600, 48, 16) if reduced else (1000, 96, 16)
    filters = gen_exact(rng, n)
    # cfg1 shape: ~50% of publishes hit a subscribed topic
    topics = [rng.choice(filters) if rng.random() < 0.5
              else _tree_topic(rng, 4) for _ in range(pairs * bs)]
    log(f"[{name}] {n} subs, {pairs} pairs of micro-batches of {bs}")
    table, fids = build_tpu_table(filters, "partitioned")
    m_fused = PartitionedMatcher(table)
    prior = os.environ.get("RMQTT_FUSED")
    os.environ["RMQTT_FUSED"] = "0"
    try:
        m_plain = PartitionedMatcher(table)
    finally:
        if prior is None:
            os.environ.pop("RMQTT_FUSED", None)
        else:
            os.environ["RMQTT_FUSED"] = prior
    batches = [topics[i: i + bs] for i in range(0, len(topics), bs)]
    batches = [b for b in batches if len(b) == bs]
    for m in (m_fused, m_plain):  # warmup/compile + fused verify
        m.match(batches[0])
        m.match(batches[1])
        m.prewarm((bs,))
        m.stage_timing = True

    lat = {"fused": [], "unfused": []}
    ratios = []
    t0 = time.perf_counter()
    for i, b in enumerate(batches):
        def one(m, key):
            t1 = time.perf_counter()
            m.match(b)
            lat[key].append(time.perf_counter() - t1)
        if i % 2:
            one(m_plain, "unfused")
            one(m_fused, "fused")
        else:
            one(m_fused, "fused")
            one(m_plain, "unfused")
        ratios.append(lat["fused"][-1] / max(1e-9, lat["unfused"][-1]))
    wall = time.perf_counter() - t0
    ratios.sort()

    def leg(key, m):
        ls = sorted(lat[key])
        total = max(1, sum(m.stage_ns.values()))
        return {
            "p50_ms": round(ls[len(ls) // 2] * 1e3, 3),
            "p99_ms": round(ls[min(len(ls) - 1, int(len(ls) * 0.99))] * 1e3, 3),
            "stage_ms": {k: round(v / 1e6, 2) for k, v in m.stage_ns.items()},
            "stage_share": {k: round(v / total, 4)
                            for k, v in m.stage_ns.items()},
        }

    res = {
        "name": name,
        "table_size": len(fids),
        "micro_batch": bs,
        "pairs": len(batches),
        "topics_per_sec": round(2 * len(batches) * bs / wall, 1),
        "fused_verified": m_fused._fused is True,
        "fused": leg("fused", m_fused),
        "unfused": leg("unfused", m_plain),
        "median_pair_ratio": round(ratios[len(ratios) // 2], 3),
        "decode_share_unfused": leg("unfused", m_plain)["stage_share"]["decode"],
        "decode_share_fused": leg("fused", m_fused)["stage_share"]["decode"],
        **({"reduced_sizes": True} if reduced else {}),
    }
    log(f"[{name}] fused p50 {res['fused']['p50_ms']}ms vs unfused "
        f"{res['unfused']['p50_ms']}ms (median pair ratio "
        f"{res['median_pair_ratio']}x) | decode share "
        f"{res['decode_share_unfused']:.1%} → {res['decode_share_fused']:.1%}")
    return res


def run_devprof_overhead_config(name, rng, reduced):
    """Config 12: device-profiler overhead, cfg7-style order-symmetric
    paired estimator.

    Same matcher, same batches; leg A runs with ``device_profile`` ON
    (the global DEVPROF registry + flight ring + the matcher's
    stage_timing — exactly what the [observability] knob enables), leg B
    with both off. Order alternates per pair so a host-noise stall lands
    on both legs equally; the median pair ratio bounds the enabled cost.
    The profiler adds only host work (no new jit signatures), so one
    warmup covers both legs. Acceptance: overhead ≤ 2% — a standalone
    ``--config 12`` run exits nonzero past the bound so CI can gate on it."""
    from rmqtt_tpu.broker.devprof import DEVPROF
    from rmqtt_tpu.broker.telemetry import Telemetry

    n, pairs, bs = (5_000, 64, 128) if reduced else (50_000, 192, 512)
    filters = gen_mixed(rng, n)
    # batches draw from a BOUNDED topic pool and every batch is warmed
    # once below: the first match of a fresh batch pays candidate-cache
    # misses (~20x the steady encode), which would otherwise land on
    # whichever leg runs first and swamp the profiler cost being measured
    pool = gen_topics_uniform(rng, 4096)
    log(f"[{name}] {n} subs, {pairs} pairs of batches of {bs}")
    table, fids = build_tpu_table(filters, "partitioned")
    matcher = make_matcher(table)
    batches = [[pool[rng.randrange(len(pool))] for _ in range(bs)]
               for _ in range(pairs)]
    prior_enabled = DEVPROF.enabled
    prior_tele = DEVPROF.telemetry
    # a throwaway telemetry registry so storm/floor annotations (if any)
    # pay their real cost without touching the process-global slow ring
    DEVPROF.configure(enabled=True, telemetry=Telemetry(enabled=True))
    try:
        for b in batches:  # compile + warm every batch's candidate sets
            matcher.match(b)
        lat = {"on": [], "off": []}
        ratios = []
        t0 = time.perf_counter()
        for i, b in enumerate(batches):
            def one(key, enabled):
                DEVPROF.enabled = enabled
                matcher.stage_timing = enabled
                t1 = time.perf_counter()
                matcher.match(b)
                lat[key].append(time.perf_counter() - t1)
            if i % 2:
                one("off", False)
                one("on", True)
            else:
                one("on", True)
                one("off", False)
            ratios.append(lat["on"][-1] / max(1e-9, lat["off"][-1]))
        wall = time.perf_counter() - t0
    finally:
        DEVPROF.configure(enabled=prior_enabled, telemetry=prior_tele)
        matcher.stage_timing = False
    ratios.sort()

    def p(key, q):
        ls = sorted(lat[key])
        return round(ls[min(len(ls) - 1, int(len(ls) * q))] * 1e3, 3)

    median_ratio = ratios[len(ratios) // 2]
    overhead_pct = round((median_ratio - 1.0) * 100.0, 2)
    res = {
        "name": name,
        "table_size": len(fids),
        "batch": bs,
        "pairs": len(batches),
        "topics_per_sec": round(2 * len(batches) * bs / wall, 1),
        "on_p50_ms": p("on", 0.5), "on_p99_ms": p("on", 0.99),
        "off_p50_ms": p("off", 0.5), "off_p99_ms": p("off", 0.99),
        "median_pair_ratio": round(median_ratio, 4),
        "overhead_pct": overhead_pct,
        "bound_pct": 2.0,
        "ok": overhead_pct <= 2.0,
        **({"reduced_sizes": True} if reduced else {}),
    }
    log(f"[{name}] profiler ON p50 {res['on_p50_ms']}ms vs OFF "
        f"{res['off_p50_ms']}ms (median pair ratio {res['median_pair_ratio']}x"
        f" = {overhead_pct}% overhead, bound 2%) → "
        f"{'OK' if res['ok'] else 'FAIL'}")
    return res


def run_hostprof_overhead_config(name, rng, reduced):
    """Config 14: host-plane profiler overhead (broker/hostprof.py) on the
    REAL publish path, cfg7-style order-symmetric paired estimator.

    One live broker pipe (real sockets, the deployed RoutingService); the
    profiler is ARMED (sampler task + gc callbacks + watchdog thread —
    exactly what ``[observability] host_profile`` enables) for the ON
    bursts and fully DISARMED for the OFF bursts. HOSTPROF is
    process-global and the loop is shared, so unlike cfg7 the conditions
    cannot run as two live brokers — per-burst arm/disarm on one pipe is
    the honest design (the profiler's cost IS its background wakeups +
    per-collection gc callback, and those run during the armed bursts).
    Quads (off,on,on,off) with min-of-two per condition filter one-sided
    host-load spikes; the median pair ratio bounds the enabled cost at
    ≤2% of e2e p50 burst time (standalone ``--config 14`` exits 1 past
    the bound so CI can gate on it)."""
    import asyncio

    from rmqtt_tpu.broker.codec import MqttCodec, packets as pk
    from rmqtt_tpu.broker.context import BrokerConfig, ServerContext
    from rmqtt_tpu.broker.hostprof import HOSTPROF
    from rmqtt_tpu.broker.server import MqttBroker

    msgs = 6_000 if reduced else 15_000
    ntopics = 64
    payload = b"x" * 64

    async def _read_until(reader, codec, ptype):
        while True:
            data = await reader.read(4096)
            if not data:
                raise ConnectionError(f"peer closed before {ptype.__name__}")
            for p in codec.feed(data):
                if isinstance(p, ptype):
                    return p

    async def _connect(port, cid):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        codec = MqttCodec()
        writer.write(codec.encode(pk.Connect(client_id=cid, keepalive=600)))
        await writer.drain()
        await _read_until(reader, codec, pk.Connack)
        return reader, writer, codec

    async def _measure():
        # host_profile=False at construction: the bench owns arm/disarm
        b = MqttBroker(ServerContext(BrokerConfig(
            port=0, host_profile=False, allow_anonymous=True)))
        await b.start()
        sr, sw, scodec = await _connect(b.port, "c14-sub")
        sw.write(scodec.encode(pk.Subscribe(1, [("bench/#", pk.SubOpts(qos=0))])))
        await sw.drain()
        await _read_until(sr, scodec, pk.Suback)
        _pr, pw, pcodec = await _connect(b.port, "c14-pub")
        frames = [pcodec.encode(pk.Publish(
            topic=f"bench/t{i}", payload=payload, qos=0))
            for i in range(ntopics)]

        async def burst(n):
            t0 = time.perf_counter()
            sent = got = 0
            deadline = time.monotonic() + 60.0
            while sent < n:
                k = min(64, n - sent)
                pw.write(b"".join(
                    frames[(sent + j) % ntopics] for j in range(k)))
                sent += k
                if pw.transport.get_write_buffer_size() > 1 << 18:
                    await pw.drain()
                while got < sent - 2048:
                    data = await asyncio.wait_for(
                        sr.read(1 << 16), deadline - time.monotonic())
                    if not data:
                        raise ConnectionError("subscriber closed")
                    got += sum(1 for p in scodec.feed(data)
                               if isinstance(p, pk.Publish))
            await pw.drain()
            while got < sent:
                data = await asyncio.wait_for(
                    sr.read(1 << 16), deadline - time.monotonic())
                if not data:
                    raise ConnectionError("subscriber closed")
                got += sum(1 for p in scodec.feed(data)
                           if isinstance(p, pk.Publish))
            return time.perf_counter() - t0

        def arm():
            HOSTPROF.configure(enabled=True, dump_dir=None,
                               telemetry=b.ctx.telemetry)
            HOSTPROF.start()

        async def disarm():
            await HOSTPROF.stop()
            HOSTPROF.configure(enabled=False)

        prior_enabled = HOSTPROF.enabled
        try:
            await burst(1024)  # warm: codec, cache, deliver path
            arm()
            await burst(1024)
            await disarm()
            per = 256
            pairs = []
            done = 0
            while done < msgs:
                t_off1 = await burst(per)
                arm()
                t_on1 = await burst(per)
                t_on2 = await burst(per)
                await disarm()
                t_off2 = await burst(per)
                pairs.append((min(t_off1, t_off2), min(t_on1, t_on2)))
                done += 2 * per
            med_ratio = float(np.median([tn / tf for tf, tn in pairs]))
            best_off = min(tf for tf, _ in pairs)
            tele = b.ctx.telemetry
            lat = {"e2e_p50": tele.p_ms("publish.e2e", 0.50),
                   "e2e_p99": tele.p_ms("publish.e2e", 0.99)}
            return per / best_off, med_ratio, lat
        finally:
            await HOSTPROF.stop()
            HOSTPROF.configure(enabled=prior_enabled)
            await b.stop()

    tps_off, med_ratio, lat = asyncio.run(_measure())
    overhead_pct = round((med_ratio - 1.0) * 100.0, 2)
    res = {
        "name": name,
        "path": "broker_e2e_qos0_pipe",
        "msgs_per_window": msgs,
        "msgs_per_sec_off": round(tps_off, 1),
        "msgs_per_sec_on": round(tps_off / med_ratio, 1),
        "median_pair_ratio": round(med_ratio, 4),
        "overhead_pct": overhead_pct,
        "bound_pct": 2.0,
        "ok": overhead_pct <= 2.0,
        "latency_ms": lat,
        **({"reduced_sizes": True} if reduced else {}),
    }
    log(f"[{name}] host profiler OFF {tps_off:.0f} msg/s, median pair "
        f"ratio {res['median_pair_ratio']}x = {overhead_pct}% overhead "
        f"(bound 2%) | e2e p50 {lat['e2e_p50']}ms → "
        f"{'OK' if res['ok'] else 'FAIL'}")
    return res


def run_history_overhead_config(name, rng, reduced):
    """Config 17: telemetry-history collector overhead (broker/history.py)
    on the REAL publish path, cfg14-style order-symmetric paired estimator.

    One live broker pipe; the history collector is ARMED (periodic
    cross-plane ``collect_once`` samples + EWMA/MAD anomaly pass —
    exactly what ``[observability] history`` enables, memory-only like
    the default ``history_dir=\"\"`` deployment) for the ON bursts and
    fully stopped for the OFF bursts. The collector runs at a 250 ms
    cadence here — 20× the 5 s production default — and ``_run``
    samples at tick START, so every armed window contains at least one
    real collection and the measured bound is a deliberate upper
    estimate of the deployed cost. Quads (off,on,on,off) with
    min-of-two per condition filter one-sided host spikes; the median
    pair ratio bounds the enabled cost at ≤2% of e2e burst time
    (standalone ``--config 17`` exits 1 past the bound so CI can gate
    on it)."""
    import asyncio

    from rmqtt_tpu.broker.codec import MqttCodec, packets as pk
    from rmqtt_tpu.broker.context import BrokerConfig, ServerContext
    from rmqtt_tpu.broker.server import MqttBroker

    msgs = 6_000 if reduced else 15_000
    ntopics = 64
    payload = b"x" * 64

    async def _read_until(reader, codec, ptype):
        while True:
            data = await reader.read(4096)
            if not data:
                raise ConnectionError(f"peer closed before {ptype.__name__}")
            for p in codec.feed(data):
                if isinstance(p, ptype):
                    return p

    async def _connect(port, cid):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        codec = MqttCodec()
        writer.write(codec.encode(pk.Connect(client_id=cid, keepalive=600)))
        await writer.drain()
        await _read_until(reader, codec, pk.Connack)
        return reader, writer, codec

    async def _measure():
        # history=False at construction: the bench owns arm/disarm
        b = MqttBroker(ServerContext(BrokerConfig(
            port=0, history_enable=False, allow_anonymous=True)))
        await b.start()
        hist = b.ctx.history
        sr, sw, scodec = await _connect(b.port, "c17-sub")
        sw.write(scodec.encode(pk.Subscribe(1, [("bench/#", pk.SubOpts(qos=0))])))
        await sw.drain()
        await _read_until(sr, scodec, pk.Suback)
        _pr, pw, pcodec = await _connect(b.port, "c17-pub")
        frames = [pcodec.encode(pk.Publish(
            topic=f"bench/t{i}", payload=payload, qos=0))
            for i in range(ntopics)]

        async def burst(n):
            t0 = time.perf_counter()
            sent = got = 0
            deadline = time.monotonic() + 60.0
            while sent < n:
                k = min(64, n - sent)
                pw.write(b"".join(
                    frames[(sent + j) % ntopics] for j in range(k)))
                sent += k
                if pw.transport.get_write_buffer_size() > 1 << 18:
                    await pw.drain()
                while got < sent - 2048:
                    data = await asyncio.wait_for(
                        sr.read(1 << 16), deadline - time.monotonic())
                    if not data:
                        raise ConnectionError("subscriber closed")
                    got += sum(1 for p in scodec.feed(data)
                               if isinstance(p, pk.Publish))
            await pw.drain()
            while got < sent:
                data = await asyncio.wait_for(
                    sr.read(1 << 16), deadline - time.monotonic())
                if not data:
                    raise ConnectionError("subscriber closed")
                got += sum(1 for p in scodec.feed(data)
                           if isinstance(p, pk.Publish))
            return time.perf_counter() - t0

        def arm():
            hist.enabled = True
            hist.interval_s = 0.25  # 20× production cadence: upper bound
            hist.start()

        async def disarm():
            await hist.stop()
            hist.enabled = False

        try:
            await burst(1024)  # warm: codec, cache, deliver path
            arm()
            await burst(1024)
            await disarm()
            # 512-msg windows: long enough that one collection amortizes
            # to its steady-state share, short enough for ~15 pairs
            per = 512
            pairs = []
            done = 0
            while done < msgs:
                t_off1 = await burst(per)
                arm()
                t_on1 = await burst(per)
                t_on2 = await burst(per)
                await disarm()
                t_off2 = await burst(per)
                pairs.append((min(t_off1, t_off2), min(t_on1, t_on2)))
                done += 2 * per
            med_ratio = float(np.median([tn / tf for tf, tn in pairs]))
            best_off = min(tf for tf, _ in pairs)
            tele = b.ctx.telemetry
            lat = {"e2e_p50": tele.p_ms("publish.e2e", 0.50),
                   "e2e_p99": tele.p_ms("publish.e2e", 0.99)}
            return per / best_off, med_ratio, lat, len(hist.ring)
        finally:
            await hist.stop()
            hist.enabled = False
            await b.stop()

    tps_off, med_ratio, lat, samples = asyncio.run(_measure())
    overhead_pct = round((med_ratio - 1.0) * 100.0, 2)
    res = {
        "name": name,
        "path": "broker_e2e_qos0_pipe",
        "msgs_per_window": msgs,
        "msgs_per_sec_off": round(tps_off, 1),
        "msgs_per_sec_on": round(tps_off / med_ratio, 1),
        "median_pair_ratio": round(med_ratio, 4),
        "overhead_pct": overhead_pct,
        "bound_pct": 2.0,
        "ok": overhead_pct <= 2.0,
        # samples actually taken during the armed windows: the ON legs
        # measured a collector that really fired, not an idle task
        "samples_recorded": samples,
        "collector_interval_s": 0.25,
        "latency_ms": lat,
        **({"reduced_sizes": True} if reduced else {}),
    }
    log(f"[{name}] history collector OFF {tps_off:.0f} msg/s, median pair "
        f"ratio {res['median_pair_ratio']}x = {overhead_pct}% overhead "
        f"(bound 2%, {samples} samples) | e2e p50 {lat['e2e_p50']}ms → "
        f"{'OK' if res['ok'] else 'FAIL'}")
    return res


def run_hotkeys_overhead_config(name, rng, reduced):
    """Config 18: hot-key attribution sketch overhead (broker/hotkeys.py)
    on the REAL publish path, cfg17-style order-symmetric paired estimator.

    One live broker pipe; the hot-key plane is ARMED (per-publish
    Space-Saving + Count-Min offers across all six key spaces, the
    per-dispatch prefix seam, the per-deliver subscriber seam, plus the
    live rotation/alert task — exactly what ``[observability] hotkeys``
    enables) for the ON bursts and fully disarmed (``enabled=False`` +
    routing seam nulled, the shipped-off configuration) for the OFF
    bursts. The rotation window runs at 0.5 s here — 60× the 30 s
    production default — so every armed leg contains real rotations and
    the measured bound is a deliberate upper estimate of the deployed
    cost. Quads (off,on,on,off) with min-of-two per condition filter
    one-sided host spikes; the median pair ratio bounds the enabled cost
    at ≤2% of e2e burst time (standalone ``--config 18`` exits 1 past
    the bound so CI can gate on it)."""
    import asyncio

    from rmqtt_tpu.broker.codec import MqttCodec, packets as pk
    from rmqtt_tpu.broker.context import BrokerConfig, ServerContext
    from rmqtt_tpu.broker.server import MqttBroker

    msgs = 6_000 if reduced else 15_000
    ntopics = 64
    payload = b"x" * 64

    async def _read_until(reader, codec, ptype):
        while True:
            data = await reader.read(4096)
            if not data:
                raise ConnectionError(f"peer closed before {ptype.__name__}")
            for p in codec.feed(data):
                if isinstance(p, ptype):
                    return p

    async def _connect(port, cid):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        codec = MqttCodec()
        writer.write(codec.encode(pk.Connect(client_id=cid, keepalive=600)))
        await writer.drain()
        await _read_until(reader, codec, pk.Connack)
        return reader, writer, codec

    async def _measure():
        # hotkeys=False at construction: the bench owns arm/disarm
        b = MqttBroker(ServerContext(BrokerConfig(
            port=0, hotkeys_enable=False, history_enable=False,
            allow_anonymous=True)))
        await b.start()
        hk = b.ctx.hotkeys
        samples = 0
        sr, sw, scodec = await _connect(b.port, "c18-sub")
        sw.write(scodec.encode(pk.Subscribe(1, [("bench/#", pk.SubOpts(qos=0))])))
        await sw.drain()
        await _read_until(sr, scodec, pk.Suback)
        _pr, pw, pcodec = await _connect(b.port, "c18-pub")
        frames = [pcodec.encode(pk.Publish(
            topic=f"bench/t{i}", payload=payload, qos=0))
            for i in range(ntopics)]

        async def burst(n):
            t0 = time.perf_counter()
            sent = got = 0
            deadline = time.monotonic() + 60.0
            while sent < n:
                k = min(64, n - sent)
                pw.write(b"".join(
                    frames[(sent + j) % ntopics] for j in range(k)))
                sent += k
                if pw.transport.get_write_buffer_size() > 1 << 18:
                    await pw.drain()
                while got < sent - 2048:
                    data = await asyncio.wait_for(
                        sr.read(1 << 16), deadline - time.monotonic())
                    if not data:
                        raise ConnectionError("subscriber closed")
                    got += sum(1 for p in scodec.feed(data)
                               if isinstance(p, pk.Publish))
            await pw.drain()
            while got < sent:
                data = await asyncio.wait_for(
                    sr.read(1 << 16), deadline - time.monotonic())
                if not data:
                    raise ConnectionError("subscriber closed")
                got += sum(1 for p in scodec.feed(data)
                           if isinstance(p, pk.Publish))
            return time.perf_counter() - t0

        def arm():
            hk.enabled = True
            hk.window_s = 0.5  # 60× production cadence: rotation included
            b.ctx.routing.hotkeys = hk
            hk.start()

        async def disarm():
            nonlocal samples
            # events the armed legs actually attributed (topics space,
            # cur+prev windows): the ON legs measured sketches that
            # really recorded, not a dormant flag check
            hk.drain()
            samples += int(hk.spaces["topics"].total())
            await hk.stop()
            hk.enabled = False
            b.ctx.routing.hotkeys = None

        try:
            await burst(1024)  # warm: codec, cache, deliver path
            arm()
            await burst(1024)
            await disarm()
            # 512-msg windows, same shape as cfg17: long enough that a
            # rotation amortizes, short enough for ~15 pairs
            per = 512
            pairs = []
            done = 0
            while done < msgs:
                t_off1 = await burst(per)
                arm()
                t_on1 = await burst(per)
                t_on2 = await burst(per)
                await disarm()
                t_off2 = await burst(per)
                pairs.append((min(t_off1, t_off2), min(t_on1, t_on2)))
                done += 2 * per
            med_ratio = float(np.median([tn / tf for tf, tn in pairs]))
            best_off = min(tf for tf, _ in pairs)
            tele = b.ctx.telemetry
            lat = {"e2e_p50": tele.p_ms("publish.e2e", 0.50),
                   "e2e_p99": tele.p_ms("publish.e2e", 0.99)}
            return (per / best_off, med_ratio, lat, samples,
                    int(hk.rotations))
        finally:
            await hk.stop()
            hk.enabled = False
            b.ctx.routing.hotkeys = None
            await b.stop()

    tps_off, med_ratio, lat, samples, rotations = asyncio.run(_measure())
    overhead_pct = round((med_ratio - 1.0) * 100.0, 2)
    res = {
        "name": name,
        "path": "broker_e2e_qos0_pipe",
        "msgs_per_window": msgs,
        "msgs_per_sec_off": round(tps_off, 1),
        "msgs_per_sec_on": round(tps_off / med_ratio, 1),
        "median_pair_ratio": round(med_ratio, 4),
        "overhead_pct": overhead_pct,
        "bound_pct": 2.0,
        "ok": overhead_pct <= 2.0,
        # sketch offers actually recorded during the armed windows: the
        # ON legs measured a plane that really attributed traffic
        "samples_recorded": samples,
        "rotations": rotations,
        "window_s": 0.5,
        "latency_ms": lat,
        **({"reduced_sizes": True} if reduced else {}),
    }
    log(f"[{name}] hotkeys plane OFF {tps_off:.0f} msg/s, median pair "
        f"ratio {res['median_pair_ratio']}x = {overhead_pct}% overhead "
        f"(bound 2%, {samples} events, {rotations} rotations) | e2e p50 "
        f"{lat['e2e_p50']}ms → {'OK' if res['ok'] else 'FAIL'}")
    return res


def run_failover_config(name, rng, reduced):
    """Config 10: device-plane failover soak (broker/failover.py).

    Steady QoS1 publish load through a broker whose routing is pinned to
    the DEVICE plane; at t=2s the ``device.dispatch`` failpoint kills the
    kernel path (every batch errors), at t=4s it recovers. The failover
    plane must serve the outage from the host trie with zero lost
    publishes, then probe, force a full HBM re-upload and switch back.
    Emits the goodput dip, per-phase delivered p99 (steady vs failover vs
    post-recovery) and time-to-switchback — the regression gate for
    recovery time in future PRs."""
    import asyncio
    import struct

    from rmqtt_tpu.broker.codec import MqttCodec, packets as pk
    from rmqtt_tpu.broker.context import BrokerConfig, ServerContext
    from rmqtt_tpu.broker.server import MqttBroker
    from rmqtt_tpu.utils.failpoints import FAILPOINTS

    # rate the CPU-jax device path sustains headroom-free (each batch pays
    # a jax dispatch; on a real chip this is conservative) — oversubscribing
    # here would measure deliver-queue overflow, not failover behavior
    pub_rate = 60 if reduced else 90  # msgs/s
    soak_s = 4.5 if reduced else 6.0
    fault_at, clear_at = (1.5, 3.0) if reduced else (2.0, 4.0)
    pad = b"f" * 56

    async def _connect(port, cid):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        codec = MqttCodec(pk.V311)
        writer.write(codec.encode(pk.Connect(client_id=cid, keepalive=600)))
        await writer.drain()
        while True:
            data = await reader.read(4096)
            if not data:
                raise ConnectionError("no CONNACK")
            if codec.feed(data):
                return reader, writer, codec

    async def soak():
        # cache off: every publish must reach the dispatcher, or cache hits
        # would mask the device outage entirely
        b = MqttBroker(ServerContext(BrokerConfig(
            port=0, router="xla", route_cache=False,
            failover_cooldown=0.3, failover_threshold=2,
            failover_k_successes=2)))
        r = b.ctx.router
        r._hybrid_max = 0  # pin every batch to the device plane
        r._hybrid.small_max = 0
        r._hybrid.probe_every = 0
        await b.start()
        sw = pw = None
        try:
            fo = b.ctx.routing.failover
            assert fo is not None and fo.usable
            sr, sw, sc = await _connect(b.port, "c10-sub")
            sw.write(sc.encode(pk.Subscribe(1, [("fo10/#", pk.SubOpts(qos=0))])))
            await sw.drain()
            pr, pw, pcodec = await _connect(b.port, "c10-pub")
            # per-phase latency + arrival counts, bucketed by SEND time
            lat = {"steady": [], "failover": [], "recovered": []}
            received = [0]
            stop = asyncio.Event()
            t0 = None

            def phase_of(sent_rel):
                if sent_rel < fault_at:
                    return "steady"
                if sent_rel < clear_at:
                    return "failover"
                return "recovered"

            async def sub_loop():
                while not stop.is_set():
                    try:
                        data = await asyncio.wait_for(sr.read(65536), 0.25)
                    except asyncio.TimeoutError:
                        continue
                    if not data:
                        return
                    now = time.perf_counter()
                    for p in sc.feed(data):
                        # warm-up publishes ride a different topic: excluded
                        # from the measured counts and latencies
                        if isinstance(p, pk.Publish) and p.topic == "fo10/t":
                            ts = struct.unpack("d", p.payload[:8])[0]
                            lat[phase_of(ts - t0)].append(now - ts)
                            received[0] += 1

            # JIT warm OUTSIDE the measured window: the measured bursts run at
            # batch≈5 (pow2-padded to 8), so warm that shape too or the first
            # measured batch pays the compile and poisons the steady p99
            for _ in range(3):
                for _ in range(5):
                    pw.write(pcodec.encode(pk.Publish(
                        topic="fo10/warm",
                        payload=struct.pack("d", time.perf_counter()) + pad)))
                await pw.drain()
                await asyncio.sleep(0.3)
            await asyncio.sleep(1.0)
            task = asyncio.get_running_loop().create_task(sub_loop())
            sent = 0
            goodput = []  # per-0.5s received buckets
            switchback_s = None
            fault_set = cleared = False
            burst = 5
            t0 = time.perf_counter()
            last_bucket, last_rx = t0, 0
            while True:
                el = time.perf_counter() - t0
                # capture BEFORE the exit checks: a switchback landing after
                # soak_s (breaker backoff pushed the probe late) would otherwise
                # break out of the loop un-recorded
                if cleared and switchback_s is None and not fo.active:
                    switchback_s = time.perf_counter() - t0 - clear_at
                if el >= soak_s and not fo.active:
                    break
                if el >= soak_s + 20:
                    break  # no switchback: report it instead of hanging
                if not fault_set and el >= fault_at:
                    FAILPOINTS.set("device.dispatch", "error")
                    fault_set = True
                if not cleared and el >= clear_at:
                    FAILPOINTS.set("device.dispatch", "off")
                    cleared = True
                if el < soak_s:
                    for _ in range(burst):
                        payload = struct.pack("d", time.perf_counter()) + pad
                        pw.write(pcodec.encode(pk.Publish(topic="fo10/t", payload=payload)))
                    sent += burst
                    await pw.drain()
                now = time.perf_counter()
                if now - last_bucket >= 0.5:
                    goodput.append((received[0] - last_rx) / (now - last_bucket))
                    last_bucket, last_rx = now, received[0]
                await asyncio.sleep(burst / pub_rate)
            await asyncio.sleep(0.5)  # grace: in-flight deliveries land
            stop.set()
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass

            def p99(xs):
                return round(float(np.percentile(xs, 99)) * 1e3, 2) if xs else None

            steady_gp = [g for g in goodput[: max(1, int(fault_at / 0.5))] if g > 0]
            fault_gp = goodput[int(fault_at / 0.5): int(clear_at / 0.5)]
            res = {
                "sent": sent,
                "received": received[0],
                "lost": sent - received[0],
                "steady_p99_ms": p99(lat["steady"]),
                "failover_p99_ms": p99(lat["failover"]),
                "recovered_p99_ms": p99(lat["recovered"]),
                "steady_goodput_msgs_per_sec": round(
                    sum(steady_gp) / max(1, len(steady_gp)), 1),
                "failover_min_goodput_msgs_per_sec": round(min(fault_gp), 1)
                if fault_gp else None,
                "time_to_switchback_s": round(switchback_s, 2)
                if switchback_s is not None else None,
                "failovers": fo.failovers,
                "switchbacks": fo.switchbacks,
                "host_routed": fo.host_items,
                "device_failures": dict(fo.failures),
                "full_uploads": getattr(b.ctx.router.matcher, "full_uploads", 0),
            }
            if res["steady_goodput_msgs_per_sec"] and res["failover_min_goodput_msgs_per_sec"]:
                res["goodput_dip_pct"] = round(
                    100.0 * (1 - res["failover_min_goodput_msgs_per_sec"]
                             / res["steady_goodput_msgs_per_sec"]), 1)
            return res
        finally:
            # a mid-soak failure must not leak the armed process-
            # global failpoint or the running broker (same
            # discipline as tests/test_stress_chaos.py)
            FAILPOINTS.clear_all()
            for w in (sw, pw):
                try:
                    if w is not None:
                        w.close()
                except Exception:
                    pass
            await b.stop()

    res = {"name": name, "pub_rate": pub_rate, "soak_s": soak_s,
           "fault_window_s": [fault_at, clear_at],
           **asyncio.run(soak()),
           **({"reduced_sizes": True} if reduced else {})}
    log(f"[{name}] sent {res['sent']} received {res['received']} "
        f"(lost {res['lost']}) | p99 steady {res['steady_p99_ms']}ms "
        f"failover {res['failover_p99_ms']}ms recovered {res['recovered_p99_ms']}ms | "
        f"switchback in {res['time_to_switchback_s']}s "
        f"(failovers {res['failovers']}, host-routed {res['host_routed']})")
    return res


def run_fabric_config(name, rng, reduced):
    """Config 13: intra-node routing fabric vs localhost-broadcast workers,
    cfg7-style order-symmetric paired estimator.

    Two live 4-worker topologies in one process (each worker a full broker
    with its own listener — deterministic client placement, unlike
    SO_REUSEPORT kernel balancing): the FABRIC leg wires them through
    broker/fabric.py over real UDS sockets; the BROADCAST leg peers them
    as the localhost broadcast cluster `--workers` used before (real TCP
    cluster RPC). The workload is the shape ROADMAP item 2 calls out —
    cross-worker fan-out with a *placed* subscriber fleet: ``npubs``
    concurrent publishers on worker 2, the subscriber fleet on worker 4,
    QoS0 at 512-byte payloads.
    This is exactly where the architectures diverge: broadcast mode has no
    idea where subscribers live, so EVERY publish pays full cluster-RPC
    serialization against EVERY peer and a scatter-gather match on all of
    them; the fabric matches once at the owner and writes one deliver
    frame to the one worker that owns the fleet. Bursts alternate legs in
    order-symmetric quads; the ratio of per-burst goodputs is the
    artifact's ``fanout_goodput_ratio`` (target ≥ 3× at 4 workers on CPU).
    The CONNECT-takeover probe reconnects a client id across workers and
    reports per-leg kick p99 — the fabric resolves it via the directory
    (one targeted RPC), broadcast scatters a kick RPC to every peer."""
    import asyncio
    import tempfile

    from rmqtt_tpu.broker.codec import MqttCodec, packets as pk
    from rmqtt_tpu.broker.context import BrokerConfig, ServerContext
    from rmqtt_tpu.broker.fitter import FitterConfig
    from rmqtt_tpu.broker.server import MqttBroker

    nworkers = 4
    nsubs = 2  # the placed fleet on worker 4
    npubs = 32  # concurrent publisher sessions on worker 2
    per = 512 if reduced else 1024  # publishes per burst (×nsubs deliveries)
    quads = 3 if reduced else 5
    kick_iters = 12 if reduced else 30

    async def _read_until(reader, codec, ptype):
        while True:
            data = await reader.read(4096)
            if not data:
                raise ConnectionError(f"peer closed before {ptype.__name__}")
            for p in codec.feed(data):
                if isinstance(p, ptype):
                    return p

    async def _connect(port, cid):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        codec = MqttCodec()
        writer.write(codec.encode(pk.Connect(client_id=cid, keepalive=600)))
        await writer.drain()
        await _read_until(reader, codec, pk.Connack)
        return reader, writer, codec

    async def _leg_fabric():
        td = tempfile.mkdtemp(prefix="cfg13-fab-")
        workers = []
        for wid in range(1, nworkers + 1):
            b = MqttBroker(ServerContext(BrokerConfig(
                port=0, node_id=wid, telemetry_enable=False,
                fitter=FitterConfig(max_mqueue=100_000),
                fabric_enable=True, fabric_dir=td, fabric_worker_id=wid,
                fabric_workers=nworkers)))
            await b.start()
            workers.append(b)
        deadline = time.monotonic() + 10
        while not all(w.ctx.fabric.is_owner or w.ctx.fabric._owner_up.is_set()
                      for w in workers):
            assert time.monotonic() < deadline, "fabric never registered"
            await asyncio.sleep(0.05)
        return workers, None

    async def _leg_broadcast():
        from rmqtt_tpu.cluster.broadcast import BroadcastCluster
        from rmqtt_tpu.cluster.transport import PeerClient

        workers, clusters = [], []
        for wid in range(1, nworkers + 1):
            b = MqttBroker(ServerContext(BrokerConfig(
                port=0, node_id=wid, telemetry_enable=False, cluster=True,
                fitter=FitterConfig(max_mqueue=100_000))))
            await b.start()
            workers.append(b)
        for b in workers:
            c = BroadcastCluster(b.ctx, ("127.0.0.1", 0), [])
            await c.start()
            clusters.append(c)
        for i, c in enumerate(clusters):
            for j, other in enumerate(clusters):
                if i != j:
                    nid = workers[j].ctx.node_id
                    c.peers[nid] = PeerClient(nid, "127.0.0.1",
                                              other.bound_port)
            c.bcast.peers = list(c.peers.values())
        return workers, clusters

    async def _wire_traffic(workers, tag):
        """The placed fleet: nsubs subscribers on worker 4 + npubs
        publishers on worker 2; → (burst fn, close fn)."""
        subs = []
        for k in range(nsubs):
            r, w, c = await _connect(workers[3].port, f"{tag}s{k}")
            w.write(c.encode(pk.Subscribe(
                1, [("fab/#", pk.SubOpts(qos=0))])))
            await w.drain()
            await _read_until(r, c, pk.Suback)
            subs.append((r, w, c))
        pubs = [await _connect(workers[1].port, f"{tag}p{k}")
                for k in range(npubs)]
        frames = [pubs[0][2].encode(pk.Publish(
            topic=f"fab/t{i}", payload=b"x" * 512, qos=0))
            for i in range(32)]
        await asyncio.sleep(0.3)  # subscription replication settles

        async def burst(n):
            """n publishes spread across the npubs publisher sessions;
            → (active-window seconds, deliveries across the fleet)."""
            got = [0] * len(subs)
            done = asyncio.Event()
            want_total = n * len(subs)
            total = [0]
            last = [0.0]  # timestamp of the latest delivery (effective end)

            async def drain(si, reader, codec):
                while total[0] < want_total:
                    try:
                        data = await asyncio.wait_for(reader.read(1 << 16), 2.0)
                    except asyncio.TimeoutError:
                        return  # QoS0: late stragglers are counted as lost
                    if not data:
                        return
                    k = sum(1 for p in codec.feed(data)
                            if isinstance(p, pk.Publish))
                    got[si] += k
                    total[0] += k
                    last[0] = time.perf_counter()
                    if total[0] >= want_total:
                        done.set()

            t0 = time.perf_counter()
            drains = [asyncio.get_running_loop().create_task(
                drain(si, r, c)) for si, (r, _w, c) in enumerate(subs)]

            async def feed(pi, count):
                _r, w, _c = pubs[pi]
                sent = 0
                while sent < count:
                    k = min(32, count - sent)
                    w.write(b"".join(frames[(sent + j) % 32]
                                     for j in range(k)))
                    sent += k
                    await w.drain()

            await asyncio.gather(*(feed(pi, n // npubs)
                                   for pi in range(npubs)))
            try:
                await asyncio.wait_for(done.wait(), 30.0)
            except asyncio.TimeoutError:
                pass
            # goodput over the active delivery window: a leg that sheds
            # (or idles out) is measured to its LAST delivery, not to the
            # idle-timeout tail
            elapsed = (last[0] or time.perf_counter()) - t0
            for t in drains:
                t.cancel()
            return max(elapsed, 1e-6), total[0]

        async def close():
            for r, w, _c in [*subs, *pubs]:
                try:
                    w.close()
                except Exception:
                    pass

        return burst, close

    async def _kick_p99(workers, tag):
        """Reconnect one client id across workers; CONNECT wall time of the
        takeover side (includes the kick resolution) → p99 ms."""
        times = []
        for i in range(kick_iters):
            cid = f"{tag}kick{i}"
            _r1, w1, _c1 = await _connect(workers[2].port, cid)
            t0 = time.perf_counter()
            _r2, w2, _c2 = await _connect(workers[3].port, cid)
            times.append((time.perf_counter() - t0) * 1e3)
            for w in (w1, w2):
                try:
                    w.close()
                except Exception:
                    pass
        return float(np.percentile(times, 99)), float(np.percentile(times, 50))

    async def _measure():
        fab_workers, _ = await _leg_fabric()
        bc_workers, bc_clusters = await _leg_broadcast()
        try:
            fab_burst, fab_close = await _wire_traffic(fab_workers, "f")
            bc_burst, bc_close = await _wire_traffic(bc_workers, "b")
            await fab_burst(128)  # warm both paths (codec, links, caches)
            await bc_burst(128)
            pairs = []
            for _ in range(quads):
                # order-symmetric quad (fab, bc, bc, fab): taking each
                # condition's BEST goodput of its two bursts (= fastest
                # burst) filters one-sided load spikes before the ratio
                ef1, nf1 = await fab_burst(per)
                eb1, nb1 = await bc_burst(per)
                eb2, nb2 = await bc_burst(per)
                ef2, nf2 = await fab_burst(per)
                gf = max(nf1 / ef1, nf2 / ef2)
                gb = max(nb1 / eb1, nb2 / eb2)
                pairs.append((gf, gb))
            fk99, fk50 = await _kick_p99(fab_workers, "f")
            bk99, bk50 = await _kick_p99(bc_workers, "b")
            await fab_close()
            await bc_close()
            return pairs, (fk99, fk50), (bk99, bk50)
        finally:
            for c in bc_clusters or []:
                await c.stop()
            for b in [*fab_workers, *bc_workers]:
                await b.stop()

    pairs, fab_kick, bc_kick = asyncio.run(_measure())
    ratio = float(np.median([gf / gb for gf, gb in pairs]))
    fab_goodput = max(gf for gf, _ in pairs)
    bc_goodput = max(gb for _, gb in pairs)
    res = {
        "name": name,
        "workers": nworkers,
        "subscribers": nsubs,
        "publishers": npubs,
        "msgs_per_burst": per,
        "fanout_goodput_fabric": round(fab_goodput, 1),
        "fanout_goodput_broadcast": round(bc_goodput, 1),
        "fanout_goodput_ratio": round(ratio, 2),
        "target_ratio": 3.0,
        "ok": ratio >= 3.0,
        "connect_kick_ms": {
            "fabric_p50": round(fab_kick[1], 3),
            "fabric_p99": round(fab_kick[0], 3),
            "broadcast_p50": round(bc_kick[1], 3),
            "broadcast_p99": round(bc_kick[0], 3),
        },
        **({"reduced_sizes": True} if reduced else {}),
    }
    log(f"[{name}] cross-worker fan-out: fabric {fab_goodput:.0f} vs "
        f"broadcast {bc_goodput:.0f} deliveries/s → {ratio:.2f}x "
        f"(target ≥3x) | CONNECT kick p99 fabric {res['connect_kick_ms']['fabric_p99']}ms "
        f"vs broadcast {res['connect_kick_ms']['broadcast_p99']}ms")
    return res


def run_autotune_config(name, rng, reduced):
    """Config 15: the device-plane autotuner vs static defaults over a
    SHIFTING-REGIME workload, cfg13-style order-symmetric quads.

    The workload is the regime sequence the static env-flag matrix cannot
    serve with one setting: small-batch bursts (batch 1 — the cfg1 cliff
    shape) → steady large batches (batch 64) → subscription churn with
    more small batches. Both legs start from the SAME defaults (prewarm
    latches the sticky pad floor at 8); the autotune leg additionally
    runs the real controller (broker/autotune.py) against the real knob
    registry + devprof rollups, ticked between dispatches. The expected
    adaptation: the batch-size histogram concentrates at 1 while
    pad-waste sits at 7/8, so the pad-floor ladder canaries 8→4→2→1 and
    every later small-batch dispatch pays 1/8th the padded compute the
    static leg keeps paying.

    Legs alternate in order-symmetric quads (auto, static, static, auto)
    so drift lands on both; per quad each condition keeps its best run.
    The artifact carries the decision timeline (canary/commit/rollback
    journal with before/after metrics) — the acceptance evidence of ≥1
    adaptation and 0 unrecovered rollbacks — plus per-phase p99 and
    whole-workload goodput per leg. Target: the autotune leg beats the
    static leg by ≥1.15x on small-regime p99 or goodput."""
    from rmqtt_tpu.broker.autotune import AutotuneService
    from rmqtt_tpu.broker.devprof import DEVPROF
    from rmqtt_tpu.broker.knobs import build_registry
    from rmqtt_tpu.ops.partitioned import PartitionedMatcher

    n = 12_000 if reduced else 20_000
    d_small, d_steady, d_churn = ((240, 24, 120) if reduced
                                  else (400, 24, 200))
    quads = 1 if reduced else 3
    bs_big = 64
    pool_n = 48  # bounded topic pool: bounded shapes, warm candidate sets

    # wildcard-heavy filter population (first level '+'): candidate sets
    # stay large per topic, so the padded-batch compute the pad floor
    # multiplies is REAL — the regime where the cfg1 cliff lives (a
    # pure-exact table is dispatch-overhead-bound and no floor can help)
    def gen_first_plus(count):
        fs = set()
        while len(fs) < count:
            depth = rng.randint(3, 6)
            lv = [f"v{d}_{rng.randrange(VOCAB6[d])}" for d in range(depth)]
            lv[0] = "+"
            if rng.random() < 0.4:
                lv[rng.randrange(1, depth)] = "+"
            if rng.random() < 0.3:
                lv[-1] = "#"
            fs.add("/".join(lv))
        return sorted(fs)

    filters = gen_first_plus(n)
    table, fids = build_tpu_table(filters, "partitioned")
    # churn must NOT trigger background compaction here: a layout-epoch
    # bump invalidates every warmed shape, and the autotune leg touches
    # 4x the shapes (floors 8/4/2/1) the static leg does — recompiles
    # would bill the ladder for table maintenance this config doesn't
    # measure (cfg9 owns the compaction story)
    table.compact_min_ops = 1 << 30
    pool = gen_topics_uniform(rng, pool_n)
    big_batches = [[pool[(i * 7 + j) % pool_n] for j in range(bs_big)]
                   for i in range(8)]
    churn_filters = gen_mixed(random.Random(rng.randrange(2**31)),
                              max(32, d_churn // 4))
    log(f"[{name}] {n} subs, regimes: {d_small}x1 -> {d_steady}x{bs_big} "
        f"-> {d_churn}x1+churn, {quads} order-symmetric quad(s)")

    # deterministic workload script, shared verbatim by every leg run:
    # (phase, batch, churn_step or None)
    seq = []
    for i in range(d_small):
        seq.append(("small", [pool[i % pool_n]], None))
    for i in range(d_steady):
        seq.append(("steady", big_batches[i % len(big_batches)], None))
    for i in range(d_churn):
        seq.append(("churn", [pool[(i * 3) % pool_n]],
                    i // 16 if i % 16 == 0 else None))

    churn_fids = []

    def apply_churn(step):
        # one add + one remove per step: steady version churn (delta
        # uploads + journal activity) without net table growth
        f = churn_filters[step % len(churn_filters)]
        fid = table.add(f + f"/c{step}n{len(churn_fids)}")
        if len(churn_fids) > 1:
            table.remove(churn_fids.pop(0))
        return fid

    def run_leg(auto_on, tag):
        # NO devprof reset here: the shape-key registry must stay as old
        # as the process or every warm executable re-counts as a "trace"
        # and phantom retrace storms hold the tuner (the controller's
        # counter baselines prime from the profiler at construction)
        m = PartitionedMatcher(table)
        m.prewarm((1, 8))  # the static default: sticky pad floor 8
        svc = None
        if auto_on:
            shim = type("_RouterShim", (), {})()
            shim.matcher = m
            reg = build_registry(shim, None)
            svc = AutotuneService(
                reg, enabled=True, interval_s=0.05, canary_k=6,
                cooldown_s=0.5, p99_guard=2.0, confirm_ticks=2,
                devprof=DEVPROF)
        lat = {"small": [], "steady": [], "churn": []}
        t0 = time.perf_counter()
        for i, (phase, batch, churn_step) in enumerate(seq):
            if churn_step is not None:
                churn_fids.append(apply_churn(churn_step))
            t1 = time.perf_counter()
            m.match(batch)
            lat[phase].append(time.perf_counter() - t1)
            if svc is not None and i % 4 == 3:
                svc.tick()
        wall = time.perf_counter() - t0
        topics = sum(len(b) for _p, b, _c in seq)

        def p99(ls):
            ls = sorted(ls)
            return round(ls[min(len(ls) - 1, int(len(ls) * 0.99))] * 1e3, 3)

        # tail halves = the CONVERGED regime (the autotune leg spends its
        # head learning; the static leg's halves are statistically
        # identical, so the split is order-symmetric-fair). Full-phase
        # numbers ride alongside — the learning transient stays visible.
        tail = {k: v[len(v) // 2:] for k, v in lat.items()}
        small_churn_tail = tail["small"] + tail["churn"]
        out = {
            "goodput_topics_per_sec": round(topics / wall, 1),
            "tail_goodput_topics_per_sec": round(
                (len(small_churn_tail) + len(tail["steady"]) * bs_big)
                / max(1e-9, sum(small_churn_tail) + sum(tail["steady"])),
                1),
            # the pure small-batch regime is what the pad floor serves —
            # the pair metric reads THIS tail; steady proves the tuner
            # doesn't worsen large batches (p99_steady_ms) and churn that
            # upload traffic doesn't destabilize it (tail_p99_churn_ms),
            # both additive-equal costs that would only dilute the ratio
            "tail_small_goodput_topics_per_sec": round(
                len(tail["small"]) / max(1e-9, sum(tail["small"])), 1),
            "tail_smallchurn_goodput_topics_per_sec": round(
                len(small_churn_tail) / max(1e-9, sum(small_churn_tail)),
                1),
            "p99_small_ms": p99(lat["small"]),
            "p99_steady_ms": p99(lat["steady"]),
            "p99_churn_ms": p99(lat["churn"]),
            # combined small+churn tail: one percentile over every
            # converged small-batch dispatch — the per-phase tails are
            # ~100 samples each and their p99 is a coin-flip between
            # adjacent outliers
            "tail_p99_ms": p99(small_churn_tail),
            "tail_p99_small_ms": p99(tail["small"]),
            "tail_p99_churn_ms": p99(tail["churn"]),
            "pad_floor_final": m._pad_floor,
        }
        if svc is not None:
            out["decisions"] = list(svc.journal)
            out["commits"] = svc.commits
            out["rollbacks"] = svc.rollbacks
            out["aborts"] = svc.aborts
            out["canary_open_at_end"] = svc._canary is not None
            out["final_knobs"] = {r["name"]: r["value"]
                                  for r in reg.snapshot()}
        return out

    # shape warmup OUTSIDE measurement: every pool topic at every ladder
    # floor + the steady shape + a churn mutation, so neither leg pays an
    # XLA compile mid-measurement (the canary trace budget covers the
    # real-world compile cost story; this config measures steady state)
    DEVPROF.reset()
    prior = (DEVPROF.enabled, DEVPROF.interval_s)
    DEVPROF.configure(enabled=True, interval_s=0.05)
    warm = PartitionedMatcher(table)
    warm.match(big_batches[0])  # fused verify + pallas decide
    for floor in (8, 4, 2, 1):
        warm.set_pad_floor(floor)
        for t in pool:
            warm.match([t])
    for b in big_batches:
        warm.match(b)
    for step in range(4):  # delta-scatter + post-churn refresh shapes
        churn_fids.append(apply_churn(step))
        warm.match([pool[step]])

    try:
        autos, statics, quad_rows = [], [], []
        for _ in range(quads):
            a1 = run_leg(True, "auto")
            b1 = run_leg(False, "static")
            b2 = run_leg(False, "static")
            a2 = run_leg(True, "auto")
            autos += [a1, a2]
            statics += [b1, b2]
            # within-quad pairing (cfg13 discipline): each condition keeps
            # its best of two runs, so a host-noise window hitting one run
            # doesn't decide the quad; the MEDIAN across quads decides the
            # config (a global best-of-all-runs let one lucky static run
            # dilute the whole estimate)
            ga = max(a1["tail_small_goodput_topics_per_sec"],
                     a2["tail_small_goodput_topics_per_sec"])
            gb = max(b1["tail_small_goodput_topics_per_sec"],
                     b2["tail_small_goodput_topics_per_sec"])
            pa = min(a1["tail_p99_small_ms"], a2["tail_p99_small_ms"])
            pb = min(b1["tail_p99_small_ms"], b2["tail_p99_small_ms"])
            quad_rows.append({
                "tail_goodput_ratio": round(ga / max(1e-9, gb), 3),
                "tail_p99_ratio": round(pb / max(1e-9, pa), 3),
            })
    finally:
        DEVPROF.configure(enabled=prior[0], interval_s=prior[1])
        DEVPROF.reset()
        for fid in churn_fids:  # leave the shared table as we found it
            try:
                table.remove(fid)
            except Exception:
                pass

    best_auto = max(autos, key=lambda r: r["tail_goodput_topics_per_sec"])
    best_static = max(statics,
                      key=lambda r: r["tail_goodput_topics_per_sec"])
    goodput_ratio = (best_auto["goodput_topics_per_sec"]
                     / max(1e-9, best_static["goodput_topics_per_sec"]))
    # the converged (tail-half) regime is the autotuner's claim — the
    # learning transient rides in the full-phase numbers + the timeline.
    # Per-quad ratios, MEDIAN across quads (see quad_rows above).
    med = len(quad_rows) // 2
    tail_goodput_ratio = sorted(
        q["tail_goodput_ratio"] for q in quad_rows)[med]
    tail_p99_ratio = sorted(
        q["tail_p99_ratio"] for q in quad_rows)[med]
    pair_ratio = max(tail_goodput_ratio, tail_p99_ratio)
    adaptations = sum(a.get("commits", 0) for a in autos)
    unrecovered = sum(1 for a in autos if a.get("canary_open_at_end"))
    res = {
        "name": name,
        "table_size": len(fids),
        "regimes": {"small": d_small, "steady": d_steady,
                    "churn": d_churn, "big_batch": bs_big},
        "autotune": best_auto,
        "static": best_static,
        "quads": quad_rows,
        "goodput_ratio": round(goodput_ratio, 3),
        "tail_goodput_ratio": round(tail_goodput_ratio, 3),
        "tail_p99_ratio": round(tail_p99_ratio, 3),
        "pair_ratio": round(pair_ratio, 3),
        "target_ratio": 1.15,
        "adaptations": adaptations,
        "rollbacks": sum(a.get("rollbacks", 0) for a in autos),
        "unrecovered_rollbacks": unrecovered,
        "ok": (pair_ratio >= 1.15
               and adaptations >= 1 and unrecovered == 0),
        **({"reduced_sizes": True} if reduced else {}),
    }
    log(f"[{name}] autotune tail p99(small) "
        f"{best_auto['tail_p99_small_ms']}ms / "
        f"{best_auto['tail_goodput_topics_per_sec']:.0f}/s (floor -> "
        f"{best_auto['pad_floor_final']}) vs static "
        f"{best_static['tail_p99_small_ms']}ms / "
        f"{best_static['tail_goodput_topics_per_sec']:.0f}/s -> tail p99 "
        f"{tail_p99_ratio:.2f}x, tail goodput {tail_goodput_ratio:.2f}x, "
        f"run goodput {goodput_ratio:.2f}x (target >=1.15x, "
        f"{adaptations} commits, {res['rollbacks']} rollbacks)")
    return res


def run_egress_config(name, rng, reduced):
    """Config 16: coalesced egress vs legacy per-frame writes at
    64-subscriber fan-out, cfg13-style order-symmetric paired estimator.

    Two live single-worker brokers in one process, identical except for
    ``[network] egress_coalesce``: the COALESCED leg batches every frame
    queued for a connection within one loop tick into a single vectored
    write (broker/egress.py); the LEGACY leg is the pre-coalescer data
    plane — one transport write per outbound frame. The workload is the
    fan-out shape where per-frame writes dominate: 64 subscribers
    sharing one wildcard filter, so each QoS0 publish becomes 64
    outbound frames and the write-call count is the data plane's real
    syscall budget. Bursts alternate legs in order-symmetric quads
    (coalesced, legacy, legacy, coalesced) with each condition keeping
    its best burst; the artifact carries syscalls-per-delivered-message
    per leg — the coalesced leg counts its ACTUAL vectored writes via
    the ``net.egress_flushes`` counter delta, the legacy send path is
    structurally one transport write per frame (broker/session.py
    send_raw) — plus the goodput ratio. Targets: ≥5x fewer send
    syscalls per delivered message and ≥1.25x goodput."""
    import asyncio

    from rmqtt_tpu.broker.codec import MqttCodec, packets as pk
    from rmqtt_tpu.broker.context import BrokerConfig, ServerContext
    from rmqtt_tpu.broker.fitter import FitterConfig
    from rmqtt_tpu.broker.server import MqttBroker

    nsubs = 64  # the fan-out fleet, one shared wildcard filter
    npubs = 32  # concurrent publishers: the coalescing window is one loop
    # tick, so frames-per-flush scales with how many publisher sessions
    # route a publish in the same tick (the production fan-in shape)
    per = 256 if reduced else 512  # publishes per burst (×nsubs deliveries)
    quads = 2 if reduced else 3

    async def _read_until(reader, codec, ptype):
        while True:
            data = await reader.read(4096)
            if not data:
                raise ConnectionError(f"peer closed before {ptype.__name__}")
            for p in codec.feed(data):
                if isinstance(p, ptype):
                    return p

    async def _connect(port, cid):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        codec = MqttCodec()
        writer.write(codec.encode(pk.Connect(client_id=cid, keepalive=600)))
        await writer.drain()
        await _read_until(reader, codec, pk.Connack)
        return reader, writer, codec

    async def _leg(coalesce):
        b = MqttBroker(ServerContext(BrokerConfig(
            port=0, telemetry_enable=False, egress_coalesce=coalesce,
            fitter=FitterConfig(max_mqueue=100_000))))
        await b.start()
        return b

    async def _wire_traffic(broker, tag, coalesce):
        """64 subscribers on eg/# + npubs publishers; → (burst, close).
        burst(n) → (active-window seconds, deliveries, send calls)."""
        subs = []
        for k in range(nsubs):
            r, w, c = await _connect(broker.port, f"{tag}s{k}")
            w.write(c.encode(pk.Subscribe(
                1, [("eg/#", pk.SubOpts(qos=0))])))
            await w.drain()
            await _read_until(r, c, pk.Suback)
            subs.append((r, w, c))
        pubs = [await _connect(broker.port, f"{tag}p{k}")
                for k in range(npubs)]
        frames = [pubs[0][2].encode(pk.Publish(
            topic=f"eg/t{i}", payload=b"x" * 512, qos=0))
            for i in range(32)]
        metrics = broker.ctx.metrics

        async def burst(n):
            got = [0] * len(subs)
            done = asyncio.Event()
            want_total = n * len(subs)
            total = [0]
            last = [0.0]  # timestamp of the latest delivery (effective end)

            async def drain(si, reader, codec):
                while total[0] < want_total:
                    try:
                        data = await asyncio.wait_for(reader.read(1 << 16), 2.0)
                    except asyncio.TimeoutError:
                        return  # QoS0: late stragglers are counted as lost
                    if not data:
                        return
                    k = sum(1 for p in codec.feed(data)
                            if isinstance(p, pk.Publish))
                    got[si] += k
                    total[0] += k
                    last[0] = time.perf_counter()
                    if total[0] >= want_total:
                        done.set()

            w0 = metrics.get("net.egress_flushes")
            t0 = time.perf_counter()
            drains = [asyncio.get_running_loop().create_task(
                drain(si, r, c)) for si, (r, _w, c) in enumerate(subs)]

            async def feed(pi, count):
                _r, w, _c = pubs[pi]
                sent = 0
                while sent < count:
                    k = min(32, count - sent)
                    w.write(b"".join(frames[(sent + j) % 32]
                                     for j in range(k)))
                    sent += k
                    await w.drain()

            await asyncio.gather(*(feed(pi, n // npubs)
                                   for pi in range(npubs)))
            try:
                await asyncio.wait_for(done.wait(), 30.0)
            except asyncio.TimeoutError:
                pass
            elapsed = (last[0] or time.perf_counter()) - t0
            for t in drains:
                t.cancel()
            # send calls: the coalesced leg's flush counter counts each
            # vectored write it issued; the legacy path is one
            # transport.write per frame, i.e. exactly the delivery count
            writes = ((metrics.get("net.egress_flushes") - w0)
                      if coalesce else total[0])
            return max(elapsed, 1e-6), total[0], writes

        async def close():
            for r, w, _c in [*subs, *pubs]:
                try:
                    w.close()
                except Exception:
                    pass

        return burst, close

    async def _measure():
        cb = await _leg(True)
        lb = await _leg(False)
        try:
            c_burst, c_close = await _wire_traffic(cb, "c", True)
            l_burst, l_close = await _wire_traffic(lb, "l", False)
            await c_burst(64)  # warm both paths (codec, routes, buffers)
            await l_burst(64)
            pairs = []
            deliv_c = writes_c = deliv_l = writes_l = 0
            for _ in range(quads):
                # order-symmetric quad (coal, legacy, legacy, coal):
                # each condition keeps its BEST goodput of its two
                # bursts, filtering one-sided load spikes (cfg13 rule)
                ec1, nc1, wc1 = await c_burst(per)
                el1, nl1, wl1 = await l_burst(per)
                el2, nl2, wl2 = await l_burst(per)
                ec2, nc2, wc2 = await c_burst(per)
                pairs.append((max(nc1 / ec1, nc2 / ec2),
                              max(nl1 / el1, nl2 / el2)))
                deliv_c += nc1 + nc2
                writes_c += wc1 + wc2
                deliv_l += nl1 + nl2
                writes_l += wl1 + wl2
            # counter snapshot BEFORE teardown: closing the sessions
            # fires their final flushes and would skew the totals
            eg = {k: cb.ctx.metrics.get(f"net.egress_{k}")
                  for k in ("frames", "flushes", "coalesced", "bytes")}
            await c_close()
            await l_close()
            return pairs, (deliv_c, writes_c), (deliv_l, writes_l), eg
        finally:
            await cb.stop()
            await lb.stop()

    pairs, (dc, wc), (dl, wl), eg = asyncio.run(_measure())
    ratio = float(np.median([gc / gl for gc, gl in pairs]))
    spm_c = wc / max(1, dc)
    spm_l = wl / max(1, dl)  # 1.0 by construction (one write per frame)
    reduction = spm_l / max(1e-9, spm_c)
    res = {
        "name": name,
        "subscribers": nsubs,
        "publishers": npubs,
        "msgs_per_burst": per,
        "fanout_goodput_coalesced": round(max(gc for gc, _ in pairs), 1),
        "fanout_goodput_legacy": round(max(gl for _, gl in pairs), 1),
        "goodput_ratio": round(ratio, 3),
        "syscalls_per_msg_coalesced": round(spm_c, 4),
        "syscalls_per_msg_legacy": round(spm_l, 4),
        "syscall_reduction_x": round(reduction, 2),
        "egress_counters": eg,
        "target_syscall_reduction": 5.0,
        "target_goodput_ratio": 1.25,
        "ok": reduction >= 5.0 and ratio >= 1.25,
        **({"reduced_sizes": True} if reduced else {}),
    }
    log(f"[{name}] {nsubs}-sub fan-out: coalesced "
        f"{res['fanout_goodput_coalesced']:.0f} vs legacy "
        f"{res['fanout_goodput_legacy']:.0f} deliveries/s → {ratio:.2f}x "
        f"goodput (target ≥1.25x) | {spm_c:.3f} vs {spm_l:.3f} "
        f"send calls/msg → {reduction:.1f}x fewer (target ≥5x)")
    return res


def tpu_available(probe_timeout: float = 60.0, retries: int = 2) -> bool:
    """Probe the TPU in a subprocess (see rmqtt_tpu.utils.tpuprobe: the axon
    grant can be wedged, making in-process jax.devices() block forever)."""
    from rmqtt_tpu.utils.tpuprobe import tpu_available as _probe

    return _probe(timeout=probe_timeout, retries=retries)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny config 1 only")
    ap.add_argument("--full", action="store_true", help="include 10M-sub configs 4-5")
    ap.add_argument("--config", type=int, default=None, help="run a single config 1-17")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cpu", action="store_true", help="force CPU (skip TPU probe)")
    ap.add_argument(
        "--profile", metavar="DIR", default=None,
        help="capture an XLA/device profile of the measured configs into DIR "
             "(view with tensorboard / xprof; stats.rs-era tracing analogue)",
    )
    args = ap.parse_args()

    import os

    import jax

    on_tpu = not args.cpu and tpu_available()
    reduced = False
    if not on_tpu:
        if not args.cpu:
            log("TPU unreachable — falling back to CPU platform (reduced sizes)")
        from jax.extend import backend as _eb

        _eb.clear_backends()  # a preload may override JAX_PLATFORMS (tpuprobe)
        jax.config.update("jax_platforms", "cpu")
        # still run cfgs 1-3 at reduced-but-nontrivial sizes: a wedged-chip
        # driver run must emit a multi-config, information-bearing artifact
        # (round 2 recorded only cfg1@200subs and lost the round's progress)
        reduced = args.config is None and not args.smoke
    else:
        # a wedge mid-run must fail the one config, not hang the process:
        # every device fetch in the match/scan paths honors this deadline
        os.environ.setdefault("RMQTT_FETCH_TIMEOUT", "180")

    rng = random.Random(args.seed)
    platform = jax.devices()[0].platform
    global _ON_TPU
    _ON_TPU = platform == "tpu"
    log(f"jax devices: {jax.devices()} (platform={platform})")

    # device-plane profiler (broker/devprof.py): every bench run carries
    # the devprof snapshot in its JSON, and a FAILED config persists a
    # flight-recorder dump so the next TPU window is diagnosable even when
    # the run dies (the postmortem cfg4/cfg5 never got)
    from rmqtt_tpu.broker.devprof import DEVPROF

    devprof_dir = os.path.join(os.path.dirname(__file__), ".devprof")
    DEVPROF.configure(enabled=True, dump_dir=devprof_dir)
    # the chip hunter TERMs a wedged child before KILLing it: freeze the
    # flight recorder on the way out so even a timed-out config leaves an
    # artifact (SIGKILL leaves nothing — that is why the TERM comes first).
    # The handler ONLY raises: signal handlers run on the main thread
    # between bytecodes, and the interrupted frame may be inside a
    # `with DEVPROF._lock:` block — dumping here would deadlock on the
    # non-reentrant lock. The KeyboardInterrupt unwinds those `with`
    # blocks (releasing the lock) and guarded()'s handler does the dump.
    import signal as _signal

    def _on_term(_sig, _frm):
        raise KeyboardInterrupt

    try:
        _signal.signal(_signal.SIGTERM, _on_term)
    except (ValueError, OSError):  # non-main thread / exotic platform
        pass

    results = {}

    def want(i):
        if args.smoke:
            return i == 1
        if args.config is not None:
            return i == args.config
        if reduced:
            # CPU fallback: ALL configs at reduced-but-nontrivial
            # sizes — cfg4/cfg5's code paths (shared+zipf, retained
            # interleave, segmented tables) must be exercised even in a
            # wedged-chip round, and the artifact carries a number for
            # every config (round 3's fallback skipped 4-5 entirely)
            return i <= 18
        # on real TPU the default is ALL FIVE baseline configs; cfg6 (the
        # host-side match-result cache), cfg7 (telemetry overhead), cfg8
        # (overload soak), cfg9 (churn soak / delta uploads), cfg11
        # (small-batch stage attribution), cfg12/cfg14 (device/host
        # profiler overhead bounds), cfg13 (fabric-vs-broadcast fan-out),
        # cfg15 (autotune-vs-static shifting regime), cfg16
        # (coalesced-vs-legacy egress), cfg17 (history collector
        # overhead bound) and cfg18 (hot-key sketch overhead bound) are
        # cheap and always informative
        return (i <= 3 or i in (6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17,
                                18)
                or args.full or on_tpu)

    failures = {}
    if args.profile:
        global _PROFILE_DIR
        _PROFILE_DIR = args.profile

    interrupted = False

    def guarded(name, fn):
        """A late config failing (OOM at 10M subs, driver timeout nearing,
        the accelerator wedging mid-run) must not lose the results already
        measured — even SIGINT falls through to the JSON print below."""
        nonlocal interrupted
        if interrupted:
            failures[name] = "skipped: interrupted"
            return
        try:
            results[name] = fn()
        except KeyboardInterrupt:
            interrupted = True
            failures[name] = "KeyboardInterrupt (timeout/wedge?)"
            log(f"{name} INTERRUPTED — emitting the configs already measured")
            # safe here: the interrupt already unwound any profiler-lock
            # `with` blocks on this thread (see the SIGTERM handler note)
            DEVPROF.dump_to(os.path.join(devprof_dir, f"{name}.json"),
                            f"bench-config-interrupted: {name}")
        except BaseException as e:
            failures[name] = f"{type(e).__name__}: {e}"
            log(f"{name} FAILED: {failures[name]}")
            # persist the flight recorder for the dead config: the artifact
            # that makes a failed chip run diagnosable after the window
            DEVPROF.dump_to(os.path.join(devprof_dir, f"{name}.json"),
                            f"bench-config-failed: {failures[name]}")
            if on_tpu and not tpu_available(probe_timeout=30.0, retries=1):
                # the accelerator wedged mid-run: later configs would spend
                # minutes building tables only to hang on their first device
                # call — emit what was measured instead
                interrupted = True
                log("accelerator unreachable after failure — skipping "
                    "remaining configs")

    if want(1):
        def cfg1():
            n = 1000 if not args.smoke else 200
            filters = gen_exact(rng, n)
            # ~50% of publishes hit a subscribed topic
            topics = [rng.choice(filters) if rng.random() < 0.5 else _tree_topic(rng, 4) for _ in range(4096)]
            return run_config("cfg1_exact_1k", filters, topics, 4096, 1024)

        guarded("cfg1_exact_1k", cfg1)

    if want(2):
        def cfg2():
            n, nt, bs = (20_000, 8_192, 2048) if reduced else (100_000, 20_000, 8192)
            filters = gen_single_plus(rng, n)
            # depth 3-5 filters over l{d}n{...} names: generate matching-shape topics
            topics = ["/".join(f"l{d}n{rng.randrange(400)}" for d in range(rng.randint(3, 5))) for _ in range(nt)]
            return run_config("cfg2_plus_100k", filters, topics, bs, 512)

        guarded("cfg2_plus_100k", cfg2)

    if want(3):
        def cfg3():
            n, nt, bs = (100_000, 8_192, 2048) if reduced else (1_000_000, 32_768, 16384)
            filters = gen_mixed(rng, n)
            topics = gen_topics_uniform(rng, nt)
            return run_config("cfg3_mixed_1m", filters, topics, bs, 256)

        guarded("cfg3_mixed_1m", cfg3)

    if want(4):
        def cfg4():
            n, nt, bs, cs = ((200_000, 4_096, 2048, 64) if reduced
                             else (10_000_000, 16_384, 8192, 64))
            filters = gen_mixed(rng, n, shared_frac=0.1)
            topics = gen_topics_zipf(rng, nt)
            return run_config("cfg4_shared_10m_zipf", filters, topics, bs, cs)

        guarded("cfg4_shared_10m_zipf", cfg4)

    if want(5):
        def cfg5():
            n, nt, bs, cs, nr = ((200_000, 4_096, 2048, 64, 50_000) if reduced
                                 else (10_000_000, 16_384, 8192, 64, 1_000_000))
            filters = gen_mixed(rng, n, shared_frac=0.05)
            topics = gen_topics_zipf(rng, nt)
            retained = list({_tree_topic(rng, rng.randint(3, 6)) for _ in range(nr)})
            return run_config("cfg5_retained_10m", filters, topics, bs, cs,
                              retained=retained)

        guarded("cfg5_retained_10m", cfg5)

    if want(6):
        def cfg6():
            return run_cache_config("cfg6_cache_zipf", rng, reduced)

        guarded("cfg6_cache_zipf", cfg6)

    if want(7):
        def cfg7():
            return run_telemetry_config("cfg7_telemetry_overhead", rng, reduced)

        guarded("cfg7_telemetry_overhead", cfg7)

    if want(8):
        def cfg8():
            return run_overload_config("cfg8_overload_soak", rng, reduced)

        guarded("cfg8_overload_soak", cfg8)

    if want(9):
        def cfg9():
            return run_churn_config("cfg9_churn_soak", rng, reduced)

        guarded("cfg9_churn_soak", cfg9)

    if want(10):
        def cfg10():
            return run_failover_config("cfg10_failover_soak", rng, reduced)

        guarded("cfg10_failover_soak", cfg10)

    if want(11):
        def cfg11():
            return run_smallbatch_config("cfg11_smallbatch_paired", rng,
                                         reduced)

        guarded("cfg11_smallbatch_paired", cfg11)

    if want(12):
        def cfg12():
            return run_devprof_overhead_config("cfg12_devprof_overhead", rng,
                                               reduced)

        guarded("cfg12_devprof_overhead", cfg12)

    if want(13):
        def cfg13():
            return run_fabric_config("cfg13_fabric_paired", rng, reduced)

        guarded("cfg13_fabric_paired", cfg13)

    if want(14):
        def cfg14():
            return run_hostprof_overhead_config("cfg14_hostprof_overhead",
                                                rng, reduced)

        guarded("cfg14_hostprof_overhead", cfg14)

    if want(15):
        def cfg15():
            return run_autotune_config("cfg15_autotune_paired", rng, reduced)

        guarded("cfg15_autotune_paired", cfg15)

    if want(16):
        def cfg16():
            return run_egress_config("cfg16_egress_paired", rng, reduced)

        guarded("cfg16_egress_paired", cfg16)

    if want(17):
        def cfg17():
            return run_history_overhead_config("cfg17_history_overhead",
                                               rng, reduced)

        guarded("cfg17_history_overhead", cfg17)

    if want(18):
        def cfg18():
            return run_hotkeys_overhead_config("cfg18_sketch_overhead",
                                               rng, reduced)

        guarded("cfg18_sketch_overhead", cfg18)

    # cfg6/cfg7/cfg8 have their own shapes (on/off comparisons, no tpu/cpu
    # variants): they ride the artifact under "route_cache" /
    # "telemetry_overhead" / "overload_soak" instead of the configs table
    cache_res = results.pop("cfg6_cache_zipf", None)
    tele_res = results.pop("cfg7_telemetry_overhead", None)
    overload_res = results.pop("cfg8_overload_soak", None)
    churn_res = results.pop("cfg9_churn_soak", None)
    failover_res = results.pop("cfg10_failover_soak", None)
    smallbatch_res = results.pop("cfg11_smallbatch_paired", None)
    devprof_res = results.pop("cfg12_devprof_overhead", None)
    fabric_res = results.pop("cfg13_fabric_paired", None)
    hostprof_res = results.pop("cfg14_hostprof_overhead", None)
    autotune_res = results.pop("cfg15_autotune_paired", None)
    egress_res = results.pop("cfg16_egress_paired", None)
    history_res = results.pop("cfg17_history_overhead", None)
    hotkeys_res = results.pop("cfg18_sketch_overhead", None)
    if (not results and hotkeys_res is not None and history_res is None
            and egress_res is None and autotune_res is None
            and hostprof_res is None and fabric_res is None
            and devprof_res is None and smallbatch_res is None
            and failover_res is None and churn_res is None
            and overload_res is None and tele_res is None
            and cache_res is None):
        # a --config 18 run: its own artifact shape; the >2% bound FAILS
        # the run (exit 1) so CI can gate on the hot-key sketch cost
        print(json.dumps({
            "metric": "hotkeys_overhead_pct[cfg18_sketch_overhead]",
            "value": hotkeys_res["overhead_pct"],
            "unit": "pct_vs_off",
            "vs_baseline": hotkeys_res["overhead_pct"],
            "ok": hotkeys_res["ok"],
            "samples_recorded": hotkeys_res["samples_recorded"],
            "platform": platform,
            "hotkeys_overhead": hotkeys_res,
            **({"failed_configs": failures} if failures else {}),
        }))
        if not hotkeys_res["ok"]:
            sys.exit(1)
        return
    if (not results and history_res is not None and egress_res is None
            and autotune_res is None and hostprof_res is None
            and fabric_res is None and devprof_res is None
            and smallbatch_res is None and failover_res is None
            and churn_res is None and overload_res is None
            and tele_res is None and cache_res is None
            and hotkeys_res is None):
        # a --config 17 run: its own artifact shape; the >2% bound FAILS
        # the run (exit 1) so CI can gate on the history-collector cost
        print(json.dumps({
            "metric": "history_overhead_pct[cfg17_history_overhead]",
            "value": history_res["overhead_pct"],
            "unit": "pct_vs_off",
            "vs_baseline": history_res["overhead_pct"],
            "ok": history_res["ok"],
            "samples_recorded": history_res["samples_recorded"],
            "platform": platform,
            "history_overhead": history_res,
            **({"failed_configs": failures} if failures else {}),
        }))
        if not history_res["ok"]:
            sys.exit(1)
        return
    if (not results and egress_res is not None and autotune_res is None
            and hostprof_res is None and fabric_res is None
            and devprof_res is None and smallbatch_res is None
            and failover_res is None and churn_res is None
            and overload_res is None and tele_res is None
            and cache_res is None and history_res is None
            and hotkeys_res is None):
        # a --config 16 run: its own artifact shape; the ≥5x send-syscall
        # reduction AND ≥1.25x goodput bounds FAIL the run (exit 1) so CI
        # can gate on the coalesced data plane
        print(json.dumps({
            "metric": "egress_syscall_reduction[cfg16_egress_paired]",
            "value": egress_res["syscall_reduction_x"],
            "unit": "x_fewer_send_calls_per_msg",
            "vs_baseline": egress_res["syscall_reduction_x"],
            "ok": egress_res["ok"],
            "goodput_ratio": egress_res["goodput_ratio"],
            "syscalls_per_msg_coalesced":
                egress_res["syscalls_per_msg_coalesced"],
            "platform": platform,
            "egress_paired": egress_res,
            **({"failed_configs": failures} if failures else {}),
        }))
        if not egress_res["ok"]:
            sys.exit(1)
        return
    if (not results and autotune_res is not None and hostprof_res is None
            and fabric_res is None and devprof_res is None
            and smallbatch_res is None and failover_res is None
            and churn_res is None and overload_res is None
            and tele_res is None and cache_res is None
            and egress_res is None
            and history_res is None
            and hotkeys_res is None):
        # a --config 15 run: its own artifact shape; the ≥1.15x
        # autotune-over-static bound (plus ≥1 adaptation and 0 unrecovered
        # rollbacks) FAILS the run (exit 1) so CI can gate on it
        print(json.dumps({
            "metric": "autotune_pair_ratio[cfg15_autotune_paired]",
            "value": autotune_res["pair_ratio"],
            "unit": "x_autotune_over_static",
            "vs_baseline": autotune_res["pair_ratio"],
            "ok": autotune_res["ok"],
            "adaptations": autotune_res["adaptations"],
            "unrecovered_rollbacks": autotune_res["unrecovered_rollbacks"],
            "platform": platform,
            "autotune_paired": autotune_res,
            **({"failed_configs": failures} if failures else {}),
        }))
        if not autotune_res["ok"]:
            sys.exit(1)
        return
    if (not results and hostprof_res is not None and fabric_res is None
            and devprof_res is None and smallbatch_res is None
            and failover_res is None and churn_res is None
            and overload_res is None and tele_res is None
            and cache_res is None and egress_res is None
            and history_res is None
            and hotkeys_res is None):
        # a --config 14 run: its own artifact shape; the >2% bound FAILS
        # the run (exit 1) so CI can gate on the host-profiler cost
        print(json.dumps({
            "metric": "hostprof_overhead_pct[cfg14_hostprof_overhead]",
            "value": hostprof_res["overhead_pct"],
            "unit": "pct_vs_off",
            "vs_baseline": hostprof_res["overhead_pct"],
            "ok": hostprof_res["ok"],
            "platform": platform,
            "hostprof_overhead": hostprof_res,
            **({"failed_configs": failures} if failures else {}),
        }))
        if not hostprof_res["ok"]:
            sys.exit(1)
        return
    if (not results and fabric_res is not None and devprof_res is None
            and smallbatch_res is None and failover_res is None
            and churn_res is None and overload_res is None
            and tele_res is None and cache_res is None
            and hostprof_res is None and egress_res is None
            and history_res is None
            and hotkeys_res is None):
        # a --config 13 run: its own artifact shape; the ≥3× cross-worker
        # fan-out bound FAILS the run (exit 1) so CI can gate on it
        print(json.dumps({
            "metric": "fanout_goodput_ratio[cfg13_fabric_paired]",
            "value": fabric_res["fanout_goodput_ratio"],
            "unit": "x_fabric_over_broadcast",
            "vs_baseline": fabric_res["fanout_goodput_ratio"],
            "ok": fabric_res["ok"],
            "connect_kick_ms": fabric_res["connect_kick_ms"],
            "platform": platform,
            "fabric_paired": fabric_res,
            **({"failed_configs": failures} if failures else {}),
        }))
        if not fabric_res["ok"]:
            sys.exit(1)
        return
    # every bench JSON carries the device-plane profiler snapshot + the
    # tail of the flight ring (satellite of the devprof PR: on-chip runs
    # become diagnosable from the artifact alone)
    devprof_embed = {"devprof": {**DEVPROF.snapshot(),
                                 "flight": DEVPROF.flight()[-16:]}}
    if (not results and devprof_res is not None and smallbatch_res is None
            and failover_res is None and churn_res is None
            and overload_res is None and tele_res is None
            and cache_res is None and egress_res is None
            and history_res is None
            and hotkeys_res is None):
        # a --config 12 run: its own artifact shape; the >2% bound FAILS
        # the run (exit 1) so CI and the chip hunter can gate on it
        print(json.dumps({
            "metric": "devprof_overhead_pct[cfg12_devprof_overhead]",
            "value": devprof_res["overhead_pct"],
            "unit": "pct_vs_off",
            "vs_baseline": devprof_res["overhead_pct"],
            "ok": devprof_res["ok"],
            "platform": platform,
            "devprof_overhead": devprof_res,
            **devprof_embed,
            **({"failed_configs": failures} if failures else {}),
        }))
        if not devprof_res["ok"]:
            sys.exit(1)
        return
    if (not results and smallbatch_res is not None and failover_res is None
            and churn_res is None and overload_res is None
            and tele_res is None and cache_res is None
            and egress_res is None
            and history_res is None
            and hotkeys_res is None):
        # a --config 11 run (chip hunter window): its own artifact shape
        print(json.dumps({
            "metric": "smallbatch_fused_pair_ratio[cfg11_smallbatch_paired]",
            "value": smallbatch_res["median_pair_ratio"],
            "unit": "x_fused_over_unfused",
            "vs_baseline": smallbatch_res["median_pair_ratio"],
            "decode_share_unfused": smallbatch_res["decode_share_unfused"],
            "decode_share_fused": smallbatch_res["decode_share_fused"],
            "platform": platform,
            "smallbatch_paired": smallbatch_res,
            **({"failed_configs": failures} if failures else {}),
        }))
        return
    if (not results and failover_res is not None and churn_res is None
            and overload_res is None and tele_res is None
            and cache_res is None and egress_res is None
            and history_res is None
            and hotkeys_res is None):
        sb = failover_res["time_to_switchback_s"]
        no_sb = sb is None
        if no_sb:
            # the soak gives up soak_s+20s in (see run_failover_config);
            # emit that observation bound instead of null so numeric
            # consumers (regression gates, plots) see a finite worst case
            # in exactly the failure this metric exists to catch
            sb = round(failover_res["soak_s"] + 20.0
                       - failover_res["fault_window_s"][1], 2)
        print(json.dumps({
            "metric": "failover_switchback_s[cfg10_failover_soak]",
            "value": sb,
            "unit": "seconds_to_switchback",
            "vs_baseline": sb,
            **({"no_switchback": True} if no_sb else {}),
            "lost": failover_res["lost"],
            "failover_p99_ms": failover_res["failover_p99_ms"],
            "steady_p99_ms": failover_res["steady_p99_ms"],
            "platform": platform,
            "failover_soak": failover_res,
            **({"failed_configs": failures} if failures else {}),
        }))
        return
    if (not results and churn_res is not None and overload_res is None
            and tele_res is None and cache_res is None
            and egress_res is None
            and history_res is None
            and hotkeys_res is None):
        print(json.dumps({
            "metric": "delta_upload_reduction[cfg9_churn_soak]",
            "value": churn_res["delta_reduction_x"],
            "unit": "x_vs_full_refresh",
            "vs_baseline": churn_res["delta_reduction_x"],
            "upload_bytes_per_mutation": churn_res["upload_bytes_per_mutation"],
            "p99_churn_over_free": churn_res["p99_churn_over_free"],
            "median_pair_ratio": churn_res["median_pair_ratio"],
            "platform": platform,
            "churn_soak": churn_res,
            **({"failover_soak": failover_res} if failover_res else {}),
            **({"failed_configs": failures} if failures else {}),
        }))
        return
    if (not results and overload_res is not None and tele_res is None
            and cache_res is None and egress_res is None
            and history_res is None
            and hotkeys_res is None):
        print(json.dumps({
            "metric": "overload_p99_bound[cfg8_overload_soak]",
            "value": overload_res["p99_ratio_off_over_on"],
            "unit": "x_off_over_on",
            "vs_baseline": overload_res["p99_ratio_off_over_on"],
            "platform": platform,
            "overload_soak": overload_res,
            **({"churn_soak": churn_res} if churn_res else {}),
            **({"failover_soak": failover_res} if failover_res else {}),
            **({"failed_configs": failures} if failures else {}),
        }))
        return
    if (not results and tele_res is not None and cache_res is None
            and egress_res is None
            and history_res is None
            and hotkeys_res is None):
        print(json.dumps({
            "metric": "telemetry_overhead_pct[cfg7_telemetry_overhead]",
            "value": tele_res["overhead_pct"],
            "unit": "pct_vs_off",
            "vs_baseline": tele_res["overhead_pct"],
            "platform": platform,
            "latency_ms": tele_res["latency_ms"],
            "telemetry_overhead": tele_res,
            **({"overload_soak": overload_res} if overload_res else {}),
            **({"churn_soak": churn_res} if churn_res else {}),
            **({"failed_configs": failures} if failures else {}),
        }))
        return
    if (not results and cache_res is not None and egress_res is None
            and history_res is None
            and hotkeys_res is None):
        print(json.dumps({
            "metric": "route_cache_speedup[cfg6_cache_zipf]",
            "value": cache_res["zipf"]["speedup_cached"],
            "unit": "x_vs_uncached",
            "vs_baseline": cache_res["zipf"]["speedup_cached"],
            "hit_rate": cache_res["zipf"]["cached"].get("hit_rate"),
            "platform": platform,
            "route_cache": cache_res,
            **({"telemetry_overhead": tele_res} if tele_res else {}),
            **({"overload_soak": overload_res} if overload_res else {}),
            **({"churn_soak": churn_res} if churn_res else {}),
            **({"failed_configs": failures} if failures else {}),
        }))
        return

    if devprof_res is not None and not devprof_res["ok"]:
        # surfaced as a failed config in the merged artifact; a standalone
        # --config 12 run (the CI gate) exits nonzero above
        failures["cfg12_devprof_overhead"] = (
            f"profiler overhead {devprof_res['overhead_pct']}% > "
            f"{devprof_res['bound_pct']}% bound")
    if hostprof_res is not None and not hostprof_res["ok"]:
        # same contract for the host-plane profiler (cfg14)
        failures["cfg14_hostprof_overhead"] = (
            f"host profiler overhead {hostprof_res['overhead_pct']}% > "
            f"{hostprof_res['bound_pct']}% bound")
    if history_res is not None and not history_res["ok"]:
        # same contract for the telemetry-history collector (cfg17)
        failures["cfg17_history_overhead"] = (
            f"history collector overhead {history_res['overhead_pct']}% > "
            f"{history_res['bound_pct']}% bound")
    if hotkeys_res is not None and not hotkeys_res["ok"]:
        # same contract for the hot-key attribution plane (cfg18)
        failures["cfg18_sketch_overhead"] = (
            f"hot-key sketch overhead {hotkeys_res['overhead_pct']}% > "
            f"{hotkeys_res['bound_pct']}% bound")

    # headline = the largest routing config that ran
    if not results:
        print(
            json.dumps(
                {
                    "metric": "publish_route_topics_per_sec",
                    "value": 0,
                    "unit": "topics/s",
                    "vs_baseline": 0,
                    "platform": platform,
                    "error": failures or "no config ran",
                }
            )
        )
        sys.exit(1)
    for headline in ["cfg4_shared_10m_zipf", "cfg5_retained_10m", "cfg3_mixed_1m", "cfg2_plus_100k", "cfg1_exact_1k"]:
        if headline in results:
            break
    r = results[headline]
    # the headline is the ROUTER-LEVEL (hybrid) number when measured — the
    # throughput a broker user gets from the deployed XlaRouter; the raw
    # device figure rides alongside in every config entry
    head = r.get("router") or r["tpu"]
    head_speedup = r.get("router_speedup") or r["speedup"]
    # reduced-size fallback numbers must not masquerade as full-config
    # results: the metric name and every config entry carry the marker
    tag = "@reduced" if reduced else ""
    out = {
        "metric": f"publish_route_topics_per_sec[{headline}{tag}]",
        "value": round(head["topics_per_sec"], 1),
        "unit": "topics/s",
        "vs_baseline": round(head_speedup, 2),
        "routes_per_sec": round(head["routes_per_sec"], 1),
        "p99_ms": round(head["p99_ms"], 2),
        "level": "router_hybrid" if r.get("router") else "device_raw",
        "platform": platform,
        "baseline": r["baseline_kind"],
        "configs": {
            k: {
                "tpu_topics_per_sec": round(v["tpu"]["topics_per_sec"], 1),
                "tpu_backend": v["tpu_backend"],
                "cpu_topics_per_sec": round(v["cpu"]["topics_per_sec"], 1),
                "cpu_native_topics_per_sec": (
                    round(v["cpu_native"]["topics_per_sec"], 1) if v["cpu_native"] else None
                ),
                "speedup": round(v["speedup"], 2),
                "p99_ms": round(v["tpu"]["p99_ms"], 2),
                **({
                    "router_topics_per_sec": round(v["router"]["topics_per_sec"], 1),
                    "router_speedup": round(v["router_speedup"], 2),
                    "router_choice": v["router"].get("hybrid_choice"),
                    "router_p99_1topic_ms": round(
                        v["router"].get("p99_1topic_ms", 0.0), 3),
                } if v.get("router") else {}),
                **({"stream": v["stream"]} if "stream" in v else {}),
                **({"retained": v["retained"]} if "retained" in v else {}),
                **({"roofline_model": v["roofline_model"]}
                   if "roofline_model" in v else {}),
                **({"reduced_sizes": True} if reduced else {}),
            }
            for k, v in results.items()
        },
        **({"route_cache": cache_res} if cache_res is not None else {}),
        # latency trajectory: p50/p99 for match + publish e2e (cfg7's
        # enabled run) so BENCH rounds track tails, not just throughput
        **({"telemetry_overhead": tele_res,
            "latency_ms": tele_res["latency_ms"]} if tele_res is not None else {}),
        # overload soak (cfg8): bounded-backlog + bounded-p99 evidence for
        # the overload controller, on vs off (broker/overload.py)
        **({"overload_soak": overload_res} if overload_res is not None else {}),
        # churn soak (cfg9): delta-upload traffic + p99-under-churn evidence
        # for the churn-resilient device table (ops/partitioned.py)
        **({"churn_soak": churn_res} if churn_res is not None else {}),
        # failover soak (cfg10): goodput dip + time-to-switchback evidence
        # for the device-plane failover (broker/failover.py)
        **({"failover_soak": failover_res} if failover_res is not None else {}),
        # small-batch paired estimator (cfg11): per-stage attribution of
        # the cfg1 regime, fused vs unfused (ops/partitioned.py)
        **({"smallbatch_paired": smallbatch_res}
           if smallbatch_res is not None else {}),
        # device-profiler overhead bound (cfg12): enabled-vs-disabled cost
        # of the [observability] device_profile knob (broker/devprof.py)
        **({"devprof_overhead": devprof_res}
           if devprof_res is not None else {}),
        # host-profiler overhead bound (cfg14): armed-vs-disarmed cost of
        # the [observability] host_profile knob (broker/hostprof.py)
        **({"hostprof_overhead": hostprof_res}
           if hostprof_res is not None else {}),
        # intra-node fabric paired estimator (cfg13): cross-worker fan-out
        # goodput fabric-vs-broadcast + per-leg CONNECT kick p99
        # (broker/fabric.py)
        **({"fabric_paired": fabric_res} if fabric_res is not None else {}),
        # autotune paired estimator (cfg15): autotune-vs-static goodput/p99
        # over the shifting-regime workload + the decision timeline
        # (broker/autotune.py)
        **({"autotune_paired": autotune_res}
           if autotune_res is not None else {}),
        # coalesced-egress paired estimator (cfg16): send-syscalls per
        # delivered message + fan-out goodput, coalesced vs legacy
        # per-frame writes (broker/egress.py)
        **({"egress_paired": egress_res} if egress_res is not None else {}),
        # history-collector overhead bound (cfg17): armed-vs-stopped cost
        # of the [observability] history knob at 100× production cadence
        # (broker/history.py)
        **({"history_overhead": history_res}
           if history_res is not None else {}),
        # hot-key sketch overhead bound (cfg18): armed-vs-disarmed cost
        # of the [observability] hotkeys knob at 60× production rotation
        # cadence (broker/hotkeys.py)
        **({"hotkeys_overhead": hotkeys_res}
           if hotkeys_res is not None else {}),
        **devprof_embed,
        **({"failed_configs": failures} if failures else {}),
        **({"reduced_sizes": True} if reduced else {}),
    }
    # gate persistence on the RESOLVED platform, not just the probe: a
    # probe false-positive that still lands on CPU devices must not
    # clobber the last real on-chip snapshot with CPU numbers
    _persist_last_tpu(out, on_tpu and platform == "tpu")
    print(json.dumps(out))


_LAST_TPU_PATH = __file__.replace("bench.py", "BENCH_LAST_TPU.json")


def _persist_last_tpu(out: dict, on_tpu: bool) -> None:
    """Real-chip results persist across runs: a later wedged-chip driver run
    still carries the last on-chip numbers (clearly labeled as prior-run)
    instead of emitting a near-zero-information CPU artifact (round 2 lost
    its real progress to exactly this)."""
    import os

    if os.environ.get("RMQTT_BENCH_NO_PERSIST") == "1":
        # A/B legs (chip_hunter phase 2) run deliberately-degraded configs
        # (RMQTT_FUSED=0 / RMQTT_PACKED=0): their numbers must never merge
        # into the standing last-on-chip snapshot
        return
    try:
        if on_tpu:
            snap = {k: out[k] for k in
                    ("metric", "value", "unit", "vs_baseline", "configs") if k in out}
            # MERGE with any prior on-chip configs (round-5 chip hunter runs
            # one config per process; a --config 4 run must not clobber the
            # cfg1-3 results a previous window captured)
            try:
                with open(_LAST_TPU_PATH) as f:
                    prior = json.load(f).get("configs") or {}
                merged = dict(prior)
                merged.update(snap.get("configs") or {})
                snap["configs"] = merged
            except (FileNotFoundError, json.JSONDecodeError):
                pass
            snap["recorded_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
            if "failed_configs" in out:
                snap["failed_configs"] = out["failed_configs"]
            with open(_LAST_TPU_PATH, "w") as f:
                json.dump(snap, f, indent=1)
        else:
            with open(_LAST_TPU_PATH) as f:
                out["last_tpu_run"] = json.load(f)
            out["last_tpu_run"]["note"] = (
                "prior-run on-chip results (this run fell back to CPU)"
            )
    except FileNotFoundError:
        pass
    except Exception as e:  # the artifact must print regardless
        log(f"last-tpu persistence skipped: {e}")


if __name__ == "__main__":
    main()
