"""Subprocess TPU-availability probe.

The axon TPU grant can be wedged by a dead client, in which case any
in-process ``jax.devices()`` blocks forever inside PJRT client init
(NOTES.md). The default backend must therefore never be touched until
availability is confirmed from the outside: probe in a throwaway
subprocess, which can be timed out safely. This is the single home of
that pattern — ``bench.py`` and ``__graft_entry__`` both use it.
"""

from __future__ import annotations

import subprocess
import sys
import time


def probe_device_count(timeout: float = 60.0, retries: int = 1,
                       retry_sleep: float = 15.0) -> int:
    """Count real devices via a throwaway subprocess; 0 if unreachable."""
    for attempt in range(retries):
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
                timeout=timeout,
                capture_output=True,
                text=True,
            )
            if r.returncode == 0:
                return int(r.stdout.strip().splitlines()[-1])
        except (subprocess.TimeoutExpired, ValueError, IndexError):
            pass
        if attempt + 1 < retries:
            time.sleep(retry_sleep)
    return 0


def tpu_available(timeout: float = 60.0, retries: int = 2) -> bool:
    return probe_device_count(timeout=timeout, retries=retries) > 0
