"""Subprocess TPU-availability probe.

The axon TPU grant can be wedged by a dead client, in which case any
in-process ``jax.devices()`` blocks forever inside PJRT client init
(NOTES.md). The default backend must therefore never be touched until
availability is confirmed from the outside: probe in a throwaway
subprocess, which can be timed out safely. This is the single home of
that pattern — ``bench.py`` and ``__graft_entry__`` both use it.
"""

from __future__ import annotations

import subprocess
import sys
import time


def probe_device_count(timeout: float = 60.0, retries: int = 1,
                       retry_sleep: float = 15.0) -> int:
    """Count real devices via a throwaway subprocess; 0 if unreachable."""
    for attempt in range(retries):
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
                timeout=timeout,
                capture_output=True,
                text=True,
            )
            if r.returncode == 0:
                return int(r.stdout.strip().splitlines()[-1])
        except (subprocess.TimeoutExpired, ValueError, IndexError):
            pass
        if attempt + 1 < retries:
            time.sleep(retry_sleep)
    return 0


def tpu_available(timeout: float = 60.0, retries: int = 2) -> bool:
    return probe_device_count(timeout=timeout, retries=retries) > 0


_ensured: str | None = None


def ensure_safe_platform(probe_timeout: float = 60.0) -> str:
    """Guard jax-using components against an unreachable accelerator.

    Two failure modes on this class of host (NOTES.md):
    - a sitecustomize preload force-selects the accelerator platform and
      OVERRIDES the ``JAX_PLATFORMS`` env var, so ``JAX_PLATFORMS=cpu`` is
      silently ignored;
    - the accelerator grant can be wedged, making the first backend touch
      block forever.

    Policy (memoized per process, must run before the first backend touch):
    if cpu was explicitly requested (env or jax config), re-apply it; else
    probe the default backend in a subprocess and force cpu when
    unreachable. Returns the platform that will be used.
    """
    global _ensured
    if _ensured is not None:
        return _ensured
    import os

    import jax

    def _force_cpu() -> None:
        # a preload may have registered (not initialised) the accelerator
        # platform already; clear backends or the platform switch is a no-op
        from jax.extend import backend as _eb

        _eb.clear_backends()
        jax.config.update("jax_platforms", "cpu")

    cfg = (jax.config.jax_platforms or "").split(",")[0]
    env = os.environ.get("JAX_PLATFORMS", "").split(",")[0]
    if "cpu" in (cfg, env):
        if cfg != "cpu":
            _force_cpu()
        _ensured = "cpu"
    elif probe_device_count(timeout=probe_timeout) == 0:
        import logging

        logging.getLogger("rmqtt_tpu").warning(
            "accelerator backend unreachable (subprocess probe timed out); "
            "forcing jax_platforms=cpu"
        )
        _force_cpu()
        _ensured = "cpu"
    else:
        _ensured = cfg or "default"
    return _ensured
