"""Process resource probes.

One definition of "resident set size" shared by the overload sampler
(`broker/overload.py`), the admin gauges (`ServerContext.stats()`,
`http_api.sysinfo`), and the bench/scenario runners (`rmqtt_tpu/bench`,
`scripts/soak_bench.py`, ...) — previously each carried its own
/proc-parsing copy with subtly different fallbacks.
"""

from __future__ import annotations

from typing import Optional


def rss_mb(pid: Optional[int] = None) -> float:
    """Resident set of ``pid`` (default: this process) in MB.

    Reads ``/proc/<pid>/status`` VmRSS; returns 0.0 where /proc is
    unavailable (non-Linux) or the process is gone — callers treat 0.0 as
    "no signal", never as "no memory"."""
    path = f"/proc/{pid}/status" if pid else "/proc/self/status"
    try:
        with open(path) as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0
