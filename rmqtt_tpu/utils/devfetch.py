"""Timeout-guarded device→host fetches.

On this hardware class the accelerator grant can wedge mid-run (NOTES.md):
a ``np.asarray`` of a device array then blocks forever inside PJRT, taking
the whole process with it — round 2's cfg5 bench died exactly there, losing
every result already measured. When ``RMQTT_FETCH_TIMEOUT`` (seconds) is
set, fetches run on a daemon worker thread and raise ``TimeoutError``
instead of hanging, so callers (bench ``guarded()``, the routing service)
can record the failure and continue/exit. Unset (the default, e.g. broker
production paths on a healthy chip) it is a plain ``np.asarray`` — no
thread, no overhead.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

import numpy as np

_timeout: Optional[float] = None
_loaded = False


def fetch_timeout() -> Optional[float]:
    global _timeout, _loaded
    if not _loaded:
        raw = os.environ.get("RMQTT_FETCH_TIMEOUT", "")
        _timeout = float(raw) if raw else None
        _loaded = True
    return _timeout


def set_fetch_timeout(seconds: Optional[float]) -> None:
    global _timeout, _loaded
    _timeout = seconds
    _loaded = True


def fetch(arr, what: str = "device fetch") -> np.ndarray:
    """``np.asarray(arr)`` with the configured wedge guard."""
    t = fetch_timeout()
    if t is None:
        return np.asarray(arr)
    box: dict = {}

    def run() -> None:
        try:
            box["v"] = np.asarray(arr)
        except BaseException as e:  # surfaced on the caller thread
            box["e"] = e

    th = threading.Thread(target=run, daemon=True, name="devfetch")
    th.start()
    th.join(t)
    if "v" in box:
        return box["v"]
    if "e" in box:
        raise box["e"]
    # the worker stays parked on the wedged fetch; daemon=True means it
    # cannot block process exit
    raise TimeoutError(f"{what} exceeded {t:.0f}s (wedged accelerator?)")
