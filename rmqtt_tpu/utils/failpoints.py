"""Failpoint fault-injection registry: named sites, runtime-togglable faults.

Chaos testing a broker needs faults that can be *provoked*, not waited for:
an XLA dispatch error, a hung kernel completion, a flaky sqlite lock, a
dropped peer RPC. Each such seam registers a **failpoint** — a named site
whose behavior is ``off`` in production and can be flipped at runtime to
inject a fault (the classic failpoints/fail-rs pattern; TiKV and sled ship
the same discipline). The catalog of sites is fixed and documented (README
"Failure domains & failover"; a test diffs it against this registry).

Action grammar (one spec string per site)::

    off                      no effect (the default)
    error                    raise FailpointError at the site
    error(message)           ... with a custom message
    delay(ms)                sleep that many milliseconds, then continue
    hang                     block until the site is reconfigured (a "hung
                             device" that heals when the operator flips the
                             point off — never an unkillable thread)
    prob(p, action)          fire `action` with probability p, else off
    times(n, action)         fire `action` for the next n evaluations, off after

Configuration surfaces, lowest to highest:

- ``[failpoints]`` conf section (``"device.dispatch" = "error"``) applied by
  ``ServerContext`` from ``BrokerConfig.failpoints``;
- ``RMQTT_FAILPOINTS`` env string (``site=spec;site=spec``), applied at
  import so even non-broker harnesses (bench, scripts) honor it;
- ``PUT /api/v1/failpoints`` (broker/http_api.py) for live chaos drills.

Hot-path discipline (the PR4 ``enable=false`` rule): a site holds a direct
reference to its ``Failpoint`` and guards with ``if fp.action is not None``
— one attribute load + ``is`` test when every point is off, pinned by
tests/test_failpoints.py. ``fire_sync``/``fire_async`` are only entered
when an action is armed.
"""

from __future__ import annotations

import asyncio
import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "FAILPOINTS", "Failpoint", "FailpointError", "FailpointRegistry", "SITES",
]


class FailpointError(RuntimeError):
    """The injected error (``error`` action). Sites that already classify
    transport faults treat it like any other failure of that seam."""


#: the documented site catalog: name → where it fires (README parity is
#: test-enforced, so adding a site here requires documenting it there)
SITES: List[Tuple[str, str]] = [
    ("device.dispatch", "XlaRouter batch submit (kernel dispatch / host encode)"),
    ("device.complete", "XlaRouter batch completion (device fetch / decode)"),
    ("device.upload", "device-table HBM refresh (delta scatter or full pack+put)"),
    ("storage.write", "sqlite/redis store mutations (put/delete/bulk)"),
    ("storage.read", "sqlite/redis store reads (get/scan/count)"),
    ("storage.fsync", "durability journal group commit (the batched fsync "
                      "window; error = commit retried, hang = acks park)"),
    ("storage.torn_write", "durability journal append (truncates the last "
                           "record mid-write and wedges the journal — "
                           "recovery must drop the torn tail by CRC)"),
    ("cluster.forward", "cross-node publish forwarding (broadcast + raft)"),
    ("cluster.rpc", "every cluster frame, both directions (partition: "
                    "outbound fails fast, inbound is blackholed)"),
    ("fabric.submit", "intra-node fabric publish submission to the router "
                      "owner (failure degrades to worker-local match)"),
    ("bridge.egress", "bridge producer sends (kafka/pulsar/nats egress pumps)"),
    ("net.egress", "per-connection coalesced egress flush (the vectored "
                   "write; error = connection drops, its read loop reaps it)"),
    ("history.collect", "telemetry-history sample collection (delay = a "
                        "provokable latency step on the history.collect_ms "
                        "series for anomaly drills)"),
    ("hotkeys.rotate", "hot-key sketch epoch rotation (a provokable "
                       "rotation stall/fault — the previous epoch keeps "
                       "serving while the rotator misbehaves)"),
]


class _Action:
    """One parsed action node (``prob``/``times`` wrap an inner node)."""

    __slots__ = ("kind", "message", "delay_s", "p", "n", "inner")

    def __init__(self, kind: str, message: str = "", delay_s: float = 0.0,
                 p: float = 0.0, n: int = 0, inner: "Optional[_Action]" = None):
        self.kind = kind
        self.message = message
        self.delay_s = delay_s
        self.p = p
        self.n = n
        self.inner = inner


def _parse_action(spec: str) -> Optional[_Action]:
    """Spec string → action tree (None = off). Raises ValueError on typos —
    a chaos drill must fail loudly at configure time, not silently no-op."""
    s = spec.strip()
    if not s or s == "off":
        return None
    if s == "error":
        return _Action("error")
    if s == "hang":
        return _Action("hang")
    if "(" in s and s.endswith(")"):
        head, _, body = s.partition("(")
        head = head.strip()
        body = body[:-1]
        if head == "error":
            return _Action("error", message=body.strip())
        if head == "delay":
            ms = float(body)
            if ms < 0:
                raise ValueError(f"delay(ms) must be >= 0, got {spec!r}")
            return _Action("delay", delay_s=ms / 1000.0)
        if head in ("prob", "times"):
            arg, _, inner_s = body.partition(",")
            if not inner_s.strip():
                raise ValueError(f"{head}(x, action) needs an inner action: {spec!r}")
            inner = _parse_action(inner_s)
            if inner is None:
                raise ValueError(f"{head}(..., off) is meaningless: {spec!r}")
            if inner.kind in ("prob", "times"):
                raise ValueError(f"{head} cannot nest {inner.kind}: {spec!r}")
            if head == "prob":
                p = float(arg)
                if not 0.0 <= p <= 1.0:
                    raise ValueError(f"prob p must be in [0,1]: {spec!r}")
                return _Action("prob", p=p, inner=inner)
            n = int(arg)
            if n <= 0:
                raise ValueError(f"times n must be > 0: {spec!r}")
            return _Action("times", n=n, inner=inner)
    raise ValueError(
        f"bad failpoint spec {spec!r} (off | error[(msg)] | delay(ms) | "
        f"hang | prob(p, action) | times(n, action))"
    )


class Failpoint:
    """One named injection site.

    ``action`` is ``None`` when off — the ONLY hot-path state. Everything
    else (trigger counters, the times-remaining budget) lives behind the
    armed check and a small lock."""

    __slots__ = ("name", "help", "spec", "action", "triggers", "evaluations",
                 "_times_left", "_lock", "_rng")

    def __init__(self, name: str, help: str = "",
                 rng: Optional[random.Random] = None) -> None:
        self.name = name
        self.help = help
        self.spec = "off"
        self.action: Optional[_Action] = None
        self.triggers = 0  # times a fault actually fired
        self.evaluations = 0  # armed-site passes (incl. prob misses)
        self._times_left = 0
        self._lock = threading.Lock()
        self._rng = rng if rng is not None else random

    # ------------------------------------------------------------ configure
    def set(self, spec: str) -> None:
        act = _parse_action(spec)
        with self._lock:
            self.spec = spec.strip() or "off"
            self._times_left = act.n if act is not None and act.kind == "times" else 0
            # publish the action LAST: a concurrent fire sees a consistent
            # (spec, budget) once it observes the new action
            self.action = act

    def clear(self) -> None:
        self.set("off")

    # --------------------------------------------------------------- firing
    def _resolve(self) -> Optional[_Action]:
        """One evaluation under the armed check: unwrap prob/times to the
        concrete action to run now (None = this pass does nothing)."""
        act = self.action
        if act is None:
            return None
        with self._lock:
            self.evaluations += 1
            if act.kind == "times":
                if self._times_left <= 0:
                    return None
                self._times_left -= 1
                act = act.inner
            elif act.kind == "prob":
                if self._rng.random() >= act.p:
                    return None
                act = act.inner
            self.triggers += 1
            return act

    def _raise(self, act: _Action) -> None:
        raise FailpointError(
            act.message or f"failpoint {self.name!r}: injected error")

    def fire_sync(self) -> None:
        """Blocking form (executor threads, storage backends). Callers guard
        with ``if fp.action is not None`` so this is never on the off path."""
        act = self._resolve()
        if act is None:
            return
        if act.kind == "error":
            self._raise(act)
        elif act.kind == "delay":
            time.sleep(act.delay_s)
        elif act.kind == "hang":
            marker = self.action  # hang until the site is reconfigured
            while self.action is marker:
                time.sleep(0.02)

    async def fire_async(self) -> None:
        """Event-loop form: identical semantics, cooperative sleeps."""
        act = self._resolve()
        if act is None:
            return
        if act.kind == "error":
            self._raise(act)
        elif act.kind == "delay":
            await asyncio.sleep(act.delay_s)
        elif act.kind == "hang":
            marker = self.action
            while self.action is marker:
                await asyncio.sleep(0.02)

    def snapshot(self) -> dict:
        with self._lock:
            out = {"action": self.spec, "triggers": self.triggers,
                   "evaluations": self.evaluations}
            if self.action is not None and self.action.kind == "times":
                out["times_left"] = self._times_left
            return out


async def fire_async_as(fp: Failpoint, exc_type=ConnectionError) -> None:
    """Fire an armed failpoint, translating an injected FailpointError into
    ``exc_type`` so the site's existing transient-fault handling (breaker,
    reconnect, retry) treats it exactly like the real fault it models."""
    try:
        await fp.fire_async()
    except FailpointError as e:
        raise exc_type(str(e)) from e


def fire_sync_as(fp: Failpoint, exc_type=ConnectionError) -> None:
    """Sync sibling of :func:`fire_async_as` — same translation contract
    (message text, ``__cause__`` chain) for synchronous store surfaces.
    Includes the armed check, so call sites stay one attribute test when
    every point is off."""
    if fp.action is not None:
        try:
            fp.fire_sync()
        except FailpointError as e:
            raise exc_type(str(e)) from e


class FailpointRegistry:
    """Process-global site registry (one per process, like the metrics
    registry): sites self-register at import, chaos surfaces configure."""

    def __init__(self, rng: Optional[random.Random] = None) -> None:
        self._points: Dict[str, Failpoint] = {}
        self._rng = rng
        for name, help_ in SITES:
            self.register(name, help_)

    def register(self, name: str, help: str = "") -> Failpoint:
        """Idempotent: the catalog pre-registers every standard site, so
        module-level ``register`` calls just fetch the shared instance
        (tests may register extra throwaway sites)."""
        fp = self._points.get(name)
        if fp is None:
            fp = self._points[name] = Failpoint(name, help, rng=self._rng)
        return fp

    def point(self, name: str) -> Failpoint:
        fp = self._points.get(name)
        if fp is None:
            raise ValueError(
                f"unknown failpoint {name!r} (catalog: {sorted(self._points)})")
        return fp

    def set(self, name: str, spec: str) -> None:
        self.point(name).set(spec)

    def clear_all(self) -> None:
        for fp in self._points.values():
            fp.clear()

    def configure(self, mapping: Dict[str, str]) -> None:
        """Apply a conf-section dict (``[failpoints]``); unknown names and
        bad specs raise, so typos fail at load. All-or-nothing: every name
        and spec is validated BEFORE any site is armed, so a 400 on the
        HTTP surface (or a load-time typo) never leaves a half-applied
        request live on a production broker."""
        parsed = [(self.point(name), str(spec)) for name, spec in mapping.items()]
        for _fp, spec in parsed:
            _parse_action(spec)
        for fp, spec in parsed:
            fp.set(spec)

    def configure_env(self, env: str) -> None:
        """``RMQTT_FAILPOINTS="a=error;b=delay(5)"`` (';'-separated);
        validated as one batch like :meth:`configure`."""
        mapping: Dict[str, str] = {}
        for part in env.split(";"):
            part = part.strip()
            if not part:
                continue
            name, eq, spec = part.partition("=")
            if not eq:
                raise ValueError(f"RMQTT_FAILPOINTS entry needs site=spec: {part!r}")
            mapping[name.strip()] = spec
        self.configure(mapping)

    def names(self) -> List[str]:
        return sorted(self._points)

    def snapshot(self) -> Dict[str, dict]:
        return {name: fp.snapshot() for name, fp in sorted(self._points.items())}

    def armed(self) -> Dict[str, str]:
        return {name: fp.spec for name, fp in sorted(self._points.items())
                if fp.action is not None}


#: the process-wide registry; sites bind their Failpoint once at import
FAILPOINTS = FailpointRegistry()

# env-string configuration at import: bench/scripts/chaos harnesses honor
# RMQTT_FAILPOINTS without any broker config plumbing
_env = os.environ.get("RMQTT_FAILPOINTS", "")
if _env:
    FAILPOINTS.configure_env(_env)
