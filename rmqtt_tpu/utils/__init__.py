"""Utilities: counters with cluster merge modes, sliding-window rates.

Mirrors `rmqtt-utils` (`/root/reference/rmqtt-utils/src/counter.rs:39-343`,
`src/rate_counter.rs`): ``Counter`` tracks (current, max) and merges across
cluster nodes under a ``StatsMergeMode``; ``RateCounter`` measures events/sec
over a sliding window.
"""

from rmqtt_tpu.utils.counter import Counter, RateCounter, StatsMergeMode
