"""Counter / RateCounter (reference rmqtt-utils equivalents)."""

from __future__ import annotations

import enum
import time
from collections import deque
from typing import Deque, Tuple


class StatsMergeMode(enum.Enum):
    """How a gauge merges across cluster nodes (counter.rs StatsMergeMode)."""

    NONE = "none"
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    AVG = "avg"


class Counter:
    """(current, max) pair; max tracks the high-water mark (counter.rs:39)."""

    __slots__ = ("current", "max")

    def __init__(self, current: int = 0, max_: int = 0) -> None:
        self.current = current
        self.max = max(max_, current)

    def inc(self, n: int = 1) -> int:
        self.current += n
        if self.current > self.max:
            self.max = self.current
        return self.current

    def dec(self, n: int = 1) -> int:
        self.current -= n
        return self.current

    def sets(self, v: int) -> None:
        self.current = v
        if v > self.max:
            self.max = v

    def merge(self, other: "Counter", mode: StatsMergeMode) -> "Counter":
        """Cluster merge (counter.rs merge modes)."""
        if mode is StatsMergeMode.SUM:
            return Counter(self.current + other.current, self.max + other.max)
        if mode is StatsMergeMode.MAX:
            return Counter(max(self.current, other.current), max(self.max, other.max))
        if mode is StatsMergeMode.MIN:
            return Counter(min(self.current, other.current), min(self.max, other.max))
        if mode is StatsMergeMode.AVG:
            return Counter((self.current + other.current) // 2, (self.max + other.max) // 2)
        return Counter(self.current, self.max)

    def to_json(self) -> dict:
        return {"count": self.current, "max": self.max}


class RateCounter:
    """Sliding-window events/sec (rate_counter.rs)."""

    def __init__(self, window: float = 5.0) -> None:
        self.window = window
        self._events: Deque[Tuple[float, int]] = deque()
        self._total = 0

    def inc(self, n: int = 1) -> None:
        self._trim()  # keep the window bounded even if rate() is never read
        self._events.append((time.monotonic(), n))
        self._total += n

    def _trim(self) -> None:
        cutoff = time.monotonic() - self.window
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    def rate(self) -> float:
        self._trim()
        return sum(n for _, n in self._events) / self.window

    def total(self) -> int:
        return self._total
