"""Minimal ECDSA (NIST P-256/384/521) for JWT ES* verification.

The reference's rmqtt-auth-jwt accepts ES-family tokens; this image has no
asymmetric-crypto library, so verification is implemented directly: affine
short-Weierstrass point arithmetic over the NIST primes with stdlib big
ints (``pow(x, -1, p)`` modular inverse). Verification-only in the broker;
``sign`` exists for the test suite (round-trip + tamper vectors) — it uses
RFC-6979-style deterministic nonces derived with HMAC so tests never need
an RNG. One verify is a handful of milliseconds in CPython — fine for the
once-per-CONNECT auth path, not a bulk-data primitive.

Curve constants are validated by tests/test_plugins2.py
(test_ec_curve_constants_and_roundtrip): G must satisfy the curve equation
and n·G must be the point at infinity; ES256 additionally verifies a token
signed independently by openssl.
"""

from __future__ import annotations

import hashlib
import hmac
from typing import NamedTuple, Optional, Tuple


class Curve(NamedTuple):
    p: int  # field prime
    b: int  # y^2 = x^3 - 3x + b
    n: int  # group order
    gx: int
    gy: int
    size: int  # byte length of a coordinate / signature half


P256 = Curve(
    p=0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF,
    b=0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B,
    n=0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551,
    gx=0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296,
    gy=0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5,
    size=32,
)

P384 = Curve(
    p=int(
        "fffffffffffffffffffffffffffffffffffffffffffffffffffffffffffffffe"
        "ffffffff0000000000000000ffffffff", 16
    ),
    b=int(
        "b3312fa7e23ee7e4988e056be3f82d19181d9c6efe8141120314088f5013875a"
        "c656398d8a2ed19d2a85c8edd3ec2aef", 16
    ),
    n=int(
        "ffffffffffffffffffffffffffffffffffffffffffffffffc7634d81f4372ddf"
        "581a0db248b0a77aecec196accc52973", 16
    ),
    gx=int(
        "aa87ca22be8b05378eb1c71ef320ad746e1d3b628ba79b9859f741e082542a38"
        "5502f25dbf55296c3a545e3872760ab7", 16
    ),
    gy=int(
        "3617de4a96262c6f5d9e98bf9292dc29f8f41dbd289a147ce9da3113b5f0b8c0"
        "0a60b1ce1d7e819d7a431d7c90ea0e5f", 16
    ),
    size=48,
)

P521 = Curve(
    p=(1 << 521) - 1,
    b=int(
        "0051953eb9618e1c9a1f929a21a0b68540eea2da725b99b315f3b8b489918ef1"
        "09e156193951ec7e937b1652c0bd3bb1bf073573df883d2c34f1ef451fd46b50"
        "3f00", 16
    ),
    n=int(
        "01ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff"
        "fffa51868783bf2f966b7fcc0148f709a5d03bb5c9b8899c47aebb6fb71e9138"
        "6409", 16
    ),
    gx=int(
        "00c6858e06b70404e9cd9e3ecb662395b4429c648139053fb521f828af606b4d"
        "3dbaa14b5e77efe75928fe1dc127a2ffa8de3348b3c1856a429bf97e7e31c2e5"
        "bd66", 16
    ),
    gy=int(
        "011839296a789a3bc0045c8a5fb42c7d1bd998f54449579b446817afbd17273e"
        "662c97ee72995ef42640c550b9013fad0761353c7086a272c24088be94769fd1"
        "6650", 16
    ),
    size=66,
)

CURVES = {"ES256": P256, "ES384": P384, "ES512": P521}
HASHES = {"ES256": hashlib.sha256, "ES384": hashlib.sha384, "ES512": hashlib.sha512}

# the point at infinity
_INF: Optional[Tuple[int, int]] = None


def on_curve(c: Curve, pt) -> bool:
    if pt is _INF:
        return True
    x, y = pt
    return (y * y - (x * x * x - 3 * x + c.b)) % c.p == 0


def _add(c: Curve, p1, p2):
    if p1 is _INF:
        return p2
    if p2 is _INF:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % c.p == 0:
            return _INF
        # doubling: s = (3x^2 - 3) / 2y
        s = (3 * x1 * x1 - 3) * pow(2 * y1, -1, c.p) % c.p
    else:
        s = (y2 - y1) * pow(x2 - x1, -1, c.p) % c.p
    x3 = (s * s - x1 - x2) % c.p
    return x3, (s * (x1 - x3) - y1) % c.p


def _mul(c: Curve, k: int, pt):
    acc = _INF
    while k:
        if k & 1:
            acc = _add(c, acc, pt)
        pt = _add(c, pt, pt)
        k >>= 1
    return acc


def _hash_to_int(c: Curve, h: bytes) -> int:
    e = int.from_bytes(h, "big")
    extra = len(h) * 8 - c.n.bit_length()
    return e >> extra if extra > 0 else e


def verify(alg: str, signed: bytes, sig: bytes, pub: Tuple[int, int]) -> bool:
    """JWT ES* verify: ``sig`` is the raw r||s concatenation."""
    c = CURVES.get(alg)
    if c is None or len(sig) != 2 * c.size:
        return False
    r = int.from_bytes(sig[: c.size], "big")
    s = int.from_bytes(sig[c.size :], "big")
    if not (0 < r < c.n and 0 < s < c.n) or not on_curve(c, pub):
        return False
    e = _hash_to_int(c, HASHES[alg](signed).digest())
    w = pow(s, -1, c.n)
    u1 = e * w % c.n
    u2 = r * w % c.n
    pt = _add(c, _mul(c, u1, (c.gx, c.gy)), _mul(c, u2, pub))
    if pt is _INF:
        return False
    return pt[0] % c.n == r


def sign(alg: str, signed: bytes, priv: int) -> bytes:
    """Deterministic-nonce ECDSA sign (tests only; HMAC-derived k)."""
    c = CURVES[alg]
    e = _hash_to_int(c, HASHES[alg](signed).digest())
    kseed = hmac.new(priv.to_bytes(c.size, "big"),
                     HASHES[alg](signed).digest(), hashlib.sha512).digest()
    k = int.from_bytes(kseed * ((2 * c.size) // len(kseed) + 1), "big") % c.n
    while True:
        k = k or 1
        x, _y = _mul(c, k, (c.gx, c.gy))
        r = x % c.n
        s = pow(k, -1, c.n) * (e + r * priv) % c.n
        if r and s:
            return r.to_bytes(c.size, "big") + s.to_bytes(c.size, "big")
        k = (k + 1) % c.n


def public_key(alg: str, priv: int) -> Tuple[int, int]:
    c = CURVES[alg]
    pt = _mul(c, priv, (c.gx, c.gy))
    assert pt is not _INF
    return pt
