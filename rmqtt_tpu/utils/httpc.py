"""Minimal raw asyncio HTTP client (shared by web-hook, auth-http and the
ReductStore bridge — one copy of the connect/TLS/status/body skeleton).

No external deps; Connection: close per request (plugin traffic volumes
don't need pooling). Malformed/empty responses raise ``ConnectionError``
(an OSError subclass) so every caller's network-error handling covers
them; header NAMES are caller-controlled constants, header VALUES are
sanitized against CR/LF injection (MQTT topics may legally contain them).
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional, Tuple
from urllib.parse import urlparse


def _clean(value: str) -> str:
    """Header values must not break the request framing."""
    return value.replace("\r", " ").replace("\n", " ")


async def request(
    url: str,
    method: str = "GET",
    path: Optional[str] = None,
    body: bytes = b"",
    headers: Optional[Dict[str, str]] = None,
    timeout: float = 5.0,
    read_body: bool = False,
) -> Tuple[int, bytes]:
    """→ (status, response_body if read_body else b"").

    ``url`` carries scheme/host/port (and the default path+query);
    ``path`` overrides the target when given."""
    u = urlparse(url)
    port = u.port or (443 if u.scheme == "https" else 80)
    if u.scheme == "https":
        import ssl

        sslctx = ssl.create_default_context()
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(u.hostname, port, ssl=sslctx), timeout
        )
    else:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(u.hostname, port), timeout
        )
    try:
        if path is None:
            path = u.path or "/"
            if u.query:
                path += "?" + u.query
        head = [
            f"{method} {path} HTTP/1.1",
            f"Host: {u.hostname}",
            f"Content-Length: {len(body)}",
            "Connection: close",
        ]
        for k, v in (headers or {}).items():
            head.append(f"{k}: {_clean(str(v))}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + body)
        await writer.drain()
        status_line = await asyncio.wait_for(reader.readline(), timeout)
        parts = status_line.split()
        if len(parts) < 2 or not parts[1].isdigit():
            raise ConnectionError(f"bad http status line {status_line!r}")
        status = int(parts[1])
        if not read_body:
            return status, b""
        length = 0
        while True:
            line = await asyncio.wait_for(reader.readline(), timeout)
            if line in (b"\r\n", b""):
                break
            k, _, v = line.decode("latin1").partition(":")
            if k.strip().lower() == "content-length":
                length = int(v)
        payload = await asyncio.wait_for(reader.readexactly(length), timeout) if length else b""
        return status, payload
    finally:
        try:
            writer.close()
        except Exception:
            pass
