"""Asyncio MQTT client (bridge-grade: reconnect, QoS1, callbacks).

The client half the bridge plugins need (the reference bridges embed their
own client sessions, `rmqtt-plugins/rmqtt-bridge-ingress-mqtt`): CONNECT/
SUBSCRIBE/PUBLISH over the shared wire codec, exponential-backoff reconnect
with resubscribe, inbound publish callback, QoS0/1 outbound (QoS1 acked).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

from rmqtt_tpu.broker.codec import MqttCodec, packets as pk
from rmqtt_tpu.broker.codec.packets import SubOpts

log = logging.getLogger("rmqtt_tpu.bridge")

OnPublish = Callable[[pk.Publish], Awaitable[None]]


class MqttClient:
    def __init__(
        self,
        host: str,
        port: int,
        client_id: str,
        on_publish: Optional[OnPublish] = None,
        version: int = pk.V311,
        keepalive: int = 30,
        username: Optional[str] = None,
        password: Optional[bytes] = None,
        reconnect_min: float = 0.5,
        reconnect_max: float = 30.0,
    ) -> None:
        self.host = host
        self.port = port
        self.client_id = client_id
        self.on_publish = on_publish
        self.version = version
        self.keepalive = keepalive
        self.username = username
        self.password = password
        self.reconnect_min = reconnect_min
        self.reconnect_max = reconnect_max
        self.connected = asyncio.Event()
        self._writer: Optional[asyncio.StreamWriter] = None
        self._codec = MqttCodec(version)
        self._subs: Dict[str, int] = {}  # filter → qos (for resubscribe)
        self._pid = itertools.cycle(range(1, 65536))
        self._acks: Dict[int, asyncio.Future] = {}
        self._task: Optional[asyncio.Task] = None
        self._stopping = False

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass

    # ---------------------------------------------------------------- core
    async def _run(self) -> None:
        backoff = self.reconnect_min
        while not self._stopping:
            try:
                await self._session()
                backoff = self.reconnect_min
            except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                log.warning("bridge %s: connection lost (%s); retry in %.1fs",
                            self.client_id, e, backoff)
            self.connected.clear()
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, self.reconnect_max)

    async def _session(self) -> None:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), 10.0
        )
        self._writer = writer
        self._codec = MqttCodec(self.version)
        writer.write(
            self._codec.encode(
                pk.Connect(
                    client_id=self.client_id, protocol=self.version,
                    keepalive=self.keepalive, clean_start=True,
                    username=self.username, password=self.password,
                )
            )
        )
        await writer.drain()
        ping_task: Optional[asyncio.Task] = None
        try:
            while True:
                data = await asyncio.wait_for(
                    reader.read(65536), timeout=max(self.keepalive * 2, 10)
                )
                if not data:
                    raise ConnectionError("closed by remote")
                for p in self._codec.feed(data):
                    if isinstance(p, pk.Connack):
                        if p.reason_code != 0:
                            raise ConnectionError(f"connack rc={p.reason_code}")
                        self.connected.set()
                        if self.keepalive and ping_task is None:
                            ping_task = asyncio.create_task(self._ping_loop())
                        await self._resubscribe()
                    elif isinstance(p, pk.Publish):
                        if p.qos == 1:
                            await self._send(pk.Puback(p.packet_id))
                        elif p.qos == 2:
                            await self._send(pk.Pubrec(p.packet_id))
                        if self.on_publish is not None:
                            await self.on_publish(p)
                    elif isinstance(p, pk.Pubrel):
                        await self._send(pk.Pubcomp(p.packet_id))
                    elif isinstance(p, (pk.Puback, pk.Pubcomp)):
                        fut = self._acks.pop(p.packet_id, None)
                        if fut is not None and not fut.done():
                            fut.set_result(p)
                    elif isinstance(p, pk.Pubrec):
                        await self._send(pk.Pubrel(p.packet_id))
                    elif isinstance(p, pk.Suback):
                        fut = self._acks.pop(("sub", p.packet_id), None)  # type: ignore[arg-type]
                        if fut is not None and not fut.done():
                            fut.set_result(p)
        finally:
            if ping_task is not None:
                ping_task.cancel()
            for fut in self._acks.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("bridge session ended"))
            self._acks.clear()
            try:
                writer.close()
            except Exception:
                pass
            self._writer = None

    async def _ping_loop(self) -> None:
        while True:
            await asyncio.sleep(max(self.keepalive * 0.7, 1.0))
            await self._send(pk.Pingreq())

    async def _send(self, p) -> None:
        if self._writer is None:
            raise ConnectionError("not connected")
        self._writer.write(self._codec.encode(p))
        await self._writer.drain()

    async def _resubscribe(self) -> None:
        for tf, qos in self._subs.items():
            pid = next(self._pid)
            await self._send(pk.Subscribe(pid, [(tf, SubOpts(qos=qos))]))

    # ----------------------------------------------------------------- API
    async def subscribe(self, topic_filter: str, qos: int = 0) -> None:
        self._subs[topic_filter] = qos
        if self.connected.is_set():
            pid = next(self._pid)
            await self._send(pk.Subscribe(pid, [(topic_filter, SubOpts(qos=qos))]))

    async def publish(
        self, topic: str, payload: bytes, qos: int = 0, retain: bool = False,
        wait_ack: bool = True, timeout: float = 10.0,
    ) -> bool:
        if not self.connected.is_set():
            return False
        pid = next(self._pid) if qos else None
        # install the ack future BEFORE the send: drain() can suspend under
        # write backpressure, letting the read loop process the PUBACK first
        fut = None
        if qos and wait_ack:
            fut = asyncio.get_running_loop().create_future()
            self._acks[pid] = fut
        try:
            await self._send(
                pk.Publish(topic=topic, payload=payload, qos=qos, retain=retain, packet_id=pid)
            )
        except (ConnectionError, OSError):
            if fut is not None:
                self._acks.pop(pid, None)
            return False
        if fut is not None:
            try:
                await asyncio.wait_for(fut, timeout)
            except (asyncio.TimeoutError, ConnectionError):
                self._acks.pop(pid, None)
                return False
        return True
