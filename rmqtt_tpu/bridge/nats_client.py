"""Minimal asyncio NATS client (for the NATS bridge plugins).

The reference bridges to NATS via the async-nats crate
(`rmqtt-plugins/rmqtt-bridge-ingress-nats`). NATS speaks a simple text
protocol (INFO/CONNECT/SUB/PUB/MSG/PING/PONG, docs.nats.io), implemented
here directly: publish, queue-group subscribe, auto-reconnect with
resubscribe. Subject mapping MQTT↔NATS: ``/``↔``.``, ``+``↔``*``, ``#``↔``>``.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
from typing import Awaitable, Callable, Dict, Optional, Tuple

log = logging.getLogger("rmqtt_tpu.bridge.nats")

# on_message(subject, payload)
OnMessage = Callable[[str, bytes], Awaitable[None]]


def mqtt_to_nats_subject(topic: str) -> str:
    return topic.replace(".", "_").replace("/", ".")


def nats_to_mqtt_topic(subject: str) -> str:
    return subject.replace("/", "_").replace(".", "/")


def mqtt_filter_to_nats(topic_filter: str) -> str:
    out = []
    for lev in topic_filter.split("/"):
        if lev == "+":
            out.append("*")
        elif lev == "#":
            out.append(">")
        else:
            out.append(lev.replace(".", "_"))
    return ".".join(out)


class NatsClient:
    def __init__(
        self,
        host: str,
        port: int = 4222,
        on_message: Optional[OnMessage] = None,
        name: str = "rmqtt-bridge",
        reconnect_min: float = 0.5,
        reconnect_max: float = 30.0,
    ) -> None:
        self.host = host
        self.port = port
        self.on_message = on_message
        self.name = name
        self.reconnect_min = reconnect_min
        self.reconnect_max = reconnect_max
        self.connected = asyncio.Event()
        # server advertises header support (HPUB/HMSG) in its INFO line;
        # publishes with headers fall back to plain PUB when unsupported
        self._hdr_support = False
        self._writer: Optional[asyncio.StreamWriter] = None
        self._subs: Dict[int, Tuple[str, Optional[str]]] = {}  # sid → (subject, queue)
        self._sid = itertools.count(1)
        self._task: Optional[asyncio.Task] = None
        self._stopping = False

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass

    async def _run(self) -> None:
        backoff = self.reconnect_min
        while not self._stopping:
            try:
                await self._session()
                backoff = self.reconnect_min
            except (ConnectionError, OSError, asyncio.TimeoutError, ValueError) as e:
                log.warning("nats bridge: connection lost (%s); retry in %.1fs", e, backoff)
            self.connected.clear()
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, self.reconnect_max)

    async def _session(self) -> None:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), 10.0
        )
        self._writer = writer
        try:
            info = await asyncio.wait_for(reader.readline(), 10.0)
            if not info.startswith(b"INFO"):
                raise ValueError(f"unexpected NATS greeting: {info[:40]!r}")
            try:
                self._hdr_support = bool(json.loads(info[4:]).get("headers"))
            except (ValueError, AttributeError):
                self._hdr_support = False
            opts = {"verbose": False, "pedantic": False, "name": self.name,
                    "lang": "python", "version": "0.1", "protocol": 0}
            if self._hdr_support:
                opts["headers"] = True  # opt in so the server accepts HPUB
            writer.write(b"CONNECT " + json.dumps(opts).encode() + b"\r\n")
            await writer.drain()
            self.connected.set()
            # resubscribe
            for sid, (subject, queue) in self._subs.items():
                q = f" {queue}" if queue else ""
                writer.write(f"SUB {subject}{q} {sid}\r\n".encode())
            await writer.drain()
            while True:
                line = await reader.readline()
                if not line:
                    raise ConnectionError("nats closed")
                if line.startswith(b"MSG"):
                    parts = line.decode().split()
                    # MSG <subject> <sid> [reply-to] <#bytes>
                    subject = parts[1]
                    nbytes = int(parts[-1])
                    payload = await reader.readexactly(nbytes)
                    await reader.readexactly(2)  # trailing \r\n
                    if self.on_message is not None:
                        await self.on_message(subject, payload)
                elif line.startswith(b"PING"):
                    writer.write(b"PONG\r\n")
                    await writer.drain()
                elif line.startswith(b"-ERR"):
                    log.warning("nats error: %s", line.decode().strip())
        finally:
            self.connected.clear()
            try:
                writer.close()
            except Exception:
                pass
            self._writer = None

    async def subscribe(self, subject: str, queue: Optional[str] = None) -> int:
        sid = next(self._sid)
        self._subs[sid] = (subject, queue)
        if self.connected.is_set() and self._writer is not None:
            q = f" {queue}" if queue else ""
            self._writer.write(f"SUB {subject}{q} {sid}\r\n".encode())
            await self._writer.drain()
        return sid

    async def publish(self, subject: str, payload: bytes,
                      headers: Optional[list] = None) -> bool:
        """``headers`` is ``[(key, value), ...]``; sent as an HPUB header
        block when the server supports headers, silently dropped (plain
        PUB) when it doesn't — delivery beats metadata."""
        if not self.connected.is_set() or self._writer is None:
            return False
        if headers and self._hdr_support:
            hdr = b"NATS/1.0\r\n" + b"".join(
                f"{k}: {v}\r\n".encode() for k, v in headers) + b"\r\n"
            self._writer.write(
                f"HPUB {subject} {len(hdr)} {len(hdr) + len(payload)}\r\n".encode()
                + hdr + payload + b"\r\n")
        else:
            self._writer.write(
                f"PUB {subject} {len(payload)}\r\n".encode() + payload + b"\r\n")
        await self._writer.drain()
        return True
