"""Bridging: connect this broker to external MQTT brokers.

Mirrors the reference's bridge plugin family (SURVEY.md §2.3:
bridge-ingress-mqtt / bridge-egress-mqtt and the kafka/pulsar/nats
equivalents). The MQTT bridges are built on `bridge.client.MqttClient`,
an asyncio client over the same wire codec with auto-reconnect.
"""
