"""Dependency-free Apache Pulsar wire-protocol client (asyncio).

The reference's Pulsar bridges sit on the `pulsar` crate; no Pulsar stack
ships in this image, so this implements the protocol subset a bridge needs
directly over the public binary protocol (pulsar.apache.org/docs/developing
-binary-protocol): frames are ``[totalSize][commandSize][BaseCommand]``
with SEND/MESSAGE adding ``[0x0e01][crc32c][metadataSize][MessageMetadata]
[payload]``. Commands are protobuf messages — encoded/decoded here with a
minimal hand-rolled protobuf layer (varint + length-delimited fields only),
field numbers per PulsarApi.proto.

Scope notes (vs the crate the reference uses): connects straight to the
configured broker (no topic-lookup redirection — correct for standalone /
single-broker deployments), no batching, no compression, subscription
types Exclusive/Shared/Failover/KeyShared.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

from rmqtt_tpu.bridge.kafka_client import crc32c  # same Castagnoli table

log = logging.getLogger("rmqtt_tpu.bridge.pulsar")

# BaseCommand.Type values / field numbers (PulsarApi.proto: the submessage
# field number equals these for every command used here)
CONNECT = 2
CONNECTED = 3
SUBSCRIBE = 4
PRODUCER = 5
SEND = 6
SEND_RECEIPT = 7
SEND_ERROR = 8
MESSAGE = 9
ACK = 10
FLOW = 11
SUCCESS = 13
ERROR = 14
PRODUCER_SUCCESS = 17
PING = 18
PONG = 19

SUB_TYPES = {"exclusive": 0, "shared": 1, "failover": 2, "key_shared": 3}
POS_LATEST, POS_EARLIEST = 0, 1

MAGIC = b"\x0e\x01"
PROTOCOL_VERSION = 6  # baseline features only


# ------------------------------------------------------- minimal protobuf
def _uvarint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def pb_varint(out: bytearray, field: int, v: int) -> None:
    _uvarint(out, (field << 3) | 0)
    _uvarint(out, v)


def pb_bytes(out: bytearray, field: int, data: bytes) -> None:
    _uvarint(out, (field << 3) | 2)
    _uvarint(out, len(data))
    out += data


def pb_str(out: bytearray, field: int, s: str) -> None:
    pb_bytes(out, field, s.encode())


def pb_decode(buf: bytes) -> Dict[int, list]:
    """Generic decode → {field: [values]} (varint ints, bytes for len-delim)."""
    out: Dict[int, list] = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key = 0
        shift = 0
        while True:
            b = buf[pos]
            pos += 1
            key |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        field, wire = key >> 3, key & 0x7
        if wire == 0:
            v = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                v |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            out.setdefault(field, []).append(v)
        elif wire == 2:
            ln = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            out.setdefault(field, []).append(bytes(buf[pos : pos + ln]))
            pos += ln
        elif wire == 5:
            pos += 4
        elif wire == 1:
            pos += 8
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
    return out


def base_command(ctype: int, sub: bytes = b"") -> bytes:
    out = bytearray()
    pb_varint(out, 1, ctype)
    # ALWAYS emit the submessage field (even empty): the broker-side decoder
    # checks hasX() for the command's field — a bare PONG is rejected
    pb_bytes(out, ctype, sub)  # submessage field number == type value
    return bytes(out)


def message_metadata(producer_name: str, sequence_id: int,
                     properties: List[Tuple[str, str]] = (),
                     partition_key: Optional[str] = None) -> bytes:
    out = bytearray()
    pb_str(out, 1, producer_name)
    pb_varint(out, 2, sequence_id)
    pb_varint(out, 3, int(time.time() * 1000))
    for k, v in properties:
        kv = bytearray()
        pb_str(kv, 1, k)
        pb_str(kv, 2, v)
        pb_bytes(out, 4, bytes(kv))
    if partition_key is not None:
        pb_str(out, 6, partition_key)
    return bytes(out)


def frame_simple(cmd: bytes) -> bytes:
    return struct.pack(">II", 4 + len(cmd), len(cmd)) + cmd


def frame_payload(cmd: bytes, metadata: bytes, payload: bytes) -> bytes:
    tail = struct.pack(">I", len(metadata)) + metadata + payload
    crc = crc32c(tail)
    body = struct.pack(">I", len(cmd)) + cmd + MAGIC + struct.pack(">I", crc) + tail
    return struct.pack(">I", len(body)) + body


# ----------------------------------------------------------------- client
class PulsarClient:
    def __init__(self, host: str, port: int = 6650,
                 on_message: Optional[Callable[..., Awaitable[None]]] = None) -> None:
        self.host, self.port = host, port
        self.on_message = on_message  # async (consumer_id, msg_id_raw, props, payload)
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.connected = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._req_id = 0
        self._req_waiters: Dict[int, asyncio.Future] = {}  # request_id → fut
        self._send_waiters: Dict[Tuple[int, int], asyncio.Future] = {}
        self._producer_names: Dict[int, str] = {}

    def _next_request(self) -> Tuple[int, asyncio.Future]:
        self._req_id += 1
        fut = asyncio.get_running_loop().create_future()
        self._req_waiters[self._req_id] = fut
        return self._req_id, fut

    async def connect(self, timeout: float = 10.0) -> None:
        self.reader, self.writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), timeout
        )
        sub = bytearray()
        pb_str(sub, 1, "rmqtt-tpu-bridge")
        pb_varint(sub, 4, PROTOCOL_VERSION)
        await self._send(frame_simple(base_command(CONNECT, bytes(sub))))
        self._task = asyncio.get_running_loop().create_task(self._read_loop())
        await asyncio.wait_for(self.connected.wait(), timeout)

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:
                pass

    async def _send(self, data: bytes) -> None:
        self.writer.write(data)
        await self.writer.drain()

    # ------------------------------------------------------------ commands
    async def create_producer(self, topic: str, producer_id: int = 1,
                              timeout: float = 10.0) -> str:
        rid, fut = self._next_request()
        sub = bytearray()
        pb_str(sub, 1, topic)
        pb_varint(sub, 2, producer_id)
        pb_varint(sub, 3, rid)
        await self._send(frame_simple(base_command(PRODUCER, bytes(sub))))
        reply = await asyncio.wait_for(fut, timeout)
        name = reply.get(2, [b"producer"])[0].decode()
        self._producer_names[producer_id] = name
        return name

    async def send(self, producer_id: int, sequence_id: int, payload: bytes,
                   properties: List[Tuple[str, str]] = (),
                   partition_key: Optional[str] = None, timeout: float = 10.0) -> None:
        sub = bytearray()
        pb_varint(sub, 1, producer_id)
        pb_varint(sub, 2, sequence_id)
        meta = message_metadata(
            self._producer_names.get(producer_id, "producer"), sequence_id,
            properties, partition_key,
        )
        fut = asyncio.get_running_loop().create_future()
        self._send_waiters[(producer_id, sequence_id)] = fut
        try:
            await self._send(frame_payload(base_command(SEND, bytes(sub)), meta, payload))
            await asyncio.wait_for(fut, timeout)
        finally:
            self._send_waiters.pop((producer_id, sequence_id), None)

    async def subscribe(self, topic: str, subscription: str, consumer_id: int = 1,
                        sub_type: str = "shared", initial_position: str = "latest",
                        timeout: float = 10.0) -> None:
        rid, fut = self._next_request()
        sub = bytearray()
        pb_str(sub, 1, topic)
        pb_str(sub, 2, subscription)
        pb_varint(sub, 3, SUB_TYPES.get(sub_type, 1))
        pb_varint(sub, 4, consumer_id)
        pb_varint(sub, 5, rid)
        pb_varint(sub, 13, POS_EARLIEST if initial_position in ("earliest", "beginning") else POS_LATEST)
        await self._send(frame_simple(base_command(SUBSCRIBE, bytes(sub))))
        await asyncio.wait_for(fut, timeout)

    async def flow(self, consumer_id: int, permits: int = 1000) -> None:
        sub = bytearray()
        pb_varint(sub, 1, consumer_id)
        pb_varint(sub, 2, permits)
        await self._send(frame_simple(base_command(FLOW, bytes(sub))))

    async def ack(self, consumer_id: int, message_id_raw: bytes) -> None:
        sub = bytearray()
        pb_varint(sub, 1, consumer_id)
        pb_varint(sub, 2, 0)  # Individual
        pb_bytes(sub, 3, message_id_raw)
        await self._send(frame_simple(base_command(ACK, bytes(sub))))

    # ----------------------------------------------------------- read loop
    async def _read_loop(self) -> None:
        try:
            while True:
                head = await self.reader.readexactly(4)
                (total,) = struct.unpack(">I", head)
                body = await self.reader.readexactly(total)
                (csize,) = struct.unpack(">I", body[:4])
                cmd = pb_decode(body[4 : 4 + csize])
                ctype = cmd.get(1, [0])[0]
                rest = body[4 + csize :]
                await self._dispatch(ctype, cmd, rest)
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self.connected.clear()
            # fail fast: in-flight calls must not sit out their timeouts
            err = ConnectionError("pulsar connection lost")
            for fut in list(self._req_waiters.values()) + list(self._send_waiters.values()):
                if not fut.done():
                    fut.set_exception(err)
            self._req_waiters.clear()
            self._send_waiters.clear()

    async def _dispatch(self, ctype: int, cmd: Dict[int, list], rest: bytes) -> None:
        sub = pb_decode(cmd[ctype][0]) if ctype in cmd and cmd[ctype] else {}
        if ctype == CONNECTED:
            self.connected.set()
        elif ctype == PING:
            await self._send(frame_simple(base_command(PONG)))
        elif ctype in (PRODUCER_SUCCESS, SUCCESS):
            rid = sub.get(1, [0])[0]
            fut = self._req_waiters.pop(rid, None)
            if fut is not None and not fut.done():
                fut.set_result(sub)
        elif ctype == ERROR:
            rid = sub.get(1, [0])[0]
            fut = self._req_waiters.pop(rid, None)
            msg = sub.get(3, [b""])[0]
            if fut is not None and not fut.done():
                fut.set_exception(ConnectionError(f"pulsar error: {msg!r}"))
        elif ctype == SEND_RECEIPT:
            key = (sub.get(1, [0])[0], sub.get(2, [0])[0])
            fut = self._send_waiters.get(key)
            if fut is not None and not fut.done():
                fut.set_result(True)
        elif ctype == SEND_ERROR:
            key = (sub.get(1, [0])[0], sub.get(2, [0])[0])
            fut = self._send_waiters.get(key)
            if fut is not None and not fut.done():
                fut.set_exception(ConnectionError("pulsar send error"))
        elif ctype == MESSAGE:
            consumer_id = sub.get(1, [0])[0]
            msg_id_raw = sub.get(2, [b""])[0]
            if len(rest) >= 10 and rest[:2] == MAGIC:
                (msize,) = struct.unpack(">I", rest[6:10])
                meta = pb_decode(rest[10 : 10 + msize])
                payload = rest[10 + msize :]
            else:  # checksum-less variant: [metadataSize][metadata][payload]
                (msize,) = struct.unpack(">I", rest[:4])
                meta = pb_decode(rest[4 : 4 + msize])
                payload = rest[4 + msize :]
            props = []
            for kv in meta.get(4, []):
                d = pb_decode(kv)
                props.append((d.get(1, [b""])[0].decode(), d.get(2, [b""])[0].decode()))
            if self.on_message is not None:
                await self.on_message(consumer_id, msg_id_raw, props, payload)
