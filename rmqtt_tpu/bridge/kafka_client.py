"""Dependency-free Kafka wire-protocol client (asyncio).

The reference's Kafka bridges (`rmqtt-plugins/rmqtt-bridge-ingress-kafka`,
`-egress-kafka`) sit on rdkafka; no Kafka stack ships in this image, so this
is an independent implementation of the protocol subset a bridge needs
(kafka.apache.org/protocol, non-flexible message versions to keep the
encoding simple):

- Metadata v1 (key 3) — topic → partition leaders,
- Produce v3 (key 0) — RecordBatch (magic 2, CRC32C) publishing,
- Fetch v4 (key 1) — RecordBatch consumption,
- ListOffsets v1 (key 2) — earliest/latest offset resolution.

Like the reference bridge, partition assignment is explicit/manual (its
``start_partition``/``stop_partition`` config) — no consumer-group
coordination. One connection per broker node, requests serialized per
connection (bridge volumes don't need pipelining).
"""

from __future__ import annotations

import asyncio
import logging
import struct
from typing import Dict, List, Optional, Sequence, Tuple

log = logging.getLogger("rmqtt_tpu.bridge.kafka")

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3

EARLIEST = -2
LATEST = -1


class KafkaError(Exception):
    def __init__(self, code: int, where: str) -> None:
        super().__init__(f"kafka error {code} in {where}")
        self.code = code


# ------------------------------------------------------------------- crc32c
def _make_crc32c_table():
    poly = 0x82F63B78
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_CRC32C_TABLE = _make_crc32c_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# ------------------------------------------------------------------ varints
def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_varint(out: bytearray, n: int) -> None:
    n = _zigzag(n) & 0xFFFFFFFFFFFFFFFF
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = result = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return _unzigzag(result), pos
        shift += 7


# ----------------------------------------------------------- wire primitives
class Writer:
    def __init__(self) -> None:
        self.b = bytearray()

    def i8(self, v):
        self.b += struct.pack(">b", v)

    def i16(self, v):
        self.b += struct.pack(">h", v)

    def i32(self, v):
        self.b += struct.pack(">i", v)

    def i64(self, v):
        self.b += struct.pack(">q", v)

    def string(self, s: Optional[str]):
        if s is None:
            self.i16(-1)
        else:
            raw = s.encode()
            self.i16(len(raw))
            self.b += raw

    def bytes_(self, v: Optional[bytes]):
        if v is None:
            self.i32(-1)
        else:
            self.i32(len(v))
            self.b += v


class Reader:
    def __init__(self, buf: bytes) -> None:
        self.buf = buf
        self.pos = 0

    def _unpack(self, fmt, size):
        v = struct.unpack_from(fmt, self.buf, self.pos)[0]
        self.pos += size
        return v

    def i8(self):
        return self._unpack(">b", 1)

    def i16(self):
        return self._unpack(">h", 2)

    def i32(self):
        return self._unpack(">i", 4)

    def i64(self):
        return self._unpack(">q", 8)

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        v = self.buf[self.pos : self.pos + n].decode()
        self.pos += n
        return v

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        v = bytes(self.buf[self.pos : self.pos + n])
        self.pos += n
        return v


# --------------------------------------------------------------- recordbatch
def encode_record_batch(
    records: Sequence[Tuple[Optional[bytes], Optional[bytes], Sequence[Tuple[str, bytes]]]],
    first_timestamp_ms: int,
    base_offset: int = 0,
) -> bytes:
    """records: [(key, value, headers)] → one RecordBatch (magic 2).
    ``base_offset`` is 0 for produce (the broker assigns); a broker-side
    encoder (the test fake) passes the log position."""
    body = bytearray()
    recs = bytearray()
    for i, (key, value, headers) in enumerate(records):
        rec = bytearray()
        rec.append(0)  # attributes
        write_varint(rec, 0)  # timestampDelta
        write_varint(rec, i)  # offsetDelta
        if key is None:
            write_varint(rec, -1)
        else:
            write_varint(rec, len(key))
            rec += key
        if value is None:
            write_varint(rec, -1)
        else:
            write_varint(rec, len(value))
            rec += value
        write_varint(rec, len(headers))
        for hk, hv in headers:
            hkr = hk.encode()
            write_varint(rec, len(hkr))
            rec += hkr
            write_varint(rec, len(hv))
            rec += hv
        write_varint(recs, len(rec))
        recs += rec
    n = len(records)
    # fields covered by the CRC (attributes .. records)
    crc_body = bytearray()
    crc_body += struct.pack(">h", 0)  # attributes (no compression)
    crc_body += struct.pack(">i", n - 1)  # lastOffsetDelta
    crc_body += struct.pack(">q", first_timestamp_ms)
    crc_body += struct.pack(">q", first_timestamp_ms)
    crc_body += struct.pack(">q", -1)  # producerId
    crc_body += struct.pack(">h", -1)  # producerEpoch
    crc_body += struct.pack(">i", -1)  # baseSequence
    crc_body += struct.pack(">i", n)
    crc_body += recs
    body += struct.pack(">q", base_offset)
    batch_len = 4 + 1 + 4 + len(crc_body)  # leaderEpoch + magic + crc + rest
    body += struct.pack(">i", batch_len)
    body += struct.pack(">i", -1)  # partitionLeaderEpoch
    body += struct.pack(">b", 2)  # magic
    body += struct.pack(">I", crc32c(bytes(crc_body)))
    body += crc_body
    return bytes(body)


def decode_record_batches(buf: bytes):
    """→ [(offset, timestamp_ms, key, value, headers)] across all batches."""
    out = []
    pos = 0
    while pos + 17 <= len(buf):
        base_offset = struct.unpack_from(">q", buf, pos)[0]
        batch_len = struct.unpack_from(">i", buf, pos + 8)[0]
        if batch_len <= 0 or pos + 12 + batch_len > len(buf):
            break  # partial batch at the end of a fetch response
        magic = buf[pos + 16]
        if magic != 2:
            log.warning("skipping record batch with magic %s", magic)
            pos += 12 + batch_len
            continue
        p = pos + 12 + 4 + 1 + 4  # skip leaderEpoch, magic, crc
        # attributes(2) lastOffsetDelta(4) firstTs(8) maxTs(8) producerId(8)
        # producerEpoch(2) baseSequence(4) count(4) = 40 bytes to the records
        attributes = struct.unpack_from(">h", buf, p)[0]
        first_ts = struct.unpack_from(">q", buf, p + 6)[0]
        count = struct.unpack_from(">i", buf, p + 36)[0]
        p += 40
        if attributes & 0x07:
            log.warning("skipping compressed record batch (codec %s)", attributes & 0x07)
            pos += 12 + batch_len
            continue
        for _ in range(count):
            rec_len, p = read_varint(buf, p)
            rec_end = p + rec_len
            p += 1  # attributes
            ts_delta, p = read_varint(buf, p)
            off_delta, p = read_varint(buf, p)
            klen, p = read_varint(buf, p)
            key = bytes(buf[p : p + klen]) if klen >= 0 else None
            p += max(0, klen)
            vlen, p = read_varint(buf, p)
            value = bytes(buf[p : p + vlen]) if vlen >= 0 else None
            p += max(0, vlen)
            nh, p = read_varint(buf, p)
            headers = []
            for _h in range(nh):
                hklen, p = read_varint(buf, p)
                hk = buf[p : p + hklen].decode()
                p += hklen
                hvlen, p = read_varint(buf, p)
                hv = bytes(buf[p : p + hvlen]) if hvlen >= 0 else b""
                p += max(0, hvlen)
                headers.append((hk, hv))
            out.append((base_offset + off_delta, first_ts + ts_delta, key, value, headers))
            p = rec_end
        pos += 12 + batch_len
    return out


# ------------------------------------------------------------------- client
class _Conn:
    def __init__(self, host: str, port: int, client_id: str) -> None:
        self.host, self.port = host, port
        self.client_id = client_id
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self._corr = 0
        self._lock = asyncio.Lock()

    async def connect(self) -> None:
        self.reader, self.writer = await asyncio.open_connection(self.host, self.port)

    def close(self) -> None:
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:
                pass
        self.reader = self.writer = None

    async def call(self, api_key: int, api_version: int, body: bytes,
                   timeout: float = 30.0) -> Reader:
        async with self._lock:
            if self.writer is None:
                await self.connect()
            self._corr += 1
            corr = self._corr
            head = Writer()
            head.i16(api_key)
            head.i16(api_version)
            head.i32(corr)
            head.string(self.client_id)
            frame = bytes(head.b) + body
            self.writer.write(struct.pack(">i", len(frame)) + frame)
            await self.writer.drain()
            try:
                raw = await asyncio.wait_for(self.reader.readexactly(4), timeout)
                (size,) = struct.unpack(">i", raw)
                payload = await asyncio.wait_for(self.reader.readexactly(size), timeout)
            except (asyncio.TimeoutError, asyncio.IncompleteReadError):
                self.close()
                raise ConnectionError(f"kafka {self.host}:{self.port} request failed")
            r = Reader(payload)
            got_corr = r.i32()
            if got_corr != corr:
                self.close()
                raise ConnectionError(f"kafka correlation mismatch {got_corr} != {corr}")
            return r


class KafkaClient:
    """Bootstrap + per-leader connections + the 4 APIs a bridge needs."""

    def __init__(self, servers: str, client_id: str = "rmqtt-bridge") -> None:
        # "host1:9092,host2:9092" (reference Bridge.servers format)
        self.bootstrap: List[Tuple[str, int]] = []
        for part in servers.split(","):
            host, _, port = part.strip().rpartition(":")
            self.bootstrap.append((host or part.strip(), int(port or 9092)))
        self.client_id = client_id
        self._conns: Dict[Tuple[str, int], _Conn] = {}
        # topic → {partition: (host, port)}
        self._leaders: Dict[str, Dict[int, Tuple[str, int]]] = {}

    def _conn(self, addr: Tuple[str, int]) -> _Conn:
        c = self._conns.get(addr)
        if c is None:
            c = self._conns[addr] = _Conn(addr[0], addr[1], self.client_id)
        return c

    async def close(self) -> None:
        for c in self._conns.values():
            c.close()
        self._conns.clear()

    async def _bootstrap_call(self, api, ver, body) -> Reader:
        last: Optional[Exception] = None
        for addr in self.bootstrap:
            try:
                return await self._conn(addr).call(api, ver, body)
            except (ConnectionError, OSError) as e:
                last = e
        raise last if last is not None else ConnectionError("no kafka bootstrap servers")

    # ------------------------------------------------------------- metadata
    async def metadata(self, topics: Sequence[str]) -> Dict[str, Dict[int, Tuple[str, int]]]:
        w = Writer()
        w.i32(len(topics))
        for t in topics:
            w.string(t)
        r = await self._bootstrap_call(API_METADATA, 1, bytes(w.b))
        nodes: Dict[int, Tuple[str, int]] = {}
        for _ in range(r.i32()):
            node_id = r.i32()
            host = r.string()
            port = r.i32()
            r.string()  # rack
            nodes[node_id] = (host, port)
        r.i32()  # controller id
        for _ in range(r.i32()):
            terr = r.i16()
            name = r.string()
            r.i8()  # is_internal
            parts: Dict[int, Tuple[str, int]] = {}
            for _p in range(r.i32()):
                perr = r.i16()
                pid = r.i32()
                leader = r.i32()
                for _x in range(r.i32()):
                    r.i32()  # replicas
                for _x in range(r.i32()):
                    r.i32()  # isr
                if perr == 0 and leader in nodes:
                    parts[pid] = nodes[leader]
            if terr == 0:
                self._leaders[name] = parts
        return {t: self._leaders.get(t, {}) for t in topics}

    async def _leader(self, topic: str, partition: int) -> Tuple[str, int]:
        parts = self._leaders.get(topic)
        if not parts or partition not in parts:
            await self.metadata([topic])
            parts = self._leaders.get(topic) or {}
        if partition not in parts:
            raise KafkaError(3, f"no leader for {topic}[{partition}]")  # UNKNOWN_TOPIC
        return parts[partition]

    async def partitions(self, topic: str) -> List[int]:
        if topic not in self._leaders:
            await self.metadata([topic])
        return sorted(self._leaders.get(topic, {}))

    # -------------------------------------------------------------- produce
    async def produce(
        self, topic: str, value: bytes, key: Optional[bytes] = None,
        partition: int = 0, headers: Sequence[Tuple[str, bytes]] = (),
        timestamp_ms: int = 0, acks: int = -1,
    ) -> int:
        """→ assigned base offset."""
        batch = encode_record_batch([(key, value, headers)], timestamp_ms)
        w = Writer()
        w.string(None)  # transactional_id
        w.i16(acks)
        w.i32(30_000)  # timeout
        w.i32(1)  # one topic
        w.string(topic)
        w.i32(1)  # one partition
        w.i32(partition)
        w.bytes_(batch)
        addr = await self._leader(topic, partition)
        try:
            r = await self._conn(addr).call(API_PRODUCE, 3, bytes(w.b))
        except ConnectionError:
            self._leaders.pop(topic, None)  # leadership may have moved
            raise
        r.i32()  # topic count (1)
        r.string()
        r.i32()  # partition count (1)
        r.i32()  # partition
        err = r.i16()
        base_offset = r.i64()
        if err != 0:
            self._leaders.pop(topic, None)
            raise KafkaError(err, f"produce {topic}[{partition}]")
        return base_offset

    # ---------------------------------------------------------------- fetch
    async def fetch(
        self, topic: str, partition: int, offset: int,
        max_wait_ms: int = 500, min_bytes: int = 1, max_bytes: int = 1 << 20,
    ):
        """→ (records [(offset, ts, key, value, headers)], high_watermark)."""
        w = Writer()
        w.i32(-1)  # replica_id
        w.i32(max_wait_ms)
        w.i32(min_bytes)
        w.i32(max_bytes)
        w.i8(0)  # isolation: read uncommitted
        w.i32(1)
        w.string(topic)
        w.i32(1)
        w.i32(partition)
        w.i64(offset)
        w.i32(max_bytes)
        addr = await self._leader(topic, partition)
        r = await self._conn(addr).call(API_FETCH, 4, bytes(w.b))
        r.i32()  # throttle
        r.i32()  # topic count (1)
        r.string()
        r.i32()  # partition count (1)
        r.i32()  # partition
        err = r.i16()
        high_watermark = r.i64()
        r.i64()  # last stable offset
        for _ in range(r.i32()):  # aborted transactions
            r.i64()
            r.i64()
        record_set = r.bytes_() or b""
        if err != 0:
            self._leaders.pop(topic, None)
            raise KafkaError(err, f"fetch {topic}[{partition}]")
        records = [rec for rec in decode_record_batches(record_set) if rec[0] >= offset]
        return records, high_watermark

    # --------------------------------------------------------- list offsets
    async def list_offset(self, topic: str, partition: int, at: int = LATEST) -> int:
        w = Writer()
        w.i32(-1)  # replica_id
        w.i32(1)
        w.string(topic)
        w.i32(1)
        w.i32(partition)
        w.i64(at)
        addr = await self._leader(topic, partition)
        r = await self._conn(addr).call(API_LIST_OFFSETS, 1, bytes(w.b))
        r.i32()  # topic count
        r.string()
        r.i32()  # partition count
        r.i32()  # partition
        err = r.i16()
        r.i64()  # timestamp
        off = r.i64()
        if err != 0:
            raise KafkaError(err, f"list_offset {topic}[{partition}]")
        return off
