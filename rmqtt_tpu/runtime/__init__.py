"""Native C++ runtime bindings (ctypes).

The reference's data plane is native (Rust); here the hot host-side
structures are C++ (``/root/repo/runtime``) bound via ctypes (no pybind11 in
this image). Currently: the topic-trie matcher (`runtime/topics.cc`) used as
(a) the fast host-side router backend (``NativeTrie`` →
``router.native.NativeRouter``) and (b) the honest CPU baseline in bench.py.

The shared library is built on demand with ``make`` and cached next to the
sources; environments without a toolchain fall back to the Python trie.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from pathlib import Path
from typing import List, Optional, Sequence

import numpy as np

log = logging.getLogger("rmqtt_tpu.runtime")

_RUNTIME_DIR = Path(__file__).resolve().parent.parent.parent / "runtime"
_LIB_PATH = _RUNTIME_DIR / "librmqtt_runtime.so"
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-s"], cwd=_RUNTIME_DIR, check=True, capture_output=True, timeout=120
        )
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, OSError) as e:
        log.warning("native runtime build failed: %s", e)
        return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    srcs = [_RUNTIME_DIR / "topics.cc", _RUNTIME_DIR / "encode.cc", _RUNTIME_DIR / "codec.cc"]
    if not _LIB_PATH.exists() or any(
        s.exists() and s.stat().st_mtime > _LIB_PATH.stat().st_mtime for s in srcs
    ):
        if not _build():
            _build_failed = True
            return None
    lib = ctypes.CDLL(str(_LIB_PATH))
    lib.rt_trie_new.restype = ctypes.c_void_p
    lib.rt_trie_free.argtypes = [ctypes.c_void_p]
    lib.rt_trie_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.rt_trie_add.restype = ctypes.c_int
    lib.rt_trie_remove.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.rt_trie_remove.restype = ctypes.c_int
    lib.rt_trie_size.argtypes = [ctypes.c_void_p]
    lib.rt_trie_size.restype = ctypes.c_int64
    lib.rt_trie_match.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ]
    lib.rt_trie_match.restype = ctypes.c_int64
    lib.rt_trie_match_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ]
    lib.rt_trie_match_batch.restype = ctypes.c_int64
    lib.rt_enc_new.restype = ctypes.c_void_p
    lib.rt_enc_free.argtypes = [ctypes.c_void_p]
    lib.rt_enc_add_token.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.rt_enc_cache_clear.argtypes = [ctypes.c_void_p]
    lib.rt_enc_cache_put.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
    ]
    lib.rt_enc_cache_put.restype = ctypes.c_int32
    if hasattr(lib, "rt_enc_cache_del"):  # absent in pre-delta .so builds
        lib.rt_enc_cache_del.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
        ]
        lib.rt_enc_cache_del.restype = ctypes.c_int32
    lib.rt_enc_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.rt_enc_encode.restype = ctypes.c_int64
    lib.rt_match_decode.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.rt_match_decode.restype = ctypes.c_int64
    lib.rt_match_decode_routes.argtypes = [
        ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.rt_match_decode_routes.restype = ctypes.c_int64
    lib.rt_codec_scan.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.rt_codec_scan.restype = ctypes.c_int64
    if hasattr(lib, "rt_codec_encode_publish"):  # absent in stale .so builds
        lib.rt_codec_encode_publish.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ]
        lib.rt_codec_encode_publish.restype = ctypes.c_int64
    lib.rt_topic_validate.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32]
    lib.rt_topic_validate.restype = ctypes.c_int
    _lib = lib
    return lib


CODEC_STRIDE = 10  # int64 slots per frame record (runtime/codec.cc)
_SCAN_CAP = 8192  # frames per scan call; feed loops on over-full buffers


def codec_scan(lib, buf: bytes, is_v5: bool, max_size: int):
    """→ (rows list [n][stride], consumed, err, hit_cap)."""
    cap = min(len(buf) // 2 + 1, _SCAN_CAP)
    meta = np.empty((cap, CODEC_STRIDE), dtype=np.int64)
    consumed = ctypes.c_int64(0)
    err = ctypes.c_int32(0)
    n = lib.rt_codec_scan(
        buf, len(buf), 1 if is_v5 else 0, max_size,
        meta.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), cap,
        ctypes.byref(consumed), ctypes.byref(err),
    )
    return meta[:n].tolist(), consumed.value, err.value, n == cap


def codec_encode_publish(lib, topic: bytes, payload: bytes, props: bytes,
                         qos: int, retain: bool, dup: bool,
                         packet_id: Optional[int]) -> Optional[bytes]:
    """Assemble one complete PUBLISH wire frame in C++ (codec.cc). `props`
    is the pre-encoded v5 properties blob (varint prefix + content; empty
    for v3). None when the .so predates the symbol (stale prebuilt build)
    — the caller falls back to the Python encoder."""
    if not hasattr(lib, "rt_codec_encode_publish"):
        return None
    cap = 7 + len(topic) + len(props) + len(payload) + (2 if qos else 0)
    out = (ctypes.c_uint8 * cap)()
    n = lib.rt_codec_encode_publish(
        topic, len(topic), payload, len(payload), props, len(props),
        qos, 1 if retain else 0, 1 if dup else 0,
        -1 if packet_id is None else packet_id, out, cap,
    )
    if n < 0:
        return None  # cap miscount — let the Python path handle it
    return bytes(out[:n])


def topic_validate(topic: str, is_filter: bool) -> Optional[bool]:
    """Native topic/filter validation; None if the runtime is unavailable."""
    lib = load()
    if lib is None:
        return None
    raw = topic.encode()
    return bool(lib.rt_topic_validate(raw, len(raw), 1 if is_filter else 0))


def available() -> bool:
    return load() is not None


class NativeTrie:
    """ctypes wrapper over the C++ trie (same semantics as core.trie.TopicTree)."""

    def __init__(self) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable (no C++ toolchain?)")
        self._lib = lib
        self._ptr = ctypes.c_void_p(lib.rt_trie_new())

    def __del__(self) -> None:
        ptr = getattr(self, "_ptr", None)
        if ptr:
            self._lib.rt_trie_free(ptr)
            self._ptr = None

    def add(self, topic_filter: str, value: int) -> bool:
        return bool(self._lib.rt_trie_add(self._ptr, topic_filter.encode(), value))

    def remove(self, topic_filter: str, value: int) -> bool:
        return bool(self._lib.rt_trie_remove(self._ptr, topic_filter.encode(), value))

    def __len__(self) -> int:
        return int(self._lib.rt_trie_size(self._ptr))

    def match(self, topic: str, cap: int = 4096) -> np.ndarray:
        buf = np.empty(cap, dtype=np.int64)
        n = self._lib.rt_trie_match(
            self._ptr, topic.encode(), buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), cap
        )
        if n > cap:  # rare: grow and retry
            return self.match(topic, cap=int(n))
        return buf[:n].copy()

    def match_batch(self, topics: Sequence[str], cap_per_topic: int = 64) -> List[np.ndarray]:
        blob = b"\x00".join(t.encode() for t in topics) + b"\x00"
        n = len(topics)
        counts = np.empty(n, dtype=np.int64)
        cap = max(1, cap_per_topic * n)
        while True:
            out = np.empty(cap, dtype=np.int64)
            total = self._lib.rt_trie_match_batch(
                self._ptr, blob, n,
                counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), cap,
            )
            if total <= cap:
                break
            cap = int(total)
        rows: List[np.ndarray] = []
        off = 0
        for j in range(n):
            c = int(counts[j])
            rows.append(out[off : off + c].copy())
            off += c
        return rows


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


class NativeEncoder:
    """ctypes wrapper over the C++ batched topic encoder (runtime/encode.cc).

    Owns the native token-dict mirror and candidate-chunk cache for one
    ``PartitionedTable``; the table syncs tokens incrementally and clears
    the cache on mutation (see partitioned.py ``_encode_native``).
    """

    def __init__(self) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable (no C++ toolchain?)")
        self._lib = lib
        self._ptr = ctypes.c_void_p(lib.rt_enc_new())
        self.tokens_synced = 0  # count of TokenDict entries pushed so far
        self.cache_version = -1  # table.version the candidate cache reflects
        self.cache_epoch = -1  # table.layout_epoch the cache was built under
        self.has_cache_del = hasattr(lib, "rt_enc_cache_del")

    def __del__(self) -> None:
        ptr = getattr(self, "_ptr", None)
        if ptr:
            self._lib.rt_enc_free(ptr)
            self._ptr = None

    def add_token(self, s: str, tid: int) -> None:
        b = s.encode()
        self._lib.rt_enc_add_token(self._ptr, b, len(b), tid)

    def cache_clear(self) -> None:
        self._lib.rt_enc_cache_clear(self._ptr)

    def cache_del(self, key: bytes) -> int:
        """Erase one prefix entry (selective invalidation); returns the
        number of entries dropped. A stale prebuilt .so without the symbol
        degrades to a full clear — correct, just colder."""
        if not self.has_cache_del:
            self.cache_clear()
            return 1
        return self._lib.rt_enc_cache_del(self._ptr, key, len(key))

    def cache_put(self, key: bytes, chunks: np.ndarray) -> int:
        """→ the gid the native side assigned to this entry (authoritative —
        no Python-side mirror counter to drift out of sync)."""
        chunks = np.ascontiguousarray(chunks, dtype=np.int32)
        return self._lib.rt_enc_cache_put(
            self._ptr, key, len(key), _i32p(chunks), len(chunks)
        )

    def encode(
        self,
        blob: bytes,
        n: int,
        max_levels: int,
        ttok: np.ndarray,
        tlen: np.ndarray,
        tdollar: np.ndarray,
        nc_cap: int,
        cand: np.ndarray,
        cand_counts: np.ndarray,
        group: np.ndarray,
    ) -> np.ndarray:
        """Returns the indices of topics whose prefix key missed the cache;
        ``group`` receives each topic's candidate-row gid (-1 on miss)."""
        miss = np.empty(n, dtype=np.int32)
        nmiss = self._lib.rt_enc_encode(
            self._ptr, blob, n, max_levels,
            _i32p(ttok), _i32p(tlen),
            tdollar.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            nc_cap, _i32p(cand), _i32p(cand_counts), _i32p(group), _i32p(miss),
        )
        return miss[:nmiss]


def match_decode_routes(routes: np.ndarray, counts: np.ndarray,
                        chunk_ids: np.ndarray, b: int, wpc: int, chunk: int,
                        fid_map: np.ndarray):
    """Native route-level global compaction → flat per-topic-sorted fids;
    None if the runtime is unavailable. routes uint32, counts int64 (per
    PADDED topic), chunk_ids int32, fid_map int64, all C-contiguous. The
    route total is known up front (= counts.sum() = len(routes)), so
    unlike the word decoders there is no two-pass cap dance."""
    lib = load()
    if lib is None:
        return None
    bp, nc = chunk_ids.shape
    fid_map = np.ascontiguousarray(fid_map, dtype=np.int64)
    i32 = ctypes.POINTER(ctypes.c_int32)
    i64 = ctypes.POINTER(ctypes.c_int64)
    u32 = ctypes.POINTER(ctypes.c_uint32)
    n = int(routes.shape[0])
    out = np.empty(n, dtype=np.int64)
    total = lib.rt_match_decode_routes(
        routes.ctypes.data_as(u32), n, counts.ctypes.data_as(i64),
        chunk_ids.ctypes.data_as(i32), b, bp, nc, wpc, chunk,
        fid_map.ctypes.data_as(i64), out.ctypes.data_as(i64),
    )
    if total < 0:
        raise AssertionError(
            "rt_match_decode_routes hit an out-of-range route/fid/count — "
            "kernel/compaction bug"
        )
    return out


def match_decode(wi: np.ndarray, wb: np.ndarray, chunk_ids: np.ndarray,
                 wpc: int, chunk: int, fid_map: np.ndarray):
    """Native compact-words → (flat sorted fids, per-topic counts); None if
    the runtime is unavailable. Arrays must be C-contiguous int32/uint32
    except fid_map (int64)."""
    lib = load()
    if lib is None:
        return None
    b, k = wi.shape
    nc = chunk_ids.shape[1]
    fid_map = np.ascontiguousarray(fid_map, dtype=np.int64)
    counts = np.empty(b, dtype=np.int64)
    cap = max(64, int(b) * 16)
    i32 = ctypes.POINTER(ctypes.c_int32)
    i64 = ctypes.POINTER(ctypes.c_int64)
    u32 = ctypes.POINTER(ctypes.c_uint32)
    while True:
        out = np.empty(cap, dtype=np.int64)
        total = lib.rt_match_decode(
            wi.ctypes.data_as(i32), wb.ctypes.data_as(u32), b, k,
            chunk_ids.ctypes.data_as(i32), nc, wpc, chunk,
            fid_map.ctypes.data_as(i64),
            out.ctypes.data_as(i64), cap, counts.ctypes.data_as(i64),
        )
        if total < 0:
            raise AssertionError(
                "rt_match_decode hit an out-of-range fid (cleared-row "
                "sentinel or overflow) — kernel/compaction bug"
            )
        if total <= cap:
            return out[:total], counts
        cap = int(total)
