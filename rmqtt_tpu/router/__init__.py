"""Subscription routing: the `Router` seam of the framework.

Mirrors the reference's swappable `Router` trait
(`/root/reference/rmqtt/src/router.rs:65-112`) behind which the cluster
plugins and the TPU backend plug in. Two implementations:

- ``DefaultRouter``: CPU topic-trie router — the faithful baseline
  (`/root/reference/rmqtt/src/router.rs:121-265`).
- ``XlaRouter``: the north star — filter table in TPU HBM, batched
  `matches()` through `rmqtt_tpu.ops`.
"""

from rmqtt_tpu.router.base import Id, Router, SubRelation, SubscriptionOptions
from rmqtt_tpu.router.default import DefaultRouter
from rmqtt_tpu.router.xla import XlaRouter
