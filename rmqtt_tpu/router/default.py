"""CPU trie-backed router — the faithful baseline implementation.

Mirrors `DefaultRouter` (`/root/reference/rmqtt/src/router.rs:121-265`):
a topic trie over filter shapes plus a relations map, per-publish trie DFS
in `matches()`. This is the CPU oracle the TPU path is benchmarked against
(BASELINE.md: the reference publishes no routing microbenchmark, so this
implementation *is* the baseline).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

from rmqtt_tpu.core.trie import TopicTree
from rmqtt_tpu.router.base import (
    ClientId,
    Id,
    Router,
    SharedChoiceFn,
    SubRelationsMap,
    SubscriptionOptions,
    round_robin_choice_factory,
)
from rmqtt_tpu.router.relations import RelationsMap, expand_matches_raw


class DefaultRouter(Router):
    prefer_inline = True  # trie match is µs-scale: no executor hop needed
    epochs_tracked = True  # add/remove bump the match-cache epochs

    def __init__(
        self,
        shared_choice: Optional[SharedChoiceFn] = None,
        is_online: Callable[[ClientId], bool] = lambda cid: True,
    ) -> None:
        self._trie: TopicTree[str] = TopicTree()
        self._relations = RelationsMap()
        self._shared_choice = shared_choice or round_robin_choice_factory()
        self._is_online = is_online

    def add(self, topic_filter: str, id: Id, opts: SubscriptionOptions) -> None:
        if self._relations.add(topic_filter, id, opts):
            self._trie.insert(topic_filter, topic_filter)
        # any REAL relations mutation versions the match cache (the cache
        # holds expansions, so opts changes count too) — but an identical
        # re-subscribe (reconnect storms) must not trash hot entries
        if self._relations.last_add_changed:
            self.epochs.bump(topic_filter)

    def remove(self, topic_filter: str, id: Id) -> bool:
        existed, empty = self._relations.remove(topic_filter, id)
        if empty:
            self._trie.remove(topic_filter, topic_filter)
        if existed:
            self.epochs.bump(topic_filter)
        return existed

    def matches_raw(self, from_id: Optional[Id], topic: str):
        matched = [tf for _levels, vals in self._trie.matches(topic) for tf in vals]
        return expand_matches_raw(matched, self._relations, from_id, self._is_online)

    def is_match(self, topic: str) -> bool:
        return self._trie.is_match(topic)

    def gets(self, limit: int) -> List[dict]:
        out: List[dict] = []
        for tf, rels in self._relations.items():
            for cid in rels:
                if len(out) >= limit:
                    return out
                out.append({"topic_filter": tf, "client_id": cid})
        return out

    def subscribers_count(self, topic_filter: str, exclude_client=None) -> int:
        rels = self._relations.get(topic_filter)
        n = len(rels)
        if exclude_client is not None and exclude_client in rels:
            n -= 1
        return n

    def topics_count(self) -> int:
        return len(self._relations)

    def routes_count(self) -> int:
        return self._relations.edge_count
