"""Relation bookkeeping shared by the CPU and TPU routers.

The reference keeps the trie (filter shapes) separate from the relations map
(filter → {client: (Id, opts)}), `/root/reference/rmqtt/src/router.rs:121-139`
(``AllRelationsMap``, types.rs:476). Both router backends here reuse that
split: the matcher (trie or TPU table) yields matched *filters*; this module
expands filters to clients, applies v5 No-Local (router.rs:196-201), and
collapses ``$share`` groups through the strategy (router.rs:236-255).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from rmqtt_tpu.router.base import (
    ClientId,
    Id,
    SharedChoiceFn,
    SubRelation,
    SubRelationsMap,
    SubscriptionOptions,
    round_robin_choice_factory,
)


class RelationsMap:
    """filter → {client_id: (Id, opts)} with counters."""

    def __init__(self) -> None:
        self._map: Dict[str, Dict[ClientId, Tuple[Id, SubscriptionOptions]]] = {}
        self.edge_count = 0
        # (group, filter) → member count, maintained incrementally so the
        # stats gauge never walks the full table (stats.rs keeps counters)
        self.shared_index: Dict[Tuple[str, str], int] = {}

    def _shared_dec(self, topic_filter: str, opts: SubscriptionOptions) -> None:
        if opts.shared_group:
            key = (opts.shared_group, topic_filter)
            n = self.shared_index.get(key, 0) - 1
            if n <= 0:
                self.shared_index.pop(key, None)
            else:
                self.shared_index[key] = n

    # one-call-back state for Router.add: did the last add() actually
    # mutate the relation (new edge, or same client with different
    # Id/opts)? An identical re-subscribe — reconnect storms re-subscribing
    # defensively — must NOT version the match cache, or hot-segment
    # entries are invalidated on every reconnect with no routing change.
    last_add_changed: bool = True

    def add(self, topic_filter: str, id: Id, opts: SubscriptionOptions) -> bool:
        """Returns True if the filter is new (needs matcher insertion)."""
        rels = self._map.get(topic_filter)
        is_new = rels is None
        if is_new:
            rels = self._map[topic_filter] = {}
        prev = rels.get(id.client_id)
        if prev is None:
            self.edge_count += 1
        else:
            self._shared_dec(topic_filter, prev[1])  # re-subscribe may change group
        if opts.shared_group:
            key = (opts.shared_group, topic_filter)
            self.shared_index[key] = self.shared_index.get(key, 0) + 1
        self.last_add_changed = prev is None or prev != (id, opts)
        rels[id.client_id] = (id, opts)
        return is_new

    def remove(self, topic_filter: str, id: Id) -> Tuple[bool, bool]:
        """Returns (existed, filter_now_empty)."""
        rels = self._map.get(topic_filter)
        if not rels or id.client_id not in rels:
            return False, False
        self._shared_dec(topic_filter, rels[id.client_id][1])
        del rels[id.client_id]
        self.edge_count -= 1
        if not rels:
            del self._map[topic_filter]
            return True, True
        return True, False

    def get(self, topic_filter: str) -> Dict[ClientId, Tuple[Id, SubscriptionOptions]]:
        return self._map.get(topic_filter, {})

    def __len__(self) -> int:
        return len(self._map)

    def items(self):
        return self._map.items()


# (group, filter) → candidates [(Id, opts, online)]
SharedCandidates = Dict[Tuple[str, str], List[Tuple[Id, SubscriptionOptions, bool]]]


def expand_matches_raw(
    matched_filters: List[str],
    relations: RelationsMap,
    from_id: Optional[Id],
    is_online: Callable[[ClientId], bool],
) -> Tuple[SubRelationsMap, SharedCandidates]:
    """Filters → (non-shared relations, shared-group candidates).

    Shared groups are NOT collapsed here — the cluster layer merges
    candidates across nodes before choosing (the reference's broadcast-mode
    global choice, `rmqtt-cluster-broadcast/src/shared.rs:516-560`);
    single-node callers collapse immediately via `collapse_shared`.
    """
    out: SubRelationsMap = {}
    shared: SharedCandidates = {}
    for tf in matched_filters:
        for cid, (sid, opts) in relations.get(tf).items():
            if opts.no_local and from_id is not None and cid == from_id.client_id:
                continue  # v5 No-Local (router.rs:196-201)
            if opts.shared_group is not None:
                shared.setdefault((opts.shared_group, tf), []).append(
                    (sid, opts, is_online(cid))
                )
            else:
                out.setdefault(sid.node_id, []).append(SubRelation(tf, sid, opts))
    return out, shared


def collapse_shared(
    out: SubRelationsMap,
    shared: SharedCandidates,
    shared_choice: SharedChoiceFn,
) -> SubRelationsMap:
    """Pick one subscriber per shared group and merge into the relation map
    (router.rs:236-255)."""
    for (group, tf), candidates in shared.items():
        idx = shared_choice(group, tf, candidates)
        if idx is None:
            continue
        sid, opts, _ = candidates[idx]
        out.setdefault(sid.node_id, []).append(SubRelation(tf, sid, opts))
    return out


def expand_matches(
    matched_filters: List[str],
    relations: RelationsMap,
    from_id: Optional[Id],
    shared_choice: SharedChoiceFn,
    is_online: Callable[[ClientId], bool],
) -> SubRelationsMap:
    """Filters → SubRelationsMap with No-Local + local shared-group collapse."""
    out, shared = expand_matches_raw(matched_filters, relations, from_id, is_online)
    return collapse_shared(out, shared, shared_choice)
