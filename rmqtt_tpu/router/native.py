"""Native (C++) trie-backed router.

Same shape as ``DefaultRouter`` with the hot match loop in C++
(`runtime/topics.cc`): the host-side production router when no TPU is
attached, and the honest CPU baseline for the routing benchmark (the
reference's DefaultRouter is native Rust; a Python-trie baseline would
flatter the TPU numbers).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from rmqtt_tpu.router.base import (
    ClientId,
    Id,
    Router,
    SharedChoiceFn,
    SubscriptionOptions,
    round_robin_choice_factory,
)
from rmqtt_tpu.router.relations import RelationsMap, expand_matches_raw
from rmqtt_tpu.runtime import NativeTrie


class NativeRouter(Router):
    prefer_inline = True  # C++ trie match is µs-scale: no executor hop
    epochs_tracked = True  # add/remove bump the match-cache epochs

    def __init__(
        self,
        shared_choice: Optional[SharedChoiceFn] = None,
        is_online: Callable[[ClientId], bool] = lambda cid: True,
    ) -> None:
        self._trie = NativeTrie()
        self._relations = RelationsMap()
        self._filter_by_vid: Dict[int, str] = {}
        self._vid_by_filter: Dict[str, int] = {}
        self._next_vid = 0
        self._shared_choice = shared_choice or round_robin_choice_factory()
        self._is_online = is_online

    def add(self, topic_filter: str, id: Id, opts: SubscriptionOptions) -> None:
        if self._relations.add(topic_filter, id, opts):
            vid = self._next_vid
            self._next_vid += 1
            self._filter_by_vid[vid] = topic_filter
            self._vid_by_filter[topic_filter] = vid
            self._trie.add(topic_filter, vid)
        # a real relations change versions the match cache even when the
        # filter already existed (opts changes count: the cache holds
        # expansions) — identical re-subscribes don't bump
        if self._relations.last_add_changed:
            self.epochs.bump(topic_filter)

    def remove(self, topic_filter: str, id: Id) -> bool:
        existed, empty = self._relations.remove(topic_filter, id)
        if empty:
            vid = self._vid_by_filter.pop(topic_filter)
            del self._filter_by_vid[vid]
            self._trie.remove(topic_filter, vid)
        if existed:
            self.epochs.bump(topic_filter)
        return existed

    def matches_raw(self, from_id: Optional[Id], topic: str):
        matched = [self._filter_by_vid[v] for v in self._trie.match(topic).tolist()]
        return expand_matches_raw(matched, self._relations, from_id, self._is_online)

    def matches_batch_raw(self, items: Sequence[Tuple[Optional[Id], str]]):
        tele = self.telemetry
        t0 = time.perf_counter_ns() if tele is not None and tele.enabled else 0
        rows = self._trie.match_batch([topic for _, topic in items])
        if t0:
            # recorder, not record(): this can run on an executor thread
            # concurrently with loop-side records — the recorder's append
            # + locked fold keeps totals exact across threads (memoized,
            # so the lookup is one dict hit per batch)
            tele.recorder("kernel.dispatch")(
                time.perf_counter_ns() - t0,
                {"backend": "native", "batch": len(items)})
        out = []
        for (from_id, _topic), vids in zip(items, rows):
            matched = [self._filter_by_vid[v] for v in vids.tolist()]
            out.append(expand_matches_raw(matched, self._relations, from_id, self._is_online))
        return out

    def is_match(self, topic: str) -> bool:
        return self._trie.match(topic).size > 0

    def gets(self, limit: int) -> List[dict]:
        out: List[dict] = []
        for tf, rels in self._relations.items():
            for cid in rels:
                if len(out) >= limit:
                    return out
                out.append({"topic_filter": tf, "client_id": cid})
        return out

    def subscribers_count(self, topic_filter: str, exclude_client=None) -> int:
        rels = self._relations.get(topic_filter)
        n = len(rels)
        if exclude_client is not None and exclude_client in rels:
            n -= 1
        return n

    def topics_count(self) -> int:
        return len(self._relations)

    def routes_count(self) -> int:
        return self._relations.edge_count
