"""Epoch-versioned publish→relations match-result cache.

Zipf-skewed IoT publish traffic re-routes the same hot topics continuously
(MQTT+ motivates broker-side reuse of per-topic routing work, arxiv
1810.00773; the broker benchmarking study 2603.21600 shows hot-topic skew
dominating real traces), yet every publish pays full matcher cost — trie DFS,
or a device round trip on the XLA path. This module caches the EXPANDED raw
match result per topic and validates entries with subscription-table epochs
so a cache can never serve stale relations:

- ``SubscriptionEpochs``: ``Router.add()/remove()`` bump a per-first-level-
  segment epoch for exact filters and one global wildcard epoch for filters
  containing ``+``/``#``. A subscribe to ``sensor/1/temp`` therefore
  invalidates only cached ``sensor/...`` topics, while wildcard churn
  invalidates broadly. Correct by construction: an entry is served only when
  BOTH epochs it was built under are still current.
- ``MatchCache``: LRU of ``topic → CacheEntry``. Entries are built from a
  ``from_id=None`` ``matches_raw`` result with shared-group candidates kept
  RAW (pre-choice) and liveness flags stripped; ``derive()`` re-applies v5
  No-Local for the actual publisher, re-evaluates ``is_online`` and returns
  fresh containers — so the shared-subscription round-robin choice point
  (``Router.collapse``) still runs per publish and rotates on cache hits.

Epoch snapshots are taken BEFORE the matcher runs (``snapshot()``): if a
subscribe lands while a match is in flight, the entry is stored under the
pre-match epochs and the next ``get()`` drops it — a racing entry can be
wastefully invalidated, never wrongly served.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, Optional, Tuple


def _first_level(topic: str) -> str:
    return topic.split("/", 1)[0]


def _is_wild(topic_filter: str) -> bool:
    return any(lv in ("+", "#") for lv in topic_filter.split("/"))


class SubscriptionEpochs:
    """Subscription-table version counters bumped by ``Router.add/remove``."""

    # distinct first-level segments tracked before folding into the global
    # wildcard epoch (first levels are attacker-chosen — any client can
    # subscribe/unsubscribe unique prefixes — so the map must be bounded)
    SEG_CAP = 65_536

    __slots__ = ("wild", "_seg")

    def __init__(self) -> None:
        self.wild = 0
        self._seg: Dict[str, int] = {}

    def bump(self, topic_filter: str) -> None:
        """One subscription-table mutation for ``topic_filter`` ($share
        already stripped). Exact filters can only change match results of
        topics sharing their first level; wildcard filters may match
        anything, so they version the whole cache."""
        if _is_wild(topic_filter):
            self.wild += 1
        else:
            seg = _first_level(topic_filter)
            if seg not in self._seg and len(self._seg) >= self.SEG_CAP:
                # overflow: treat like wildcard churn — the wild bump
                # invalidates every live entry, and clearing resets segment
                # epochs to 0, so surviving stale entries (seg_epoch > 0)
                # can still never validate. Conservative, never wrong.
                self.wild += 1
                self._seg.clear()
            self._seg[seg] = self._seg.get(seg, 0) + 1

    def segment(self, topic: str) -> int:
        return self._seg.get(_first_level(topic), 0)


class CacheEntry:
    __slots__ = ("out", "shared", "_nl", "wild_epoch", "seg_epoch", "stored")

    @property
    def has_no_local(self) -> bool:
        """Lazily computed: most publishes carry a ``from_id`` whose
        No-Local check short-circuits on this flag, but on the miss path
        (from_id=None fan-out, uniform streams) the double relation scan
        would be pure overhead — so it only runs when first consulted."""
        nl = self._nl
        if nl is None:
            nl = self._nl = any(
                r.opts.no_local for rels in self.out.values() for r in rels
            ) or any(
                opts.no_local for cands in self.shared.values()
                for _sid, opts, _on in cands
            )
        return nl


class MatchCache:
    """LRU ``topic → CacheEntry`` validated by :class:`SubscriptionEpochs`.

    Admission is doorkeeper-gated (TinyLFU-lite) by default: the FIRST miss
    for an unseen topic only registers it; storing waits for a repeat. A
    one-shot topic stream (uniform, miss-heavy) then never churns the LRU —
    churn is what costs on that path: every stored entry's containers get
    promoted to CPython's older GC generations and repeatedly re-scanned —
    while genuinely hot topics are cached from their second publish on."""

    def __init__(
        self,
        epochs: SubscriptionEpochs,
        capacity: int = 8192,
        shared_bypass: bool = False,
        admission: bool = True,
        is_online: Callable[[str], bool] = lambda cid: True,
    ) -> None:
        self._epochs = epochs
        self.capacity = max(1, capacity)
        self.shared_bypass = shared_bypass
        self.admission = admission
        self._is_online = is_online
        self._lru: "OrderedDict[str, CacheEntry]" = OrderedDict()
        self._door: set = set()  # topics missed once since the last reset
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.evictions = 0
        self.door_rejects = 0

    def __len__(self) -> int:
        return len(self._lru)

    def clear(self) -> None:
        self._lru.clear()
        self._door.clear()

    def snapshot(self, topic: str) -> Tuple[int, int]:
        """Epoch pair to build an entry under — take it BEFORE matching."""
        return self._epochs.wild, self._epochs.segment(topic)

    def get(self, topic: str) -> Optional[CacheEntry]:
        e = self._lru.get(topic)
        if e is None:
            self.misses += 1
            return None
        if (e.wild_epoch != self._epochs.wild
                or e.seg_epoch != self._epochs.segment(topic)):
            del self._lru[topic]
            if self.admission:
                # the topic proved hot once — let ONE miss re-admit it
                # instead of making it pass the doorkeeper from scratch
                self._door.add(topic)
            self.invalidations += 1
            self.misses += 1
            return None
        self._lru.move_to_end(topic)
        self.hits += 1
        return e

    def put(self, topic: str, raw, snapshot: Tuple[int, int]) -> CacheEntry:
        """Build (and usually store) an entry from a ``from_id=None``
        ``matches_raw`` result. Always returns the entry so the missing
        publish can be served through the same ``derive`` path even when
        storage is rejected (doorkeeper, shared_bypass) or the entry is
        born stale."""
        out, shared = raw
        store = True
        if self.shared_bypass and shared:
            store = False
        elif self.admission and topic not in self._lru:
            if topic in self._door:
                self._door.discard(topic)  # promoted: second miss
            else:
                self._door.add(topic)
                if len(self._door) > (self.capacity << 1):
                    self._door.clear()
                self.door_rejects += 1
                store = False
        e = CacheEntry()
        e._nl = None
        e.stored = store
        if not store:
            # transient entry: ALIAS the raw containers — it only serves the
            # missing publish and dies with the call, so no copy (and no
            # epoch validation, hence no snapshot fields) is needed
            # (consumers must not hand the raw to collapse AND derive from
            # this entry; RoutingService honors that via ``stored``)
            e.out, e.shared = out, shared
            return e
        e.wild_epoch, e.seg_epoch = snapshot
        # tuples: stored relations are shared across publishes; derive()
        # hands out fresh lists so collapse() can't mutate the entry
        e.out = {nid: tuple(rels) for nid, rels in out.items()}
        e.shared = {key: tuple(cands) for key, cands in shared.items()}
        self._lru[topic] = e
        self._lru.move_to_end(topic)
        while len(self._lru) > self.capacity:
            self._lru.popitem(last=False)
            self.evictions += 1
        return e

    def derive(self, entry: CacheEntry, from_id) -> tuple:
        """Per-publish raw result from a cached entry: No-Local filtered for
        THIS publisher, shared candidates re-flagged with CURRENT liveness,
        fresh containers throughout (``collapse`` appends into ``out``)."""
        cid = from_id.client_id if from_id is not None else None
        nl = entry.has_no_local and cid is not None
        out = {}
        for nid, rels in entry.out.items():
            if nl:
                lst = [r for r in rels
                       if not (r.opts.no_local and r.id.client_id == cid)]
            else:
                lst = list(rels)
            if lst:
                out[nid] = lst
        shared = {}
        online = self._is_online
        for key, cands in entry.shared.items():
            # the liveness flag a candidate was built under is stale by
            # definition — re-evaluate per publish
            lst = [(sid, opts, online(sid.client_id)) for sid, opts, _on in cands
                   if not (nl and opts.no_local and sid.client_id == cid)]
            if lst:
                shared[key] = lst
        return out, shared


def cached_matches_raw(router, cache: MatchCache, from_id, topic: str):
    """Synchronous get-or-build helper (bench / oracle tests / sync callers):
    the exact protocol ``RoutingService`` runs — snapshot before match, build
    from a ``from_id=None`` result, derive per publisher."""
    entry = cache.get(topic)
    if entry is None:
        snap = cache.snapshot(topic)
        raw = router.matches_raw(None, topic)
        entry = cache.put(topic, raw, snap)
        if from_id is None or not entry.has_no_local:
            # the fresh raw is already exact for this publish (No-Local has
            # nothing to filter; liveness flags were just evaluated) and its
            # containers are unaliased — skip the derive copy on the miss
            # path, where the full match was the cost anyway
            return raw
    return cache.derive(entry, from_id)
