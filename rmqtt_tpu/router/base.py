"""Router interface and routing data types.

Mirrors the reference's `Router` trait and DTOs:
`/root/reference/rmqtt/src/router.rs:65-112` (add/remove/matches/gets/
query_subscriptions/topics/routes), `/root/reference/rmqtt/src/types.rs:476-486`
(``AllRelationsMap``, ``SubRelation``, ``SubRelationsMap``) and the
``SubscriptionOptions`` carried on every subscription (types.rs).

The shared-subscription *choice point* lives in `matches()` exactly as in the
reference (`router.rs:236-255`): matched relations in a ``$share`` group are
collapsed to one subscriber by the pluggable strategy, with liveness supplied
by the session layer.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

NodeId = int
ClientId = str


@dataclass(frozen=True)
class Id:
    """Session identity: owning node + client id (reference types.rs Id)."""

    node_id: NodeId
    client_id: ClientId


@dataclass(frozen=True)
class SubscriptionOptions:
    """Per-subscription options (reference types.rs ``SubscriptionOptions``)."""

    qos: int = 0
    no_local: bool = False
    retain_as_published: bool = False
    retain_handling: int = 0
    subscription_ids: Tuple[int, ...] = ()
    shared_group: Optional[str] = None

    def merge_sub_id(self, sub_id: Optional[int]) -> "SubscriptionOptions":
        if sub_id is None:
            return self
        return replace(self, subscription_ids=(sub_id,))


@dataclass(frozen=True)
class SubRelation:
    """One matched (filter → subscriber) edge (reference types.rs:485)."""

    topic_filter: str
    id: Id
    opts: SubscriptionOptions


# node_id → relations to deliver there (reference types.rs:486 SubRelationsMap)
SubRelationsMap = Dict[NodeId, List[SubRelation]]

# choice(group, candidates[(id, opts, is_online)]) -> index or None
# (reference rmqtt/src/subscribe.rs:71-96 SharedSubscription::choice)
SharedChoiceFn = Callable[[str, str, List[Tuple[Id, SubscriptionOptions, bool]]], Optional[int]]


def round_robin_choice_factory() -> SharedChoiceFn:
    """Default shared-sub strategy: round-robin over online candidates
    (reference rmqtt/src/subscribe.rs:98-107 default impl)."""
    counters: Dict[Tuple[str, str], int] = {}

    def choice(group: str, topic_filter: str, candidates):
        online = [i for i, (_, _, is_on) in enumerate(candidates) if is_on]
        pool = online or list(range(len(candidates)))
        if not pool:
            return None
        key = (group, topic_filter)  # tuple key: no per-publish f-string
        n = counters.get(key, 0)
        counters[key] = n + 1
        return pool[n % len(pool)]

    return choice


class Router(abc.ABC):
    """The swappable routing seam (reference router.rs:65-112)."""

    # True for µs-scale CPU matchers (trie/C++): the RoutingService then
    # dispatches small batches inline instead of paying a thread-pool hop;
    # device-backed routers leave this False (their kernels block)
    prefer_inline: bool = False

    # latency telemetry registry (broker/telemetry.py), injected by
    # ServerContext at broker startup; None for standalone routers. The
    # native/xla routers record their ``kernel.dispatch`` stage through it.
    telemetry = None

    # True ONLY for routers whose add()/remove() bump ``epochs`` on every
    # mutation — the bundled trie/native/xla routers do. RoutingService
    # keys its match cache on THIS flag, not on ``epochs`` existing (the
    # lazy property below makes that non-None for every subclass): a
    # custom router that never bumps would otherwise serve stale entries
    # forever. Subclasses honoring the contract opt in explicitly.
    epochs_tracked: bool = False

    @property
    def epochs(self):
        """Subscription-table epochs (router/cache.py): every ``add()`` /
        successful ``remove()`` must bump them so the match-result cache in
        front of this router can validate entries — and the subclass must
        set ``epochs_tracked = True`` to enable that cache. Lazy so routers
        without a cache pay nothing."""
        ep = getattr(self, "_sub_epochs", None)
        if ep is None:
            from rmqtt_tpu.router.cache import SubscriptionEpochs

            ep = self._sub_epochs = SubscriptionEpochs()
        return ep

    def inline_ok(self, batch_size: int) -> bool:
        """May this batch run on the event loop (µs-scale, non-blocking)?
        Routers with a per-size fast path (XlaRouter's host-trie hybrid)
        override this; the default follows ``prefer_inline``."""
        return self.prefer_inline and batch_size <= 256

    @abc.abstractmethod
    def add(self, topic_filter: str, id: Id, opts: SubscriptionOptions) -> None:
        """Register a subscription (filter already stripped of ``$share``)."""

    @abc.abstractmethod
    def remove(self, topic_filter: str, id: Id) -> bool:
        """Remove a subscription; True if it existed."""

    @abc.abstractmethod
    def matches_raw(self, from_id: Optional[Id], topic: str):
        """→ (non-shared SubRelationsMap, shared-group candidates).

        Shared groups are left un-collapsed so cluster modes can merge
        candidates across nodes before choosing (broadcast-mode global
        choice, `rmqtt-cluster-broadcast/src/shared.rs:516-560`).
        """

    def matches_batch_raw(self, items: Sequence[Tuple[Optional[Id], str]]):
        """Batched `matches_raw` — the TPU path overrides with one launch."""
        return [self.matches_raw(fid, topic) for fid, topic in items]

    def collapse(self, raw) -> SubRelationsMap:
        """Collapse shared-group candidates with this router's strategy."""
        from rmqtt_tpu.router.relations import collapse_shared

        out, shared = raw
        return collapse_shared(out, shared, self._shared_choice)

    def matches(self, from_id: Optional[Id], topic: str) -> SubRelationsMap:
        """All deliverable relations for one publish topic."""
        return self.collapse(self.matches_raw(from_id, topic))

    def matches_batch(self, items: Sequence[Tuple[Optional[Id], str]]) -> List[SubRelationsMap]:
        """Batched `matches` — single kernel launch on the TPU path."""
        return [self.collapse(raw) for raw in self.matches_batch_raw(items)]

    # --- admin / introspection surface (router.rs gets/query/topics) ---
    def shared_groups_count(self) -> int:
        """Distinct ($share group, filter) pairs (stats gauge; O(1))."""
        return len(self._relations.shared_index)

    def dump_routes(self):
        """Every route edge as (topic_filter, Id, opts) — snapshot/transfer
        surface (raft compaction serializes the full table through this).
        Default walks the ``_relations`` map all bundled routers keep; a
        router with a different store must override."""
        for tf, rels in self._relations.items():
            for _cid, (sid, opts) in rels.items():
                yield tf, sid, opts

    @abc.abstractmethod
    def gets(self, limit: int) -> List[dict]:
        """List (topic_filter, client) routes up to limit."""

    @abc.abstractmethod
    def topics_count(self) -> int:
        """Number of distinct stored topic filters."""

    @abc.abstractmethod
    def routes_count(self) -> int:
        """Number of stored (filter, client) subscription edges."""

    @abc.abstractmethod
    def is_match(self, topic: str) -> bool:
        """Does any subscription match this topic?"""

    @abc.abstractmethod
    def subscribers_count(self, topic_filter: str, exclude_client: Optional[str] = None) -> int:
        """Current subscriber count for one exact filter ($limit/$exclusive
        enforcement; cluster-wide under raft's replicated table). When
        ``exclude_client`` is given, that client's own relation is not
        counted (re-subscribing must not trip the cap, session.rs:1292-1306)."""
