"""The north-star router: subscription matching on TPU.

Swaps the reference's trie DFS (`/root/reference/rmqtt/src/router.rs:174-265`)
for the batched XLA matcher over the flattened filter table in device HBM
(see `rmqtt_tpu.ops`). Publish ingress is micro-batched: `matches_batch()`
encodes B topics and resolves all of them in one kernel launch; matched
*filter ids* come back as packed bitmaps and are expanded host-side to
clients via the relations map — the same kernel/host split the reference
uses between trie and ``AllRelationsMap`` (router.rs:121-139), per
SURVEY.md §7 "hard parts".
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from rmqtt_tpu.ops.encode import FilterTable
from rmqtt_tpu.ops.match import TpuMatcher
from rmqtt_tpu.utils.failpoints import FAILPOINTS
from rmqtt_tpu.router.base import (
    ClientId,
    Id,
    Router,
    SharedChoiceFn,
    SubRelationsMap,
    SubscriptionOptions,
    round_robin_choice_factory,
)
from rmqtt_tpu.router.relations import RelationsMap, expand_matches_raw


class _TreeSide:
    """Python-trie fallback for the hybrid mirror (NativeTrie API subset)."""

    def __init__(self, tree) -> None:
        self._tree = tree

    def add(self, topic_filter: str, fid: int) -> None:
        self._tree.insert(topic_filter, fid)

    def remove(self, topic_filter: str, fid: int) -> None:
        self._tree.remove(topic_filter, fid)

    def match(self, topic: str):
        # numpy is imported at module scope: this sits on the small-batch
        # dispatch path and must not pay a per-call import lookup
        vals = [v for _lv, vs in self._tree.matches(topic) for v in vs]
        return np.asarray(vals, dtype=np.int64)


class XlaRouter(Router):
    epochs_tracked = True  # add/remove bump the match-cache epochs

    def __init__(
        self,
        shared_choice: Optional[SharedChoiceFn] = None,
        is_online: Callable[[ClientId], bool] = lambda cid: True,
        table=None,
        device=None,
        backend: str = "partitioned",
        mesh="auto",
    ) -> None:
        """``mesh``: a ``jax.sharding.Mesh`` to data-parallelize the
        partitioned matcher over (batch sharded, table replicated);
        ``"auto"`` uses all devices when running on a multi-chip TPU slice
        (single-device and CPU-test environments keep the local matcher);
        ``None`` forces single-device."""
        if mesh not in (None, "auto") and (backend != "partitioned" or device is not None):
            raise ValueError(
                "mesh is only supported with backend='partitioned' and no "
                "explicit device (use parallel.ShardedMatcher for dense)"
            )
        if backend == "partitioned":
            from rmqtt_tpu.ops.partitioned import PartitionedMatcher, PartitionedTable

            self.table = table or PartitionedTable()
            use_mesh = None if mesh == "auto" else mesh
            if mesh == "auto" and device is None:
                try:
                    # the platform guard MUST run before the first backend
                    # touch: jax.devices() hangs forever on a wedged
                    # accelerator grant (tpuprobe; memoized, instant when the
                    # process already chose a platform)
                    from rmqtt_tpu.utils.tpuprobe import ensure_safe_platform

                    if ensure_safe_platform() != "cpu":
                        import jax

                        devs = jax.devices()
                        if len(devs) > 1 and devs[0].platform == "tpu":
                            from rmqtt_tpu.parallel.sharded import make_mesh

                            use_mesh = make_mesh(devices=devs, dp=len(devs), fp=1)
                except Exception:
                    use_mesh = None
            if use_mesh is not None:
                from rmqtt_tpu.parallel.sharded import ShardedPartitionedMatcher

                self.matcher = ShardedPartitionedMatcher(self.table, use_mesh)
            else:
                self.matcher = PartitionedMatcher(self.table, device=device)
        elif backend == "dense":
            self.table = table or FilterTable()
            self.matcher = TpuMatcher(self.table, device=device)
        else:
            raise ValueError(f"unknown backend {backend!r}")
        self._relations = RelationsMap()
        self._fid_to_filter: Dict[int, str] = {}
        self._filter_to_fid: Dict[str, int] = {}
        self._shared_choice = shared_choice or round_robin_choice_factory()
        self._is_online = is_online
        # small-batch hybrid: a host-side trie mirror answers sub-threshold
        # batches inline — one-topic publishes through the device path paid
        # a full dispatch round trip (broker p99 2.4x the trie router,
        # NOTES.md round 2); the device stays for bursts, where batching
        # amortizes the dispatch. Matches the per-message latency contract
        # of `/root/reference/rmqtt/src/shared.rs:735-820`.
        import os

        from rmqtt_tpu.ops.hybrid import AdaptiveHybrid

        self._hybrid_max = int(os.environ.get("RMQTT_HYBRID_MAX", "64"))
        # the mirror is built even with the hybrid fast path disabled
        # (RMQTT_HYBRID_MAX=0): it doubles as the failover plane's host
        # fallback table (broker/failover.py), which must stay maintained
        # precisely in the all-device regime where every batch depends on
        # the device router. Only the >200K Python-tree drop (add()) may
        # remove it.
        self._side = None
        self._side_native = False
        try:
            from rmqtt_tpu.runtime import NativeTrie

            self._side = NativeTrie()
            self._side_native = True
        except Exception:
            from rmqtt_tpu.core.trie import TopicTree

            self._side = _TreeSide(TopicTree())
        # large batches route adaptively between the trie mirror and the
        # device (ops/hybrid.py): which path wins depends on table scale
        # and chip placement, so the hybrid measures instead of assuming.
        # Adaptivity needs the µs-scale NATIVE trie (the Python fallback
        # only serves the sub-threshold latency path); RMQTT_HYBRID_ADAPT=0
        # pins large batches to the device.
        probe = int(os.environ.get("RMQTT_PROBE_EVERY", "64"))
        if (self._hybrid_max <= 0 or not self._side_native
                or os.environ.get("RMQTT_HYBRID_ADAPT", "1") != "1"):
            # hybrid off pins large batches to the device (the mirror then
            # serves ONLY failover), and adaptivity needs the native trie
            probe = 0
        self._hybrid = AdaptiveHybrid(
            self._side, self.matcher, small_max=self._hybrid_max,
            probe_every=probe,
        )
        # fault-injection sites (utils/failpoints.py): the hybrid fires
        # them on its device branch (ops/hybrid.py) so trie-served batches
        # stay unaffected; the canary below fires them directly because it
        # bypasses the hybrid to exercise the device matcher on purpose
        self._fp_dispatch = FAILPOINTS.register("device.dispatch")
        self._fp_complete = FAILPOINTS.register("device.complete")

    def add(self, topic_filter: str, id: Id, opts: SubscriptionOptions) -> None:
        if self._relations.add(topic_filter, id, opts):
            fid = self.table.add(topic_filter)
            self._fid_to_filter[fid] = topic_filter
            self._filter_to_fid[topic_filter] = fid
            if self._side is not None:
                if not self._side_native and len(self._fid_to_filter) > 200_000:
                    # the Python-trie fallback mirror would duplicate a
                    # million-filter table in dict nodes (GBs of host RAM)
                    # for a fast path that no longer is one — drop it; the
                    # device path serves every batch size
                    self._side = None
                    self._hybrid.side = None
                else:
                    self._side.add(topic_filter, fid)
        # version the match cache on real relations mutations (router base
        # epochs seam), not just device-table inserts; identical
        # re-subscribes don't bump
        if self._relations.last_add_changed:
            self.epochs.bump(topic_filter)

    def remove(self, topic_filter: str, id: Id) -> bool:
        existed, empty = self._relations.remove(topic_filter, id)
        if empty:
            fid = self._filter_to_fid.pop(topic_filter)
            del self._fid_to_filter[fid]
            self.table.remove(fid)
            if self._side is not None:
                self._side.remove(topic_filter, fid)
        if existed:
            self.epochs.bump(topic_filter)
        return existed

    def inline_ok(self, batch_size: int) -> bool:
        # hybrid-served batches on the C++ trie are µs-scale: run them on
        # the event loop. The Python-tree fallback still answers small
        # batches without a device round trip (matches_batch_raw), but its
        # ms-scale DFS must keep the executor hop off the event loop.
        return (self._side is not None and self._side_native
                and batch_size <= self._hybrid_max)

    def matches_raw(self, from_id: Optional[Id], topic: str):
        return self.matches_batch_raw([(from_id, topic)])[0]

    def matches_batch_raw(self, items: Sequence[Tuple[Optional[Id], str]]):
        topics = [topic for _, topic in items]
        tele = self.telemetry
        t0 = time.perf_counter_ns() if tele is not None and tele.enabled else 0
        rows = self._hybrid.match(topics)
        if t0:
            # recorder, not record(): executor threads record this stage
            # concurrently with the loop — append + locked fold keeps
            # totals exact (see telemetry.recorder)
            tele.recorder("kernel.dispatch")(
                time.perf_counter_ns() - t0,
                {"backend": "xla", "batch": len(items)})
        return self._expand(items, rows)

    def _expand(self, items, fid_rows):
        out = []
        f2f = self._fid_to_filter
        for (from_id, _topic), fids in zip(items, fid_rows):
            matched = [f2f[fid] for fid in fids.tolist()]
            out.append(
                expand_matches_raw(matched, self._relations, from_id, self._is_online)
            )
        return out

    # pipelined halves (RoutingService overlap): submit encodes + dispatches,
    # complete fetches + expands — batch N+1's submit runs while batch N is
    # still on the device, cutting burst p99 from sum-of-stages to ~max-stage.
    # submit returns (True, results) when the hybrid served the batch
    # synchronously from the host trie (no pipeline slot needed), else
    # (False, handle) for complete_batch_raw.
    def submit_batch_raw(self, items: Sequence[Tuple[Optional[Id], str]]):
        items = list(items)
        topics = [topic for _, topic in items]
        tele = self.telemetry
        t0 = time.perf_counter_ns() if tele is not None and tele.enabled else 0
        h = self._hybrid.match_submit(topics)
        if h[0] == "sync":
            out = True, self._expand(items, h[1])
            if t0:
                tele.recorder("kernel.dispatch")(
                    time.perf_counter_ns() - t0,
                    {"backend": "xla-sync", "batch": len(items)})
            return out
        # async device dispatch: the kernel stage closes at complete time
        return False, (items, h, t0)

    def complete_batch_raw(self, handle):
        items, h, t0 = handle
        rows = self._hybrid.match_complete(h)
        if t0:
            tele = self.telemetry
            if tele is not None:
                tele.recorder("kernel.dispatch")(
                    time.perf_counter_ns() - t0,
                    {"backend": "xla", "batch": len(items)})
        return self._expand(items, rows)

    def prewarm(self, batch_sizes=(1, 8)) -> None:
        """Pre-compile the device matcher's small dispatch shapes (and
        latch its sticky pad floor) so the first lone publishes after
        start don't pay an XLA compile. Called by RoutingService.start()
        on a background thread; safe no-op for matchers without the hook
        or before any subscription exists (compiles are shape-keyed, so
        warming an empty table still covers the live shapes)."""
        m = getattr(self, "matcher", None)
        if m is not None and hasattr(m, "prewarm"):
            m.prewarm(batch_sizes)

    def set_hybrid_max(self, n: int) -> int:
        """Knob seam (broker/knobs.py): move the trie-vs-device batch
        threshold live — both the inline_ok gate and the hybrid's own
        small_max, which must agree or sub-threshold batches would take
        the executor hop without the trie fast path. → the old value."""
        old = self._hybrid_max
        self._hybrid_max = max(0, int(n))
        self._hybrid.set_small_max(self._hybrid_max)
        return old

    def last_match_was_device(self) -> bool:
        """Did the most recent (synchronously resolved) match run on the
        DEVICE matcher? The routing service consults this before crediting
        a success to the failover breaker — the hybrid's trie-served
        batches say nothing about device health."""
        return self._hybrid.last_backend == "device"

    # ---- host fallback plane (device-plane failover, broker/failover.py).
    # The trie mirror is updated synchronously on every add/remove, so the
    # fallback routes against the CURRENT table — its only staleness is the
    # >200K-filter regime where the Python-tree mirror is dropped (then
    # host_available() is False and failover cannot engage).
    def host_available(self) -> bool:
        return self._side is not None

    def host_inline_ok(self) -> bool:
        # the native trie is µs-scale: run failover batches on the event
        # loop; the Python-tree fallback keeps the executor hop
        return self._side_native

    def host_matches_batch_raw(self, items: Sequence[Tuple[Optional[Id], str]]):
        """Match a batch via the host trie mirror ONLY — no device dispatch,
        no device failpoints. This is the degraded-but-correct routing path
        the failover plane serves publishes through while the breaker around
        the device router is open."""
        side = self._side
        if side is None:
            raise RuntimeError("no host-side trie mirror to fail over to")
        topics = [topic for _, topic in items]
        if len(topics) > 1 and hasattr(side, "match_batch"):
            rows = side.match_batch(list(topics))
        else:
            rows = [side.match(t) for t in topics]
        return self._expand(items, rows)

    def device_rewarm(self) -> None:
        """Force the next device refresh down the FULL pack+upload path
        (half-open probe prelude): the table's layout-epoch bump closes the
        delta gate, so no delta journal state from before the outage can be
        scattered into a table whose device mirror may be gone or torn."""
        t = self.table
        if hasattr(t, "force_full_refresh"):
            t.force_full_refresh()

    def canary_topics(self, k: int = 3) -> List[str]:
        """Concrete topics derived from up to ``k`` live filters (wildcards
        substituted with a literal level) so the failover canary compares
        NON-EMPTY rows whenever the table has routes — a static unmatched
        topic would make the device-vs-trie oracle vacuously pass on a
        device that recovered into silently-wrong matches. ``$``-prefixed
        filters are skipped (their first level has special match rules);
        an empty result tells the caller to fall back to its static topic."""
        out: List[str] = []
        for filt in self._filter_to_fid:
            if len(out) >= k:
                break
            if filt.startswith("$"):
                continue
            out.append("/".join(
                "canary" if lvl in ("+", "#") else lvl
                for lvl in filt.split("/")))
        return out

    def device_canary(self, topics: Sequence[str]) -> bool:
        """One canary match through the DEVICE matcher (bypassing the
        hybrid's trie routing), checked against the host trie oracle. The
        device failpoints stay armed here so a still-injected fault keeps
        the breaker open; the first canary after ``device_rewarm`` performs
        the full HBM re-upload."""
        if self._fp_dispatch.action is not None:
            self._fp_dispatch.fire_sync()
        rows = self.matcher.match(list(topics))
        if self._fp_complete.action is not None:
            self._fp_complete.fire_sync()
        if self._side is None:
            return True
        for topic, fids in zip(topics, rows):
            want = np.sort(np.asarray(self._side.match(topic), dtype=np.int64))
            got = np.sort(np.asarray(fids, dtype=np.int64))
            if want.shape != got.shape or not np.array_equal(want, got):
                return False
        return True

    def device_stats(self) -> Dict[str, float]:
        """Device-table lifecycle counters for RoutingService.stats():
        upload/compaction activity of the HBM mirror (delta vs full, bytes
        shipped, background compactions and their cost, selective
        candidate-cache invalidations)."""
        m, t = self.matcher, self.table
        # per-stage wall attribution (PR9 stage_timing, promoted from
        # bench-only to the live stats surface): cumulative ns → ms totals,
        # zeros while stage_timing is off (the dict exists either way)
        sn = getattr(m, "stage_ns", None) or {}
        return {
            "uploads": getattr(m, "uploads", 0),
            "delta_uploads": getattr(m, "delta_uploads", 0),
            "upload_bytes": getattr(m, "upload_bytes", 0),
            "compactions": getattr(t, "compactions", 0),
            "compact_ms": round(getattr(t, "compact_ms", 0.0), 3),
            "cand_cache_invalidations": getattr(t, "cand_cache_invalidations", 0),
            # batches served end-to-end by the fused device pipeline
            # (ops/partitioned.py): nonzero proves host decode is off the
            # per-batch path
            "fused_batches": getattr(m, "fused_batches", 0),
            "stage_encode_ms_total": round(sn.get("encode", 0) / 1e6, 3),
            "stage_dispatch_ms_total": round(sn.get("dispatch", 0) / 1e6, 3),
            "stage_fetch_ms_total": round(sn.get("fetch", 0) / 1e6, 3),
            "stage_decode_ms_total": round(sn.get("decode", 0) / 1e6, 3),
        }

    def device_hbm(self) -> Dict[str, float]:
        """HBM occupancy model of the device table mirror (tiles, fid map,
        segments) — the device profiler's provider seam
        (broker/devprof.py); {} for matchers without a breakdown."""
        f = getattr(self.matcher, "hbm_breakdown", None)
        return f() if callable(f) else {}

    def is_match(self, topic: str) -> bool:
        if self._side is not None:
            return self._side.match(topic).size > 0
        (fids,) = self.matcher.match([topic])
        return fids.size > 0

    def gets(self, limit: int) -> List[dict]:
        out: List[dict] = []
        for tf, rels in self._relations.items():
            for cid in rels:
                if len(out) >= limit:
                    return out
                out.append({"topic_filter": tf, "client_id": cid})
        return out

    def subscribers_count(self, topic_filter: str, exclude_client=None) -> int:
        rels = self._relations.get(topic_filter)
        n = len(rels)
        if exclude_client is not None and exclude_client in rels:
            n -= 1
        return n

    def topics_count(self) -> int:
        return len(self._relations)

    def routes_count(self) -> int:
        return self._relations.edge_count
