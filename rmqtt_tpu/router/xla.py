"""The north-star router: subscription matching on TPU.

Swaps the reference's trie DFS (`/root/reference/rmqtt/src/router.rs:174-265`)
for the batched XLA matcher over the flattened filter table in device HBM
(see `rmqtt_tpu.ops`). Publish ingress is micro-batched: `matches_batch()`
encodes B topics and resolves all of them in one kernel launch; matched
*filter ids* come back as packed bitmaps and are expanded host-side to
clients via the relations map — the same kernel/host split the reference
uses between trie and ``AllRelationsMap`` (router.rs:121-139), per
SURVEY.md §7 "hard parts".
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from rmqtt_tpu.ops.encode import FilterTable
from rmqtt_tpu.ops.match import TpuMatcher
from rmqtt_tpu.router.base import (
    ClientId,
    Id,
    Router,
    SharedChoiceFn,
    SubRelationsMap,
    SubscriptionOptions,
    round_robin_choice_factory,
)
from rmqtt_tpu.router.relations import RelationsMap, expand_matches_raw


class _TreeSide:
    """Python-trie fallback for the hybrid mirror (NativeTrie API subset)."""

    def __init__(self, tree) -> None:
        self._tree = tree

    def add(self, topic_filter: str, fid: int) -> None:
        self._tree.insert(topic_filter, fid)

    def remove(self, topic_filter: str, fid: int) -> None:
        self._tree.remove(topic_filter, fid)

    def match(self, topic: str):
        # numpy is imported at module scope: this sits on the small-batch
        # dispatch path and must not pay a per-call import lookup
        vals = [v for _lv, vs in self._tree.matches(topic) for v in vs]
        return np.asarray(vals, dtype=np.int64)


class XlaRouter(Router):
    epochs_tracked = True  # add/remove bump the match-cache epochs

    def __init__(
        self,
        shared_choice: Optional[SharedChoiceFn] = None,
        is_online: Callable[[ClientId], bool] = lambda cid: True,
        table=None,
        device=None,
        backend: str = "partitioned",
        mesh="auto",
    ) -> None:
        """``mesh``: a ``jax.sharding.Mesh`` to data-parallelize the
        partitioned matcher over (batch sharded, table replicated);
        ``"auto"`` uses all devices when running on a multi-chip TPU slice
        (single-device and CPU-test environments keep the local matcher);
        ``None`` forces single-device."""
        if mesh not in (None, "auto") and (backend != "partitioned" or device is not None):
            raise ValueError(
                "mesh is only supported with backend='partitioned' and no "
                "explicit device (use parallel.ShardedMatcher for dense)"
            )
        if backend == "partitioned":
            from rmqtt_tpu.ops.partitioned import PartitionedMatcher, PartitionedTable

            self.table = table or PartitionedTable()
            use_mesh = None if mesh == "auto" else mesh
            if mesh == "auto" and device is None:
                try:
                    # the platform guard MUST run before the first backend
                    # touch: jax.devices() hangs forever on a wedged
                    # accelerator grant (tpuprobe; memoized, instant when the
                    # process already chose a platform)
                    from rmqtt_tpu.utils.tpuprobe import ensure_safe_platform

                    if ensure_safe_platform() != "cpu":
                        import jax

                        devs = jax.devices()
                        if len(devs) > 1 and devs[0].platform == "tpu":
                            from rmqtt_tpu.parallel.sharded import make_mesh

                            use_mesh = make_mesh(devices=devs, dp=len(devs), fp=1)
                except Exception:
                    use_mesh = None
            if use_mesh is not None:
                from rmqtt_tpu.parallel.sharded import ShardedPartitionedMatcher

                self.matcher = ShardedPartitionedMatcher(self.table, use_mesh)
            else:
                self.matcher = PartitionedMatcher(self.table, device=device)
        elif backend == "dense":
            self.table = table or FilterTable()
            self.matcher = TpuMatcher(self.table, device=device)
        else:
            raise ValueError(f"unknown backend {backend!r}")
        self._relations = RelationsMap()
        self._fid_to_filter: Dict[int, str] = {}
        self._filter_to_fid: Dict[str, int] = {}
        self._shared_choice = shared_choice or round_robin_choice_factory()
        self._is_online = is_online
        # small-batch hybrid: a host-side trie mirror answers sub-threshold
        # batches inline — one-topic publishes through the device path paid
        # a full dispatch round trip (broker p99 2.4x the trie router,
        # NOTES.md round 2); the device stays for bursts, where batching
        # amortizes the dispatch. Matches the per-message latency contract
        # of `/root/reference/rmqtt/src/shared.rs:735-820`.
        import os

        from rmqtt_tpu.ops.hybrid import AdaptiveHybrid

        self._hybrid_max = int(os.environ.get("RMQTT_HYBRID_MAX", "64"))
        self._side = None
        self._side_native = False
        if self._hybrid_max > 0:
            try:
                from rmqtt_tpu.runtime import NativeTrie

                self._side = NativeTrie()
                self._side_native = True
            except Exception:
                from rmqtt_tpu.core.trie import TopicTree

                self._side = _TreeSide(TopicTree())
        # large batches route adaptively between the trie mirror and the
        # device (ops/hybrid.py): which path wins depends on table scale
        # and chip placement, so the hybrid measures instead of assuming.
        # Adaptivity needs the µs-scale NATIVE trie (the Python fallback
        # only serves the sub-threshold latency path); RMQTT_HYBRID_ADAPT=0
        # pins large batches to the device.
        probe = int(os.environ.get("RMQTT_PROBE_EVERY", "64"))
        if not self._side_native or os.environ.get("RMQTT_HYBRID_ADAPT", "1") != "1":
            probe = 0
        self._hybrid = AdaptiveHybrid(
            self._side, self.matcher, small_max=self._hybrid_max,
            probe_every=probe,
        )

    def add(self, topic_filter: str, id: Id, opts: SubscriptionOptions) -> None:
        if self._relations.add(topic_filter, id, opts):
            fid = self.table.add(topic_filter)
            self._fid_to_filter[fid] = topic_filter
            self._filter_to_fid[topic_filter] = fid
            if self._side is not None:
                if not self._side_native and len(self._fid_to_filter) > 200_000:
                    # the Python-trie fallback mirror would duplicate a
                    # million-filter table in dict nodes (GBs of host RAM)
                    # for a fast path that no longer is one — drop it; the
                    # device path serves every batch size
                    self._side = None
                    self._hybrid.side = None
                else:
                    self._side.add(topic_filter, fid)
        # version the match cache on real relations mutations (router base
        # epochs seam), not just device-table inserts; identical
        # re-subscribes don't bump
        if self._relations.last_add_changed:
            self.epochs.bump(topic_filter)

    def remove(self, topic_filter: str, id: Id) -> bool:
        existed, empty = self._relations.remove(topic_filter, id)
        if empty:
            fid = self._filter_to_fid.pop(topic_filter)
            del self._fid_to_filter[fid]
            self.table.remove(fid)
            if self._side is not None:
                self._side.remove(topic_filter, fid)
        if existed:
            self.epochs.bump(topic_filter)
        return existed

    def inline_ok(self, batch_size: int) -> bool:
        # hybrid-served batches on the C++ trie are µs-scale: run them on
        # the event loop. The Python-tree fallback still answers small
        # batches without a device round trip (matches_batch_raw), but its
        # ms-scale DFS must keep the executor hop off the event loop.
        return (self._side is not None and self._side_native
                and batch_size <= self._hybrid_max)

    def matches_raw(self, from_id: Optional[Id], topic: str):
        return self.matches_batch_raw([(from_id, topic)])[0]

    def matches_batch_raw(self, items: Sequence[Tuple[Optional[Id], str]]):
        topics = [topic for _, topic in items]
        tele = self.telemetry
        t0 = time.perf_counter_ns() if tele is not None and tele.enabled else 0
        rows = self._hybrid.match(topics)
        if t0:
            # recorder, not record(): executor threads record this stage
            # concurrently with the loop — append + locked fold keeps
            # totals exact (see telemetry.recorder)
            tele.recorder("kernel.dispatch")(
                time.perf_counter_ns() - t0,
                {"backend": "xla", "batch": len(items)})
        return self._expand(items, rows)

    def _expand(self, items, fid_rows):
        out = []
        f2f = self._fid_to_filter
        for (from_id, _topic), fids in zip(items, fid_rows):
            matched = [f2f[fid] for fid in fids.tolist()]
            out.append(
                expand_matches_raw(matched, self._relations, from_id, self._is_online)
            )
        return out

    # pipelined halves (RoutingService overlap): submit encodes + dispatches,
    # complete fetches + expands — batch N+1's submit runs while batch N is
    # still on the device, cutting burst p99 from sum-of-stages to ~max-stage.
    # submit returns (True, results) when the hybrid served the batch
    # synchronously from the host trie (no pipeline slot needed), else
    # (False, handle) for complete_batch_raw.
    def submit_batch_raw(self, items: Sequence[Tuple[Optional[Id], str]]):
        items = list(items)
        topics = [topic for _, topic in items]
        tele = self.telemetry
        t0 = time.perf_counter_ns() if tele is not None and tele.enabled else 0
        h = self._hybrid.match_submit(topics)
        if h[0] == "sync":
            out = True, self._expand(items, h[1])
            if t0:
                tele.recorder("kernel.dispatch")(
                    time.perf_counter_ns() - t0,
                    {"backend": "xla-sync", "batch": len(items)})
            return out
        # async device dispatch: the kernel stage closes at complete time
        return False, (items, h, t0)

    def complete_batch_raw(self, handle):
        items, h, t0 = handle
        rows = self._hybrid.match_complete(h)
        if t0:
            tele = self.telemetry
            if tele is not None:
                tele.recorder("kernel.dispatch")(
                    time.perf_counter_ns() - t0,
                    {"backend": "xla", "batch": len(items)})
        return self._expand(items, rows)

    def device_stats(self) -> Dict[str, float]:
        """Device-table lifecycle counters for RoutingService.stats():
        upload/compaction activity of the HBM mirror (delta vs full, bytes
        shipped, background compactions and their cost, selective
        candidate-cache invalidations)."""
        m, t = self.matcher, self.table
        return {
            "uploads": getattr(m, "uploads", 0),
            "delta_uploads": getattr(m, "delta_uploads", 0),
            "upload_bytes": getattr(m, "upload_bytes", 0),
            "compactions": getattr(t, "compactions", 0),
            "compact_ms": round(getattr(t, "compact_ms", 0.0), 3),
            "cand_cache_invalidations": getattr(t, "cand_cache_invalidations", 0),
        }

    def is_match(self, topic: str) -> bool:
        if self._side is not None:
            return self._side.match(topic).size > 0
        (fids,) = self.matcher.match([topic])
        return fids.size > 0

    def gets(self, limit: int) -> List[dict]:
        out: List[dict] = []
        for tf, rels in self._relations.items():
            for cid in rels:
                if len(out) >= limit:
                    return out
                out.append({"topic_filter": tf, "client_id": cid})
        return out

    def subscribers_count(self, topic_filter: str, exclude_client=None) -> int:
        rels = self._relations.get(topic_filter)
        n = len(rels)
        if exclude_client is not None and exclude_client in rels:
            n -= 1
        return n

    def topics_count(self) -> int:
        return len(self._relations)

    def routes_count(self) -> int:
        return self._relations.edge_count
