"""Composable broker scenario harness (ROADMAP item 5).

``rmqtt_tpu.bench.scenarios`` holds the phase primitives (connect storm,
subscribe churn, fan-in/fan-out, overload burst, failpoint kills, durable
QoS1/2 sessions), the named profiles assembled from them, and the shared
``ScenarioReport`` JSON schema every bench/scenario entry point emits —
`scripts/slo_matrix.py` is the CLI, and the legacy bench scripts
(`soak_bench`, `throughput_bench`, `endurance_bench`) converge on the same
report shape.
"""
