"""Scenario matrix: composable load phases → named profiles → one report.

The broker benchmarking literature (PAPERS.md, arxiv 2603.21600) shows
edge/IoT broker behavior is dominated by *mixed* phases — connect storms
while fan-in runs, subscribe churn under overload — which five separate
bench scripts each tested in isolation with divergent ad-hoc JSON. This
module is the convergence point:

phase primitives
    Small async functions (``connect_storm``, ``subscribe_churn``,
    ``fan_in``, ``fan_out``, ``pipe``/``pipe_qos1``, ``overload_burst``,
    ``failpoint_kill``, ``durable_qos``) that each drive one traffic
    shape against a REAL broker (real sockets, real MQTT frames) and
    return one stats row with an ``ok`` verdict.

profiles
    Named compositions (``PROFILES``): phases grouped into steps, phases
    within a step running CONCURRENTLY (the mixed-regime point —
    ``storm_churn_overload_kill`` runs a connect storm, subscribe churn,
    an overload burst and a failpoint-driven device kill all at once).
    Each profile declares the broker config it needs (router, overload
    watermarks, storage plugins) and its ``[slo]`` objectives, so the
    broker-side SLO engine (broker/slo.py) judges the run.

``ScenarioReport``
    One JSON schema (``SCHEMA``) for every runner and legacy script:
    goodput, broker-side per-stage p50/p99 pulled from `/api/v1/latency`,
    reason-labeled drop deltas, RSS (start/peak/end), live burn-rate
    samples observed mid-run, and per-objective SLO verdicts. ``ok``
    gates CI: exit codes follow it (scripts/slo_matrix.py).

The broker runs as a subprocess by default (honest RSS, env knobs like
``RMQTT_HYBRID_MAX=0`` for the all-device kill profile); ``inproc=True``
runs it in-process through the same TOML config path for the tier-1
smoke profile, where re-importing jax per run would dominate.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from rmqtt_tpu.broker.codec import MqttCodec, packets as pk
from rmqtt_tpu.utils.sysmon import rss_mb

SCHEMA = "rmqtt_tpu.scenario_report/1"

REPO = Path(__file__).resolve().parent.parent.parent


# ----------------------------------------------------------------- report
def base_report(profile: str, mode: str = "subprocess") -> dict:
    """The shared ScenarioReport skeleton every entry point fills."""
    return {
        "schema": SCHEMA,
        "profile": profile,
        "mode": mode,
        "started_at": round(time.time(), 3),
        "duration_s": None,
        "ok": None,
        "phases": [],
        "goodput": {},
        "latency": {},
        "drops": {},
        "rss_mb": {},
        "slo": None,
        "slo_live": None,
        "errors": [],
    }


def finish_report(report: dict, ok: bool) -> dict:
    report["duration_s"] = round(time.time() - report["started_at"], 3)
    report["ok"] = bool(ok)
    return report


def write_report(report: dict, out: Optional[str]) -> None:
    """One compact JSON line to stdout (the machine-readable contract)
    plus an optional pretty file."""
    print(json.dumps(report, sort_keys=True))
    if out:
        Path(out).write_text(json.dumps(report, indent=2, sort_keys=True) + "\n")
        print(f"report -> {out} (ok={report['ok']})", file=sys.stderr)


def latency_stages(latency_body: dict) -> dict:
    """`/api/v1/latency` → {stage: {count, p50_ms, p99_ms}} for the report
    (ns → ms; count-unit stages keep raw units)."""
    out = {}
    for stage, row in (latency_body.get("histograms") or {}).items():
        if not row.get("count"):
            continue
        if row.get("unit") == "ns":
            out[stage] = {"count": row["count"],
                          "p50_ms": round(row["p50"] / 1e6, 3),
                          "p99_ms": round(row["p99"] / 1e6, 3)}
        else:
            out[stage] = {"count": row["count"], "p50": row["p50"],
                          "p99": row["p99"], "unit": row.get("unit")}
    return out


def drop_deltas(metrics0: dict, metrics1: dict) -> dict:
    """Reason-labeled drop-counter deltas across the run."""
    out = {}
    for key, after in metrics1.items():
        if not key.startswith("messages.dropped"):
            continue
        delta = after - metrics0.get(key, 0)
        if delta:
            reason = key[len("messages.dropped."):] or "total"
            out["total" if key == "messages.dropped" else reason] = delta
    return out


# ---------------------------------------------------------- mini client
class MiniClient:
    """Bench-grade asyncio MQTT client: enough for the phases (CONNECT,
    SUBSCRIBE/UNSUBSCRIBE, QoS0/1/2 publish + receive with auto-ack) and
    nothing more. Tests use the richer tests/mqtt_client.py; this one
    lives with the bench package so the runner has no test imports."""

    def __init__(self, reader, writer, codec) -> None:
        self.reader = reader
        self.writer = writer
        self.codec = codec
        self.publishes: asyncio.Queue = asyncio.Queue()
        self.received = 0
        self.auto_ack = True
        self._acks: Dict[tuple, asyncio.Future] = {}
        self._pid = 0
        self._task: Optional[asyncio.Task] = None

    @classmethod
    async def connect(cls, port: int, cid: str, clean_start: bool = True,
                      keepalive: int = 120, retries: int = 4,
                      host: str = "127.0.0.1",
                      auto_ack: bool = True) -> "MiniClient":
        """``auto_ack`` must be set HERE, not after connect returns: a
        resumed session's queued deliveries start arriving the moment the
        CONNACK lands, racing any post-connect attribute flip."""
        last: Optional[Exception] = None
        for attempt in range(retries):
            writer = c = None
            try:
                reader, writer = await asyncio.open_connection(host, port)
                codec = MqttCodec()
                writer.write(codec.encode(pk.Connect(
                    client_id=cid, clean_start=clean_start,
                    keepalive=keepalive)))
                await writer.drain()
                c = cls(reader, writer, codec)
                c.auto_ack = auto_ack
                c._task = asyncio.ensure_future(c._read_loop())
                ack = await c._wait(("connack",), timeout=10.0)
                if ack.reason_code != 0:
                    raise ConnectionError(f"refused rc={ack.reason_code}")
                return c
            except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                # the handshake busy gate legitimately refuses storms; the
                # failed attempt must not leak its socket or read task (the
                # broker would keep counting it as a live connection)
                last = e
                if c is not None:
                    await c.close()
                elif writer is not None:
                    try:
                        writer.close()
                    except Exception:
                        pass
                await asyncio.sleep(0.2 * (attempt + 1))
        raise last if last is not None else ConnectionError("connect failed")

    async def _read_loop(self) -> None:
        try:
            while True:
                data = await self.reader.read(1 << 16)
                if not data:
                    return
                for p in self.codec.feed(data):
                    await self._on_packet(p)
        except (ConnectionError, asyncio.CancelledError, OSError):
            pass

    async def _on_packet(self, p) -> None:
        if isinstance(p, pk.Connack):
            self._resolve(("connack",), p)
        elif isinstance(p, pk.Publish):
            self.received += 1
            if self.auto_ack:
                if p.qos == 1:
                    await self._send(pk.Puback(p.packet_id))
                elif p.qos == 2:
                    await self._send(pk.Pubrec(p.packet_id))
            await self.publishes.put(p)
        elif isinstance(p, pk.Puback):
            self._resolve(("puback", p.packet_id), p)
        elif isinstance(p, pk.Pubrec):
            self._resolve(("pubrec", p.packet_id), p)
            await self._send(pk.Pubrel(p.packet_id))
        elif isinstance(p, pk.Pubcomp):
            self._resolve(("pubcomp", p.packet_id), p)
        elif isinstance(p, pk.Pubrel):
            await self._send(pk.Pubcomp(p.packet_id))
        elif isinstance(p, pk.Suback):
            self._resolve(("suback", p.packet_id), p)
        elif isinstance(p, pk.Unsuback):
            self._resolve(("unsuback", p.packet_id), p)

    async def _send(self, p) -> None:
        self.writer.write(self.codec.encode(p))
        await self.writer.drain()

    def _resolve(self, key, value) -> None:
        fut = self._acks.get(key)
        if fut is not None and not fut.done():
            fut.set_result(value)

    async def _wait(self, key, timeout: float = 10.0):
        fut = asyncio.get_running_loop().create_future()
        self._acks[key] = fut
        try:
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._acks.pop(key, None)

    def _next_pid(self) -> int:
        self._pid = self._pid % 65000 + 1
        return self._pid

    async def subscribe(self, *filters: str, qos: int = 0) -> None:
        pid = self._next_pid()
        await self._send(pk.Subscribe(
            pid, [(f, pk.SubOpts(qos=qos)) for f in filters]))
        await self._wait(("suback", pid))

    async def unsubscribe(self, *filters: str) -> None:
        pid = self._next_pid()
        await self._send(pk.Unsubscribe(pid, list(filters)))
        await self._wait(("unsuback", pid))

    async def publish(self, topic: str, payload: bytes = b"x", qos: int = 0,
                      retain: bool = False) -> None:
        pid = self._next_pid() if qos else None
        await self._send(pk.Publish(topic=topic, payload=payload, qos=qos,
                                    retain=retain, packet_id=pid))
        if qos == 1:
            await self._wait(("puback", pid))
        elif qos == 2:
            await self._wait(("pubcomp", pid))

    async def blast(self, topic: str, n: int, payload: bytes = b"x" * 64,
                    chunk: int = 64, pause_s: float = 0.0) -> None:
        """QoS0 firehose: pre-encoded frame written in chunks so the bench
        client isn't the syscall bottleneck; ``pause_s`` spreads the blast
        so broker-side samplers (overload/SLO) get ticks mid-burst."""
        frame = self.codec.encode(pk.Publish(topic=topic, payload=payload))
        full, rest = divmod(n, chunk)
        batch = frame * chunk
        for _ in range(full):
            self.writer.write(batch)
            if self.writer.transport.get_write_buffer_size() > 1 << 20:
                await self.writer.drain()
            if pause_s:
                await asyncio.sleep(pause_s)
        self.writer.write(frame * rest)
        await self.writer.drain()

    async def drain(self, want: int, timeout: float = 30.0) -> int:
        """Receive until ``want`` publishes or timeout; returns the count."""
        deadline = time.monotonic() + timeout
        got = 0
        while got < want:
            left = deadline - time.monotonic()
            if left <= 0:
                break
            try:
                await asyncio.wait_for(self.publishes.get(), left)
            except asyncio.TimeoutError:
                break
            got += 1
        return got

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
        try:
            self.writer.close()
        except Exception:
            pass


async def _http_json(port: int, path: str, method: str = "GET",
                     obj: Any = None, timeout: float = 10.0):
    """One admin-API round trip against the broker's HTTP port."""
    payload = json.dumps(obj).encode() if obj is not None else b""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    try:
        writer.write(
            f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload)
        await writer.drain()
        status_line = await asyncio.wait_for(reader.readline(), timeout)
        status = int(status_line.split()[1])
        length = 0
        while True:
            line = await asyncio.wait_for(reader.readline(), timeout)
            if line in (b"\r\n", b""):
                break
            k, _, v = line.decode("latin1").partition(":")
            if k.strip().lower() == "content-length":
                length = int(v)
        body = await asyncio.wait_for(reader.readexactly(length), timeout)
        return status, json.loads(body)
    finally:
        writer.close()


# --------------------------------------------------------------- profiles
@dataclass
class Profile:
    """One named scenario: broker shape + SLO objectives + phase steps."""

    name: str
    descr: str
    #: steps run in order; phases WITHIN a step run concurrently
    steps: Tuple[Tuple[Tuple[str, Callable, Dict[str, Any]], ...], ...]
    #: [[slo.objectives]] rows written into the broker's config
    slo: Tuple[Dict[str, Any], ...] = ()
    router: str = "trie"
    #: extra TOML appended to the generated config ({workdir} formatted in)
    extra_toml: str = ""
    #: subprocess env overrides (e.g. RMQTT_HYBRID_MAX=0)
    env: Dict[str, str] = field(default_factory=dict)
    slo_sample_interval: float = 0.25
    slo_fast_window_s: float = 3.0
    slo_slow_window_s: float = 15.0
    #: profiles whose broker shape needs env knobs or real process
    #: isolation refuse the in-process fast path
    subprocess_only: bool = False
    #: custom orchestrator: a profile that cannot run as phases against ONE
    #: broker (the multi-node cluster scenarios) supplies its own
    #: ``async runner(profile, inproc, workdir) -> ScenarioReport`` and
    #: run_profile_async delegates to it wholesale
    runner: Optional[Callable] = None


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _toml_value(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_toml_value(x) for x in v) + "]"
    return json.dumps(str(v))


def profile_toml(profile: Profile, port: int, api_port: int,
                 workdir: str) -> str:
    """The broker config one profile runs under: telemetry + SLO engine on
    with bench-scale windows, the profile's objectives as
    [[slo.objectives]] rows, and its extra sections appended."""
    lines = [
        "[listener]", 'host = "127.0.0.1"', f"port = {port}", "",
        "[node]", f'router = "{profile.router}"', "",
        "[observability]", "enable = true", "slow_ms = 250.0", "",
        "[slo]", "enable = true",
        f"sample_interval = {profile.slo_sample_interval}",
        f"fast_window_s = {profile.slo_fast_window_s}",
        f"slow_window_s = {profile.slo_slow_window_s}", "",
    ]
    for obj in profile.slo:
        lines.append("[[slo.objectives]]")
        lines.extend(f"{k} = {_toml_value(v)}" for k, v in obj.items())
        lines.append("")
    lines += ["[http_api]", 'host = "127.0.0.1"', f"port = {api_port}", "",
              "[log]", 'to = "off"', ""]
    extra = profile.extra_toml.format(workdir=workdir)
    return "\n".join(lines) + extra + "\n"


class ScenarioBroker:
    """The broker under test + its admin API, subprocess or in-process.

    Subprocess is the default (own RSS, own env, real process isolation);
    ``inproc`` drives the SAME TOML through conf.load into an in-process
    MqttBroker for the tier-1 smoke profile, where paying a jax re-import
    per run would dominate the runtime."""

    def __init__(self, profile: Profile, workdir: str,
                 inproc: bool = False) -> None:
        if inproc and profile.subprocess_only:
            raise ValueError(f"profile {profile.name} needs a subprocess "
                             f"broker (env overrides / process isolation)")
        self.profile = profile
        self.workdir = workdir
        self.inproc = inproc
        self.port = _free_port()
        self.api_port = _free_port()
        self.proc: Optional[subprocess.Popen] = None
        self._inproc_broker = None
        self._inproc_api = None
        self._inproc_cluster = None

    async def start(self) -> None:
        conf_path = Path(self.workdir) / "rmqtt.toml"
        conf_path.write_text(
            profile_toml(self.profile, self.port, self.api_port,
                         self.workdir))
        if self.inproc:
            from rmqtt_tpu import conf
            from rmqtt_tpu.broker.context import ServerContext
            from rmqtt_tpu.broker.http_api import HttpApi
            from rmqtt_tpu.broker.server import MqttBroker

            settings = conf.load(str(conf_path))
            broker = MqttBroker(ServerContext(settings.broker))
            conf.instantiate_plugins(broker.ctx, settings)
            api = HttpApi(broker.ctx, **settings.http_api)
            await broker.start()
            await api.start()
            self._inproc_broker, self._inproc_api = broker, api
        else:
            env = dict(os.environ, JAX_PLATFORMS="cpu", **self.profile.env)
            # append: a crash-torture restart must not truncate the
            # killed process's log (it is the post-mortem)
            log_f = open(Path(self.workdir) / "broker.log", "ab")
            self.proc = subprocess.Popen(
                [sys.executable, "-m", "rmqtt_tpu.broker",
                 "--config", str(conf_path)],
                cwd=str(REPO), env=env, stdout=log_f, stderr=log_f)
            log_f.close()
        deadline = time.monotonic() + 120.0
        for check_port in (self.port, self.api_port):
            while True:
                if self.proc is not None and self.proc.poll() is not None:
                    tail = (Path(self.workdir) / "broker.log").read_bytes()[-2000:]
                    raise RuntimeError(
                        f"broker exited rc={self.proc.returncode} before "
                        f"listening: ...{tail.decode(errors='replace')}")
                try:
                    with socket.create_connection(
                        ("127.0.0.1", check_port), timeout=0.3
                    ):
                        break
                except OSError:
                    if time.monotonic() > deadline:
                        raise RuntimeError("broker never started listening")
                    await asyncio.sleep(0.15)

    def rss(self) -> float:
        return rss_mb(self.proc.pid if self.proc is not None else None)

    async def api(self, path: str, method: str = "GET", obj: Any = None):
        status, body = await _http_json(self.api_port, path, method, obj)
        if status != 200:
            raise RuntimeError(f"{method} {path} -> {status}: {body}")
        return body

    def kill(self) -> None:
        """SIGKILL the broker subprocess — no shutdown path runs, no
        flush, no goodbyes (the crash-torture primitive). ``start()``
        again restarts it on the same ports and workdir."""
        if self.proc is not None:
            self.proc.kill()
            self.proc.wait(timeout=10)
            self.proc = None

    async def stop(self) -> None:
        if self.inproc:
            if self._inproc_api is not None:
                await self._inproc_api.stop()
            if self._inproc_broker is not None:
                await self._inproc_broker.stop()
        elif self.proc is not None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)


# ------------------------------------------------------- phase primitives
# every phase: async (broker, **params) -> stats dict with an "ok" bool

async def connect_storm(broker, conns: int = 100, wave: int = 25,
                        hold_s: float = 0.3,
                        min_established_frac: float = 0.95) -> dict:
    """Dial ``conns`` connections in waves (the storm regime), hold them
    briefly, then close; the broker's busy gate may refuse mid-wave —
    clients retry like real fleets do."""
    clients: List[MiniClient] = []
    failures = 0
    t0 = time.monotonic()
    tag = f"storm-{int(t0 * 1000) % 100000}"
    for start in range(0, conns, wave):
        n = min(wave, conns - start)
        results = await asyncio.gather(
            *(MiniClient.connect(broker.port, f"{tag}-{start + i}")
              for i in range(n)),
            return_exceptions=True)
        for r in results:
            if isinstance(r, BaseException):
                failures += 1
            else:
                clients.append(r)
    secs = time.monotonic() - t0
    await asyncio.sleep(hold_s)
    for c in clients:
        await c.close()
    established = len(clients)
    return {
        "ok": established >= conns * min_established_frac,
        "established": established, "failures": failures,
        "seconds": round(secs, 3),
        "handshakes_per_s": round(established / secs, 1) if secs else 0.0,
    }


async def subscribe_churn(broker, clients: int = 8, rounds: int = 12,
                          filters_per: int = 4) -> dict:
    """Wildcard subscribe/unsubscribe churn: every round each client swaps
    its whole filter set — the regime that invalidates match caches and
    (on device routers) dirties the HBM table."""
    conns = [await MiniClient.connect(broker.port, f"churn-{i}")
             for i in range(clients)]
    subs = unsubs = 0
    t0 = time.monotonic()
    try:
        for r in range(rounds):
            for i, c in enumerate(conns):
                filters = [f"churn/{i}/{r % 3}/{j}/+" for j in range(filters_per)]
                await c.subscribe(*filters, qos=0)
                subs += len(filters)
                await c.unsubscribe(*filters)
                unsubs += len(filters)
    finally:
        for c in conns:
            await c.close()
    return {"ok": True, "subscribes": subs, "unsubscribes": unsubs,
            "seconds": round(time.monotonic() - t0, 3)}


async def fan_in(broker, pubs: int = 16, msgs_per: int = 120, qos: int = 0,
                 payload: int = 64, min_delivery_frac: float = 1.0,
                 topic_prefix: str = "fi") -> dict:
    """N publishers → 1 subscriber (device-fleet telemetry ingest)."""
    sub = await MiniClient.connect(broker.port, f"{topic_prefix}-sub")
    await sub.subscribe(f"{topic_prefix}/#", qos=qos)
    publishers = [await MiniClient.connect(broker.port, f"{topic_prefix}-p{i}")
                  for i in range(pubs)]
    expected = pubs * msgs_per
    t0 = time.monotonic()
    try:
        if qos == 0:
            await asyncio.gather(*(
                p.blast(f"{topic_prefix}/{i}", msgs_per, b"x" * payload)
                for i, p in enumerate(publishers)))
        else:
            async def _pump(i, p):
                for k in range(msgs_per):
                    await p.publish(f"{topic_prefix}/{i}", b"x" * payload,
                                    qos=qos)
            await asyncio.gather(*(
                _pump(i, p) for i, p in enumerate(publishers)))
        got = await sub.drain(expected, timeout=60.0)
    finally:
        for c in [sub, *publishers]:
            await c.close()
    secs = time.monotonic() - t0
    return {
        "ok": got >= expected * min_delivery_frac,
        "published": expected, "delivered": got,
        "seconds": round(secs, 3),
        "msgs_per_s": round(got / secs, 1) if secs else 0.0,
    }


async def fan_out(broker, subs: int = 20, msgs: int = 120, qos: int = 0,
                  payload: int = 64, min_delivery_frac: float = 1.0,
                  topic: str = "fo/cmd") -> dict:
    """1 publisher → N subscribers (command fan-out to a fleet)."""
    subscribers = [await MiniClient.connect(broker.port, f"fo-s{i}")
                   for i in range(subs)]
    for c in subscribers:
        await c.subscribe(topic, qos=qos)
    publ = await MiniClient.connect(broker.port, "fo-pub")
    t0 = time.monotonic()
    try:
        if qos == 0:
            await publ.blast(topic, msgs, b"x" * payload)
        else:
            for _ in range(msgs):
                await publ.publish(topic, b"x" * payload, qos=qos)
        got = sum(await asyncio.gather(*(
            c.drain(msgs, timeout=60.0) for c in subscribers)))
    finally:
        for c in [publ, *subscribers]:
            await c.close()
    secs = time.monotonic() - t0
    expected = subs * msgs
    return {
        "ok": got >= expected * min_delivery_frac,
        "published": msgs, "delivered": got, "expected": expected,
        "seconds": round(secs, 3),
        "deliveries_per_s": round(got / secs, 1) if secs else 0.0,
    }


async def pipe(broker, msgs: int = 5000, payload: int = 64) -> dict:
    """1→1 QoS0 pipe (raw throughput floor) — fan_in degenerate case."""
    return await fan_in(broker, pubs=1, msgs_per=msgs, qos=0,
                        payload=payload, topic_prefix="pipe")


async def pipe_qos1(broker, msgs: int = 2000, payload: int = 64,
                    window: int = 64) -> dict:
    """1→1 QoS1 pipe, publisher pipelined ``window`` deep and paced by
    deliveries (stays under the broker's bounded deliver queue, so
    nothing is policy-dropped) — the lossless end-to-end figure."""
    sub = await MiniClient.connect(broker.port, "pq1-sub")
    await sub.subscribe("pq1/t", qos=1)
    publ = await MiniClient.connect(broker.port, "pq1-pub")
    t0 = time.monotonic()
    deadline = t0 + 120.0
    state = {"sent": 0, "got": 0}
    try:
        # BOTH halves share the deadline: if deliveries stall, the paced
        # sender would otherwise spin forever after the receiver gives up
        # and the whole profile would hang instead of reporting FAIL
        async def sender():
            while state["sent"] < msgs and time.monotonic() < deadline:
                if state["sent"] - state["got"] >= window * 4:
                    await asyncio.sleep(0.002)
                    continue
                burst = bytearray()
                for _ in range(min(window, msgs - state["sent"])):
                    state["sent"] += 1
                    burst += publ.codec.encode(pk.Publish(
                        topic="pq1/t", payload=b"x" * payload, qos=1,
                        packet_id=(state["sent"] % 65000) + 1))
                publ.writer.write(bytes(burst))
                await publ.writer.drain()

        async def receiver():
            while state["got"] < msgs and time.monotonic() < deadline:
                try:
                    await asyncio.wait_for(sub.publishes.get(), 2.0)
                except asyncio.TimeoutError:
                    continue
                state["got"] += 1

        await asyncio.gather(sender(), receiver())
    finally:
        for c in (sub, publ):
            await c.close()
    secs = time.monotonic() - t0
    return {
        "ok": state["got"] == msgs,
        "published": state["sent"], "delivered": state["got"],
        "seconds": round(secs, 3),
        "msgs_per_s": round(state["got"] / secs, 1) if secs else 0.0,
    }


async def overload_burst(broker, msgs: int = 5000, payload: int = 1024,
                         pulses: int = 10, pulse_gap_s: float = 0.1,
                         expect_drops: Tuple[str, ...] = (
                             "shed_qos0", "queue_full")) -> dict:
    """QoS0 firehose at a NON-READING subscriber: its deliver queue backs
    up past the overload watermarks, the controller escalates, and QoS0
    is shed/dropped by policy. The phase verdict is that the protection
    ENGAGED (reason-labeled drops appeared), not that everything arrived
    — profiles pair this with availability objectives that exclude the
    intentional reasons."""
    # raw, loop-less subscriber with a TINY receive buffer set BEFORE
    # connect: the backlog must land in the broker's deliver queue (the
    # thing the controller manages), not in kernel socket buffering — the
    # blast volume is sized past the broker-side sndbuf cap on top
    sk = socket.socket()
    sk.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
    sk.setblocking(False)
    await asyncio.get_running_loop().sock_connect(
        sk, ("127.0.0.1", broker.port))
    reader, writer = await asyncio.open_connection(sock=sk)
    codec = MqttCodec()
    writer.write(codec.encode(pk.Connect(client_id="ob-sub", keepalive=120)))
    writer.write(codec.encode(pk.Subscribe(1, [("ob/t", pk.SubOpts(qos=0))])))
    await writer.drain()
    # consume CONNACK/SUBACK then stop reading for good
    await reader.read(64)
    m0 = await broker.api("/api/v1/metrics")
    publ = await MiniClient.connect(broker.port, "ob-pub")
    t0 = time.monotonic()
    try:
        per = max(1, msgs // pulses)
        for _ in range(pulses):
            await publ.blast("ob/t", per, b"x" * payload)
            await asyncio.sleep(pulse_gap_s)  # let the samplers tick
    finally:
        await publ.close()
        try:
            writer.close()
        except Exception:
            pass
    m1 = await broker.api("/api/v1/metrics")
    drops = drop_deltas(m0.get("metrics", {}), m1.get("metrics", {}))
    ov = await broker.api("/api/v1/overload")
    engaged = any(drops.get(r, 0) > 0 for r in expect_drops)
    return {
        "ok": engaged,
        "published": msgs, "drops": drops,
        "overload_state": ov.get("state"),
        "overload_transitions": ov.get("transitions"),
        "seconds": round(time.monotonic() - t0, 3),
    }


async def failpoint_kill(broker, site: str = "device.dispatch",
                         action: str = "times(4, error)",
                         msgs: int = 10, settle_s: float = 20.0,
                         expect_failover: bool = False) -> dict:
    """Arm a PR6 failpoint over the live HTTP surface mid-traffic (device
    kill by default), publish QoS1 through the fault window, disarm, and
    wait for the failover plane to switch back. Contract: zero lost."""
    sub = await MiniClient.connect(broker.port, "fk-sub")
    await sub.subscribe("fk/#", qos=1)
    publ = await MiniClient.connect(broker.port, "fk-pub")
    sent = 0
    t0 = time.monotonic()
    try:
        for i in range(3):  # healthy warmup (JIT, cache)
            await publ.publish(f"fk/{i % 2}", b"warm", qos=1)
            sent += 1
        fp0 = (await broker.api("/api/v1/failpoints"))["failpoints"]
        base = fp0.get(site, {}).get("triggers", 0)
        await broker.api("/api/v1/failpoints", "PUT", {site: action})
        for i in range(msgs):
            await publ.publish(f"fk/{i % 2}", f"fault-{i}".encode(), qos=1)
            sent += 1
        await broker.api("/api/v1/failpoints", "PUT", {site: "off"})
        # wait for the failover plane to recover (probe + switchback)
        deadline = time.monotonic() + settle_s
        fo = {}
        while time.monotonic() < deadline:
            fo = await broker.api("/api/v1/routing/failover")
            if fo.get("state") in ("device", "unavailable"):
                break
            await asyncio.sleep(0.2)
        for i in range(3):
            await publ.publish(f"fk/{i % 2}", b"post", qos=1)
            sent += 1
        got = await sub.drain(sent, timeout=30.0)
        fp1 = (await broker.api("/api/v1/failpoints"))["failpoints"]
        triggers = fp1.get(site, {}).get("triggers", 0) - base
        engaged = (not expect_failover) or fo.get("failovers", 0) >= 1
        return {
            "ok": got == sent and triggers > 0 and engaged,
            "published": sent, "delivered": got, "triggers": triggers,
            "failovers": fo.get("failovers"),
            "switchbacks": fo.get("switchbacks"),
            "failover_state": fo.get("state"),
            "seconds": round(time.monotonic() - t0, 3),
        }
    finally:
        for c in (sub, publ):
            await c.close()


async def durable_qos(broker, msgs: int = 60, qos: int = 1,
                      payload: int = 48) -> dict:
    """The durable-path profile: QoS1/2 publishes through the message
    storage plugin into an OFFLINE persistent session, resume, then a
    mid-delivery session TAKEOVER with unacked in-flight messages — the
    inflight-resend seam. Contract: every payload reaches the durable
    subscriber at least once (exactly once stays the tests' pin)."""
    cid = f"dur{qos}"
    topic = f"dq{qos}/t"
    sub = await MiniClient.connect(broker.port, cid, clean_start=False)
    await sub.subscribe(f"dq{qos}/#", qos=qos)
    await sub.close()  # offline, session persists (v3 clean_session=0)
    publ = await MiniClient.connect(broker.port, f"dq{qos}-pub")
    t0 = time.monotonic()
    try:
        for i in range(msgs):
            await publ.publish(topic, f"m-{i}".encode(), qos=qos)
    finally:
        await publ.close()
    # resume WITHOUT acking: deliveries land, the in-flight window fills
    # with unacked entries — exactly the state a takeover must transfer
    seen: set = set()
    duplicates = 0
    sub2 = await MiniClient.connect(broker.port, cid, clean_start=False,
                                    auto_ack=False)
    first_deadline = time.monotonic() + 15.0
    first = 0
    while first < min(10, msgs) and time.monotonic() < first_deadline:
        try:
            p = await asyncio.wait_for(sub2.publishes.get(), 2.0)
        except asyncio.TimeoutError:
            break
        first += 1
        seen.add(bytes(p.payload))
    # takeover: same client id, new connection; the broker transfers the
    # session and RESENDS the unacked in-flight window (DUP) alongside
    # the still-queued remainder — nothing the old connection left
    # unacked may be lost
    sub3 = await MiniClient.connect(broker.port, cid, clean_start=False)
    deadline = time.monotonic() + 30.0
    while len(seen) < msgs and time.monotonic() < deadline:
        try:
            p = await asyncio.wait_for(sub3.publishes.get(), 2.0)
        except asyncio.TimeoutError:
            continue
        if bytes(p.payload) in seen:
            duplicates += 1  # QoS1 redelivery after takeover is legal
        seen.add(bytes(p.payload))
    await sub2.close()
    await sub3.close()
    return {
        "ok": len(seen) == msgs,
        "published": msgs,
        "distinct_delivered": len(seen),
        "lost": msgs - len(seen),
        "duplicates": duplicates,
        "delivered_first_conn": first,
        "seconds": round(time.monotonic() - t0, 3),
    }


# ------------------------------------------------------------ the matrix
_OVERLOAD_TOML = """
[overload]
enable = true
sample_interval = 0.1
mqueue_elevated = 0.15
mqueue_critical = 0.6
queue_elevated = 0.5
queue_critical = 0.9
shed_slow_fraction = 0.15
"""

_STORAGE_TOML = """
[plugins]
default_startups = ["rmqtt-message-storage"]

[plugins.rmqtt-message-storage]
path = "{workdir}/messages.db"
"""

#: availability objective variants: strict (nothing may drop beyond a
#: close-race sliver) and one that treats overload-policy drops as
#: intentional, not failure
_AVAIL_STRICT = {"name": "delivery", "kind": "availability", "target": 0.995}
_AVAIL_SHED_OK = {"name": "delivery", "kind": "availability",
                  "target": 0.98,
                  "exclude_reasons": ["shed_qos0", "queue_full"]}


def _lat(name: str, stage: str, threshold_ms: float,
         target: float) -> Dict[str, Any]:
    return {"name": name, "kind": "latency", "stage": stage,
            "threshold_ms": threshold_ms, "target": target}


PROFILES: Dict[str, Profile] = {}


def _profile(p: Profile) -> Profile:
    PROFILES[p.name] = p
    return p


_profile(Profile(
    name="device_fleet_fanin",
    descr="connect storm then telemetry fan-in: many devices, one ingest",
    steps=(
        (("connect_storm", connect_storm, {"conns": 120, "wave": 40}),),
        (("fan_in", fan_in, {"pubs": 24, "msgs_per": 120}),),
    ),
    slo=(
        _lat("publish-p99", "publish.e2e", 2000.0, 0.95),
        _lat("connect-p99", "connect.handshake", 2000.0, 0.9),
        _AVAIL_STRICT,
    ),
))

_profile(Profile(
    name="command_fanout",
    descr="one commander, a fleet of listeners: fan-out under light churn",
    steps=(
        (("connect_storm", connect_storm, {"conns": 60, "wave": 30}),),
        (("fan_out", fan_out, {"subs": 30, "msgs": 120}),
         ("subscribe_churn", subscribe_churn,
          {"clients": 4, "rounds": 8})),
    ),
    slo=(
        _lat("publish-p99", "publish.e2e", 2000.0, 0.95),
        _AVAIL_STRICT,
    ),
))

_profile(Profile(
    name="storm_churn_overload_kill",
    descr="everything at once: connect storm + subscribe churn + QoS0 "
          "overload burst + failpoint-driven device kill, on the device "
          "router with the failover plane live",
    steps=(
        (("connect_storm", connect_storm,
          {"conns": 60, "wave": 20, "min_established_frac": 0.9}),
         ("subscribe_churn", subscribe_churn, {"clients": 4, "rounds": 6}),
         ("overload_burst", overload_burst, {}),
         ("failpoint_kill", failpoint_kill,
          {"site": "device.dispatch", "action": "times(6, error)",
           "msgs": 14, "expect_failover": True})),
    ),
    slo=(
        # generous latency bound: four regimes share one CPU core here —
        # the objective pins "no collapse", profiles on real fleets tighten
        _lat("publish-p99", "publish.e2e", 8000.0, 0.8),
        _AVAIL_SHED_OK,
    ),
    router="xla",
    extra_toml=_OVERLOAD_TOML + """
[routing]
cache = false
failover_timeout_s = 30.0
failover_threshold = 2
failover_cooldown = 0.3
failover_k_successes = 2
""",
    # all-device regime: every batch crosses the device plane, so the
    # kill phase actually kills the serving path (PR6 keeps the host
    # mirror alive as the failover target even with hybrid off)
    env={"RMQTT_HYBRID_MAX": "0", "RMQTT_HYBRID_ADAPT": "0"},
    subprocess_only=True,
    slo_fast_window_s=4.0,
    slo_slow_window_s=30.0,
))

_profile(Profile(
    name="durable_qos12",
    descr="QoS1+QoS2 through sqlite message storage into persistent "
          "sessions: offline queueing, resume, mid-flight takeover with "
          "inflight resend, under concurrent background load",
    steps=(
        (("durable_qos1", durable_qos, {"msgs": 60, "qos": 1}),
         ("durable_qos2", durable_qos, {"msgs": 40, "qos": 2}),
         ("background_fanout", fan_out, {"subs": 6, "msgs": 200})),
    ),
    slo=(
        _lat("publish-p99", "publish.e2e", 4000.0, 0.9),
        _AVAIL_STRICT,
    ),
    extra_toml=_STORAGE_TOML,
))

_profile(Profile(
    name="smoke_fast",
    descr="seconds-long tier-1 smoke: storm + churn + shed phases with "
          "the SLO verdict asserted (keeps the harness itself from "
          "rotting)",
    steps=(
        (("connect_storm", connect_storm, {"conns": 24, "wave": 12}),
         ("subscribe_churn", subscribe_churn,
          {"clients": 3, "rounds": 4})),
        (("overload_burst", overload_burst, {}),),
    ),
    slo=(
        _lat("publish-p99", "publish.e2e", 8000.0, 0.8),
        _AVAIL_SHED_OK,
    ),
    extra_toml=_OVERLOAD_TOML,
    slo_sample_interval=0.2,
    slo_fast_window_s=2.0,
    slo_slow_window_s=8.0,
))

_profile(Profile(
    name="throughput_suite",
    descr="the legacy throughput_bench scenarios as one profile: QoS0 "
          "pipe, paced QoS1 pipe, fan-out, fan-in",
    steps=(
        (("pipe", pipe, {"msgs": 20000}),),
        (("pipe_qos1", pipe_qos1, {"msgs": 4000}),),
        (("fan_out", fan_out, {"subs": 50, "msgs": 400}),),
        (("fan_in", fan_in, {"pubs": 50, "msgs_per": 400}),),
    ),
    slo=(
        _lat("publish-p99", "publish.e2e", 4000.0, 0.9),
        _AVAIL_STRICT,
    ),
))

# ------------------------------------------- multi-node cluster scenario
class ClusterProcNode:
    """One broker process of a scenario cluster: broadcast mode, fast
    membership knobs, HTTP admin API (membership polls + failpoint arming
    ride the same surface operators use)."""

    def __init__(self, idx: int, workdir: str, mports: List[int],
                 cports: List[int], aports: List[int]) -> None:
        self.idx = idx  # 1-based node id
        self.workdir = workdir
        self.port = mports[idx - 1]
        self.api_port = aports[idx - 1]
        peers = ", ".join(
            f'"{j + 1}@127.0.0.1:{cports[j]}"'
            for j in range(len(cports)) if j != idx - 1)
        self.conf = Path(workdir) / f"node{idx}.toml"
        self.conf.write_text(f"""
[listener]
host = "127.0.0.1"
port = {self.port}

[node]
id = {idx}

[cluster]
listen = "127.0.0.1:{cports[idx - 1]}"
mode = "broadcast"
peers = [{peers}]
heartbeat_interval = 0.25
suspect_timeout = 0.75
dead_timeout = 1.5
alive_hold = 1

[http_api]
host = "127.0.0.1"
port = {self.api_port}

[log]
to = "off"
""")
        self.proc: Optional[subprocess.Popen] = None

    def spawn(self) -> None:
        log_f = open(Path(self.workdir) / f"node{self.idx}.log", "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "rmqtt_tpu.broker",
             "--config", str(self.conf)],
            cwd=str(REPO), env=dict(os.environ, JAX_PLATFORMS="cpu"),
            stdout=log_f, stderr=log_f)
        log_f.close()

    async def wait_ready(self, timeout: float = 120.0) -> None:
        deadline = time.monotonic() + timeout
        for port in (self.port, self.api_port):
            while True:
                if self.proc is not None and self.proc.poll() is not None:
                    raise RuntimeError(
                        f"node {self.idx} exited rc={self.proc.returncode}")
                try:
                    with socket.create_connection(("127.0.0.1", port),
                                                  timeout=0.3):
                        break
                except OSError:
                    if time.monotonic() > deadline:
                        raise RuntimeError(f"node {self.idx} never listened")
                    await asyncio.sleep(0.15)

    async def api(self, path: str, method: str = "GET", obj: Any = None):
        status, body = await _http_json(self.api_port, path, method, obj)
        if status != 200:
            raise RuntimeError(f"node {self.idx} {method} {path} -> {status}")
        return body

    def kill(self) -> None:
        if self.proc is not None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=10)

    def stop(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)


async def _peer_state(node: ClusterProcNode, nid: int) -> Optional[str]:
    body = await node.api("/api/v1/cluster")
    for row in body.get("membership", {}).get("peers", []):
        if row["node"] == nid:
            return row["state"]
    return None


async def _wait_peer_state(node: ClusterProcNode, nid: int, state: str,
                           timeout: float = 25.0) -> float:
    """Poll one node's membership view until ``nid`` is ``state``; returns
    the observation timestamp (time.monotonic)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if await _peer_state(node, nid) == state:
                return time.monotonic()
        except Exception:
            pass
        await asyncio.sleep(0.1)
    raise TimeoutError(f"node {nid} never {state} from node {node.idx}")


async def _wait_digests_equal(nodes: List[ClusterProcNode],
                              timeout: float = 25.0) -> float:
    """Seconds until every node reports the same retained-store digest."""
    t0 = time.monotonic()
    deadline = t0 + timeout
    while time.monotonic() < deadline:
        try:
            ds = [
                (await n.api("/api/v1/cluster"))["digests"]["retain"]["digest"]
                for n in nodes
            ]
            if len(set(ds)) == 1:
                return time.monotonic() - t0
        except Exception:
            pass
        await asyncio.sleep(0.2)
    raise TimeoutError("retained digests never converged")


async def run_cluster_partition_heal(profile: Profile, inproc: bool = False,
                                     workdir: Optional[str] = None) -> dict:
    """The multi-node scenario ROADMAP item 5 left open: a 3-node
    broadcast cluster under live QoS1 traffic is SIGKILLed, restarted,
    fully partitioned (cluster.rpc failpoint over the live HTTP API) and
    healed. The report carries the partition-tolerance metrics: detection
    time, CONNECT latency during the outage (fast-fail kick), anti-entropy
    convergence times, the duplicate-session fence verdict, and zero loss
    for the surviving traffic path."""
    if inproc:
        raise ValueError("cluster profiles need real processes")
    report = base_report(profile.name, "subprocess")
    report["descr"] = profile.descr
    mports = [_free_port() for _ in range(3)]
    cports = [_free_port() for _ in range(3)]
    aports = [_free_port() for _ in range(3)]
    acked: List[bytes] = []
    stop_traffic = asyncio.Event()
    traffic: Optional[asyncio.Task] = None
    clients: List[MiniClient] = []

    with tempfile.TemporaryDirectory() as td:
        wd = workdir or td
        nodes = [ClusterProcNode(i, wd, mports, cports, aports)
                 for i in (1, 2, 3)]
        try:
            for n in nodes:
                n.spawn()
            for n in nodes:
                await n.wait_ready()
            # ---- phase: membership converges to all-ALIVE
            t0 = time.monotonic()
            for n in nodes:
                for other in nodes:
                    if other is not n:
                        await _wait_peer_state(n, other.idx, "ALIVE")
            report["phases"].append({
                "name": "membership_converge", "ok": True,
                "seconds": round(time.monotonic() - t0, 3)})
            # ---- live QoS1 traffic: node 1 → node 2, for the whole run
            sub = await MiniClient.connect(nodes[1].port, "cph-sub")
            clients.append(sub)
            await sub.subscribe("cph/t", qos=1)
            pub = await MiniClient.connect(nodes[0].port, "cph-pub")
            clients.append(pub)

            async def stream():
                seq = 0
                while not stop_traffic.is_set():
                    payload = f"cph-{seq}".encode()
                    try:
                        await pub.publish("cph/t", payload, qos=1)
                        acked.append(payload)
                    except (ConnectionError, asyncio.TimeoutError, OSError):
                        await asyncio.sleep(0.1)
                    seq += 1
                    await asyncio.sleep(0.02)

            traffic = asyncio.ensure_future(stream())
            await asyncio.sleep(1.0)
            # ---- phase: SIGKILL node 3 mid-traffic
            t_kill = time.monotonic()
            nodes[2].kill()
            t_seen = await _wait_peer_state(nodes[0], 3, "DEAD")
            await _wait_peer_state(nodes[1], 3, "DEAD")
            detect_s = t_seen - t_kill
            # CONNECT during the outage: the kick must skip the dead peer
            t_c = time.monotonic()
            probe = await MiniClient.connect(nodes[0].port, "cph-probe")
            clients.append(probe)
            connect_s = time.monotonic() - t_c
            await probe.close()
            # retained divergence while node 3 is down
            for i in range(8):
                await pub.publish(f"cph/keep/{i}", f"k{i}".encode(),
                                  qos=1, retain=True)
            report["phases"].append({
                "name": "node_kill", "ok": connect_s < 2.0 and detect_s < 5.0,
                "seconds": round(time.monotonic() - t_kill, 3),
                "detect_s": round(detect_s, 3),
                "connect_during_outage_s": round(connect_s, 3)})
            # ---- phase: node 3 rejoins; anti-entropy reconverges it
            nodes[2].spawn()
            await nodes[2].wait_ready()
            await _wait_peer_state(nodes[0], 3, "ALIVE")
            rejoin_converge_s = await _wait_digests_equal(nodes)
            report["phases"].append({
                "name": "rejoin", "ok": True,
                "seconds": round(rejoin_converge_s, 3),
                "converge_s": round(rejoin_converge_s, 3)})
            # ---- phase: full partition of node 3 + duplicate session
            t_p = time.monotonic()
            await nodes[2].api("/api/v1/failpoints", "PUT",
                               {"cluster.rpc": "error"})
            await _wait_peer_state(nodes[0], 3, "DEAD")
            await _wait_peer_state(nodes[2], 1, "DEAD")
            dup_a = await MiniClient.connect(nodes[0].port, "cph-dup")
            clients.append(dup_a)
            dup_b = await MiniClient.connect(nodes[2].port, "cph-dup")
            clients.append(dup_b)
            pub3 = await MiniClient.connect(nodes[2].port, "cph-pub3")
            clients.append(pub3)
            await pub3.publish("cph/keep/part", b"island", qos=1, retain=True)
            await nodes[2].api("/api/v1/failpoints", "PUT",
                               {"cluster.rpc": "off"})
            await _wait_peer_state(nodes[0], 3, "ALIVE")
            await _wait_peer_state(nodes[2], 1, "ALIVE")
            partition_converge_s = await _wait_digests_equal(nodes)
            # exactly one cph-dup survivor, fence-resolved
            kicks = live = 0
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                stats = [(await n.api("/api/v1/stats"))[0]["stats"]
                         for n in nodes]
                kicks = sum(s["cluster_fence_kicks"] for s in stats)
                # /api/v1/clients is cluster-merged — one node's listing
                # names every live copy, keyed by owning node_id
                found = {
                    c["node_id"]
                    for c in await nodes[0].api("/api/v1/clients")
                    if c.get("clientid") == "cph-dup" and c.get("connected")
                }
                live = len(found)
                if kicks >= 1 and live == 1:
                    break
                await asyncio.sleep(0.25)
            report["phases"].append({
                "name": "partition_fence",
                "ok": kicks == 1 and live == 1,
                "seconds": round(time.monotonic() - t_p, 3),
                "converge_s": round(partition_converge_s, 3),
                "fence_kicks": kicks, "dup_survivors": live})
            # ---- drain: every acked publish reached the subscriber
            stop_traffic.set()
            await traffic
            want = set(acked)
            got: set = set()
            deadline = time.monotonic() + 30.0
            while not want <= got and time.monotonic() < deadline:
                try:
                    p = await asyncio.wait_for(sub.publishes.get(), 1.0)
                    got.add(p.payload)
                except asyncio.TimeoutError:
                    pass
            lost = len(want - got)
            active_s = time.monotonic() - t0
            report["phases"].append({
                "name": "steady_traffic", "ok": lost == 0,
                "published": len(acked), "delivered": len(want & got),
                "lost": lost, "seconds": round(active_s, 3)})
            report["goodput"] = {
                "published": len(acked), "delivered": len(want & got),
                "phase_seconds": round(active_s, 3),
                "delivered_per_s": (round(len(want & got) / active_s, 1)
                                    if active_s else 0.0),
            }
            report["cluster"] = {
                "nodes": 3,
                "detect_s": round(detect_s, 3),
                "connect_during_outage_s": round(connect_s, 3),
                "rejoin_converge_s": round(rejoin_converge_s, 3),
                "partition_converge_s": round(partition_converge_s, 3),
                "fence_kicks": kicks,
            }
        except Exception as e:
            report["errors"].append(f"{type(e).__name__}: {e}")
        finally:
            # the failure path must not strand the stream task or leak
            # client sockets — a timed-out phase still tears down cleanly
            stop_traffic.set()
            if traffic is not None:
                traffic.cancel()
                try:
                    await traffic
                except (asyncio.CancelledError, Exception):
                    pass
            for c in clients:
                try:
                    await c.close()
                except Exception:
                    pass
            for n in nodes:
                n.stop()
    report["slo"] = {"state": None, "objectives": []}
    ok = (not report["errors"]
          and all(p.get("ok") for p in report["phases"]))
    return finish_report(report, ok)


_profile(Profile(
    name="cluster_partition_heal",
    descr="3-node broadcast cluster under live QoS1 traffic: SIGKILL + "
          "rejoin, full partition + heal; membership detection, fast-fail "
          "CONNECTs during the outage, anti-entropy digest convergence, "
          "duplicate-session fence resolution, zero loss on the surviving "
          "path",
    steps=(),
    subprocess_only=True,
    runner=run_cluster_partition_heal,
))


# --------------------------------------------- crash-torture (durability)
_DURABILITY_TOML = """
[durability]
enable = true
path = "{workdir}/durability.db"
flush_interval_ms = 20.0
compact_min = 192
"""


def _retained_matches(oracle: dict, got: Dict[str, str]) -> bool:
    """Retained-store vs client-side oracle, honoring the maybe-applied
    window: a set whose PUBACK the kill swallowed may legitimately have
    landed, so for those topics EITHER the last-acked value or the
    unacked candidate is correct. On a match the oracle re-anchors to
    the observed store so later rounds compare exactly."""
    maybe = oracle["retained_maybe"]
    expected = oracle["retained"]
    for topic in set(expected) | set(got) | set(maybe):
        have = got.get(topic)
        want = expected.get(topic)
        if have == want:
            continue
        if have is not None and have in maybe.get(topic, ()):
            continue  # the unacked set landed after all
        return False
    oracle["retained"] = dict(got)
    oracle["retained_maybe"] = {}
    return True


async def crash_torture_round(broker: "ScenarioBroker", oracle: dict, *,
                              rnd: int, rng, msgs: int = 60,
                              qos2_every: int = 3, retain_every: int = 5,
                              torn: bool = False,
                              recovery_bound_ms: float = 30000.0) -> dict:
    """One kill-9 round against a live durability-enabled broker.

    Live QoS1/2 + retained traffic, SIGKILL at a randomized point mid-
    stream (with ``flush_interval_ms = 20`` the kill regularly lands
    inside an open commit window; ``torn`` additionally arms
    ``storage.torn_write`` over the live HTTP API so the journal wedges
    with a truncated tail record), restart, then verify the durability
    invariants against client-side oracles:

    - **zero acked loss** — every publish the broker PUBACK/PUBCOMP'd
      reaches the durable subscriber after the restart;
    - **duplicates only with DUP=1** — a payload received twice must carry
      the DUP flag on the re-receipt;
    - **retained equality** — a fresh subscriber's retained replay matches
      the oracle's topic → last-acked-payload map exactly;
    - **bounded recovery** — ``durability_recovery_ms`` under the bound.

    The oracle dict accumulates ACROSS rounds (``acked``/``received``/
    ``retained``/``violations``) so state built in round N is still held
    to account in round N+k.
    """
    acked: set = oracle["acked"]
    received: Dict[str, List[bool]] = oracle["received"]
    sub = await MiniClient.connect(broker.port, "tortoise",
                                   clean_start=False)
    await sub.subscribe("t/#", qos=2)
    pub = await MiniClient.connect(broker.port, f"torture-pub-{rnd}")

    def _record(p) -> None:
        payload = p.payload.decode()
        seen = received.setdefault(payload, [])
        if seen and not p.dup:
            oracle["violations"].append(
                f"round {rnd}: duplicate of {payload!r} without DUP")
        seen.append(bool(p.dup))

    async def _drain_forever(client) -> None:
        try:
            while True:
                _record(await client.publishes.get())
        except asyncio.CancelledError:
            pass

    drainer = asyncio.ensure_future(_drain_forever(sub))
    killed = asyncio.Event()

    async def _killer(after_s: float) -> None:
        await asyncio.sleep(after_s)
        broker.kill()
        killed.set()

    # the kill lands somewhere inside the publish stream (the publisher
    # paces itself on acks, so wall time tracks message progress). Torn
    # rounds kill on the wedge instead — the first post-arm publish times
    # out against the wedged journal, and THAT is the crash moment
    kill_task = None
    if not torn:
        kill_task = asyncio.ensure_future(
            _killer(rng.uniform(0.15, 0.15 + msgs * 0.012)))
    sent_before_death = 0
    torn_armed = False
    try:
        for i in range(msgs):
            if i >= (msgs * 2) // 3 and sub.auto_ack:
                # the tail of the stream dies UNACKED at the subscriber:
                # the broker acks the publisher (journaled pending) but no
                # subscriber ack ever lands, so the kill strands a real
                # inflight window — recovery's pending replay
                # (recovered.inflight → DUP=1 redelivery) is exercised,
                # not just the retained/session paths
                sub.auto_ack = False
            if torn and not torn_armed and i >= msgs // 2:
                # arm the torn write over the live API: the NEXT group
                # commit truncates its tail record and wedges the journal
                # — every later publish must go un-acked
                try:
                    await broker.api("/api/v1/failpoints", "PUT",
                                     {"storage.torn_write":
                                      "times(1, error)"})
                    torn_armed = True
                except Exception:
                    break  # broker already dead
            payload = f"r{rnd}-{i}"
            if retain_every and i % retain_every == retain_every - 1:
                topic = f"keep/{i % 4}"
                try:
                    await asyncio.wait_for(
                        pub.publish(topic, payload.encode(), qos=1,
                                    retain=True), 3.0)
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    # maybe-applied window: the set may have committed
                    # with only its PUBACK lost to the kill — the oracle
                    # accepts EITHER value for this topic this round
                    oracle["retained_maybe"].setdefault(
                        topic, set()).add(payload)
                    break
                oracle["retained"][topic] = payload
                oracle["retained_maybe"].pop(topic, None)
            else:
                qos = 2 if qos2_every and i % qos2_every == 0 else 1
                try:
                    await asyncio.wait_for(
                        pub.publish(f"t/{i % 5}", payload.encode(),
                                    qos=qos), 3.0)
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    break  # killed mid-publish (or wedged) — not acked
                acked.add(payload)
            sent_before_death = i + 1
        if not killed.is_set():
            if kill_task is not None:
                await killed.wait()  # traffic outran the timer
            else:
                broker.kill()  # torn round: the wedge is the crash
                killed.set()
    finally:
        if kill_task is not None:
            kill_task.cancel()
            await asyncio.gather(kill_task, return_exceptions=True)
        if not killed.is_set():
            broker.kill()
            killed.set()
        drainer.cancel()
        await asyncio.gather(drainer, return_exceptions=True)
        await sub.close()
        await pub.close()

    # ---- restart on the same workdir/db; recovery runs before listen
    await broker.start()
    dur = await broker.api("/api/v1/durability")
    # ---- the durable subscriber returns; unacked QoS1/2 re-deliver DUP=1
    sub = await MiniClient.connect(broker.port, "tortoise",
                                   clean_start=False)
    await sub.subscribe("t/#", qos=2)
    deadline = time.monotonic() + 30.0
    missing = set(acked) - set(received)
    while missing and time.monotonic() < deadline:
        try:
            p = await asyncio.wait_for(
                sub.publishes.get(), max(0.1, deadline - time.monotonic()))
        except asyncio.TimeoutError:
            break
        _record(p)
        missing = set(acked) - set(received)
    await sub.close()

    # ---- retained oracle: a fresh subscriber's replay IS the store
    verifier = await MiniClient.connect(broker.port, f"torture-rv-{rnd}")
    await verifier.subscribe("keep/#", qos=0)
    got_retained: Dict[str, str] = {}
    quiet_until = time.monotonic() + 2.0
    while time.monotonic() < quiet_until:
        try:
            p = await asyncio.wait_for(verifier.publishes.get(), 0.5)
        except asyncio.TimeoutError:
            break
        if p.retain:
            got_retained[p.topic] = p.payload.decode()
            quiet_until = time.monotonic() + 0.5
    await verifier.close()

    recovery_ms = float(dur.get("recovery_ms") or 0.0)
    retained_ok = _retained_matches(oracle, got_retained)
    ok = (not missing and not oracle["violations"] and retained_ok
          and recovery_ms <= recovery_bound_ms)
    return {
        "ok": ok,
        "round": rnd,
        "torn": torn,
        "sent_before_death": sent_before_death,
        "acked_total": len(acked),
        "missing_acked": sorted(missing),
        "dup_violations": list(oracle["violations"]),
        "retained_expected": len(oracle["retained"]),
        "retained_got": len(got_retained),
        "retained_ok": retained_ok,
        "recovered": dur.get("recovered", {}),
        "recovery_ms": recovery_ms,
    }


async def run_crash_rounds(workdir: str, *, rounds: int = 5,
                           msgs: int = 60, torn_every: int = 3,
                           seed: int = 20260804,
                           recovery_bound_ms: float = 30000.0,
                           profile: "Optional[Profile]" = None) -> dict:
    """N crash-torture rounds against one broker/journal (state carries
    across kills — that is the point). Every ``torn_every``-th round arms
    the torn-write failpoint. Returns a verdict dict with per-round rows;
    ``ok`` iff every invariant held in every round."""
    import random

    rng = random.Random(seed)
    prof = profile or PROFILES["crash_restart"]
    broker = ScenarioBroker(prof, workdir)
    oracle: Dict[str, Any] = {"acked": set(), "received": {},
                              "retained": {}, "retained_maybe": {},
                              "violations": []}
    rows = []
    await broker.start()
    try:
        for rnd in range(rounds):
            torn = bool(torn_every) and rnd % torn_every == torn_every - 1
            row = await crash_torture_round(
                broker, oracle, rnd=rnd, rng=rng, msgs=msgs, torn=torn,
                recovery_bound_ms=recovery_bound_ms)
            rows.append(row)
    finally:
        await broker.stop()
    return {
        "ok": all(r["ok"] for r in rows) and len(rows) == rounds,
        "rounds": rows,
        "acked_total": len(oracle["acked"]),
        "retained_topics": len(oracle["retained"]),
        "dup_violations": oracle["violations"],
    }


async def run_crash_restart(profile: "Profile", inproc: bool = False,
                            workdir: Optional[str] = None) -> dict:
    """Scenario-matrix runner for the ``crash_restart`` profile: the
    kill-9 torture loop wrapped in the shared ScenarioReport schema."""
    if inproc:
        raise ValueError("crash_restart needs a real process to SIGKILL")
    report = base_report(profile.name, "subprocess")
    report["descr"] = profile.descr
    with tempfile.TemporaryDirectory() as td:
        wd = workdir or td
        t0 = time.monotonic()
        verdict = await run_crash_rounds(wd, rounds=3, msgs=48)
        seconds = round(time.monotonic() - t0, 3)
    for row in verdict["rounds"]:
        report["phases"].append({
            "name": f"crash_round_{row['round']}"
                    + ("_torn" if row["torn"] else ""),
            **row})
    report["goodput"] = {
        "published": verdict["acked_total"],
        "delivered": verdict["acked_total"],
        "phase_seconds": seconds,
        "delivered_per_s": round(verdict["acked_total"] / seconds, 1)
        if seconds else 0.0,
    }
    report["crash_torture"] = {k: v for k, v in verdict.items()
                               if k != "rounds"}
    return finish_report(report, verdict["ok"])


_profile(Profile(
    name="crash_restart",
    descr="kill-9 torture against the durability plane: live QoS1/2 + "
          "retained traffic, SIGKILL inside the commit window (torn-write "
          "rounds included), restart, verify zero acked loss / DUP-flagged "
          "duplicates / retained oracle equality / bounded recovery",
    steps=(),
    extra_toml=_DURABILITY_TOML,
    subprocess_only=True,
    runner=run_crash_restart,
))


# ------------------------------------------- sharded accept (connect storm)
async def run_connect_storm_sharded(profile: Profile, inproc: bool = False,
                                    workdir: Optional[str] = None) -> dict:
    """Scenario-matrix runner for ``connect_storm_sharded``: CONNECT
    waves against M SO_REUSEPORT fabric workers sharing ONE client port
    (the ``--workers N --fabric`` deployment shape — the kernel
    load-balances accepts across the worker processes). Each worker gets
    its OWN admin API port so the report carries per-worker connection
    gauges — the evidence that the kernel actually sharded the accept
    load instead of funneling every handshake into one process. A QoS1
    anchor stream runs through the whole storm and must land every acked
    publish (zero acked loss across the worker fleet); each wave reports
    its own CONNECT p50/p99."""
    if inproc:
        raise ValueError("sharded accept needs real SO_REUSEPORT worker "
                         "processes")
    nworkers, waves, wave_conns = 2, 6, 24
    report = base_report(profile.name, "subprocess")
    report["descr"] = profile.descr
    port = _free_port()
    api_ports = [_free_port() for _ in range(nworkers)]
    procs: List[subprocess.Popen] = []
    held: List[MiniClient] = []
    clients: List[MiniClient] = []
    acked: List[bytes] = []
    stop_traffic = asyncio.Event()
    traffic: Optional[asyncio.Task] = None

    async def _wait_tcp(p, deadline):
        while True:
            try:
                with socket.create_connection(("127.0.0.1", p), timeout=0.3):
                    return
            except OSError:
                if time.monotonic() > deadline:
                    raise RuntimeError(f"port {p} never opened")
                await asyncio.sleep(0.15)

    with tempfile.TemporaryDirectory() as td:
        wd = Path(workdir or td)
        fdir = wd / "fab"
        fdir.mkdir(exist_ok=True)
        try:
            for wid in range(1, nworkers + 1):
                conf_path = wd / f"w{wid}.toml"
                conf_path.write_text(
                    "[listener]\n"
                    'host = "127.0.0.1"\n'
                    f"port = {port}\n"
                    "reuse_port = true\n\n"
                    "[http_api]\n"
                    'host = "127.0.0.1"\n'
                    f"port = {api_ports[wid - 1]}\n\n"
                    "[log]\n"
                    'to = "off"\n')
                log_f = open(wd / f"w{wid}.log", "ab")
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", "rmqtt_tpu.broker",
                     "--config", str(conf_path), "--node-id", str(wid),
                     "--fabric", "--fabric-dir", str(fdir),
                     "--fabric-worker-id", str(wid),
                     "--fabric-workers", str(nworkers)],
                    cwd=str(REPO),
                    env=dict(os.environ, JAX_PLATFORMS="cpu"),
                    stdout=log_f, stderr=log_f))
                log_f.close()
            deadline = time.monotonic() + 120.0
            for p in (port, *api_ports):
                await _wait_tcp(p, deadline)
            await asyncio.sleep(1.0)  # workers register over the UDS mesh
            # ---- QoS1 anchor stream through the whole storm: the
            # kernel places sub and pub on whatever workers it likes, so
            # delivery may also cross the fabric mid-storm
            sub = await MiniClient.connect(port, "css-sub")
            clients.append(sub)
            await sub.subscribe("css/t", qos=1)
            pub = await MiniClient.connect(port, "css-pub")
            clients.append(pub)

            async def stream():
                seq = 0
                while not stop_traffic.is_set():
                    payload = f"css-{seq}".encode()
                    try:
                        await pub.publish("css/t", payload, qos=1)
                        acked.append(payload)
                    except (ConnectionError, asyncio.TimeoutError, OSError):
                        await asyncio.sleep(0.1)
                    seq += 1
                    await asyncio.sleep(0.01)

            traffic = asyncio.ensure_future(stream())
            # ---- the storm: waves of concurrent CONNECTs, every client
            # HELD OPEN so the final per-worker gauges show placement
            wave_rows = []
            t0 = time.monotonic()
            for w in range(waves):
                times: List[float] = []

                async def dial(i):
                    t = time.monotonic()
                    c = await MiniClient.connect(port, f"css-{w}-{i}")
                    times.append((time.monotonic() - t) * 1e3)
                    held.append(c)

                res = await asyncio.gather(
                    *(dial(i) for i in range(wave_conns)),
                    return_exceptions=True)
                fails = sum(1 for r in res if isinstance(r, BaseException))
                ts = sorted(times)
                wave_rows.append({
                    "wave": w + 1, "connects": len(ts), "failures": fails,
                    "connect_p50_ms":
                        round(ts[len(ts) // 2], 3) if ts else None,
                    "connect_p99_ms":
                        round(ts[min(len(ts) - 1, int(len(ts) * 0.99))], 3)
                        if ts else None,
                })
            storm_s = time.monotonic() - t0
            # ---- sharding evidence: each worker's own connection gauge
            per_worker = []
            for i in range(nworkers):
                status, body = await _http_json(api_ports[i],
                                                "/api/v1/stats")
                if status != 200:
                    raise RuntimeError(f"worker {i + 1} stats -> {status}")
                per_worker.append(body[0]["stats"]["connections"])
            sharded = sum(1 for c in per_worker if c > 0)
            report["phases"].append({
                "name": "connect_storm_sharded",
                "ok": (sharded >= 2
                       and all(r["failures"] == 0 for r in wave_rows)),
                "connections": len(held),
                "seconds": round(storm_s, 3),
                "handshakes_per_s": (round(len(held) / storm_s, 1)
                                     if storm_s else 0.0),
                "waves": wave_rows,
                "per_worker_connections": per_worker,
                "workers_accepting": sharded,
            })
            # ---- drain: every acked anchor publish reached the sub
            stop_traffic.set()
            await traffic
            traffic = None
            want = set(acked)
            got: set = set()
            deadline = time.monotonic() + 30.0
            while not want <= got and time.monotonic() < deadline:
                try:
                    p = await asyncio.wait_for(sub.publishes.get(), 1.0)
                    got.add(p.payload)
                except asyncio.TimeoutError:
                    pass
            lost = len(want - got)
            active_s = time.monotonic() - t0
            report["phases"].append({
                "name": "anchor_stream", "ok": lost == 0,
                "published": len(acked), "delivered": len(want & got),
                "lost": lost, "seconds": round(active_s, 3)})
            report["goodput"] = {
                "published": len(acked), "delivered": len(want & got),
                "phase_seconds": round(active_s, 3),
                "delivered_per_s": (round(len(want & got) / active_s, 1)
                                    if active_s else 0.0),
            }
            report["connect_storm"] = {
                "workers": nworkers,
                "waves": wave_rows,
                "per_worker_connections": per_worker,
                "workers_accepting": sharded,
                "handshakes_per_s": (round(len(held) / storm_s, 1)
                                     if storm_s else 0.0),
            }
        except Exception as e:
            report["errors"].append(f"{type(e).__name__}: {e}")
        finally:
            stop_traffic.set()
            if traffic is not None:
                traffic.cancel()
                try:
                    await traffic
                except (asyncio.CancelledError, Exception):
                    pass
            for c in [*clients, *held]:
                try:
                    await c.close()
                except Exception:
                    pass
            for proc in procs:
                proc.terminate()
            for proc in procs:
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)
    report["slo"] = {"state": None, "objectives": []}
    ok = (not report["errors"]
          and all(p.get("ok") for p in report["phases"]))
    return finish_report(report, ok)


_profile(Profile(
    name="connect_storm_sharded",
    descr="CONNECT waves against SO_REUSEPORT fabric workers sharing one "
          "client port: per-wave CONNECT p99, per-worker accept counts "
          "(kernel sharding evidence), QoS1 anchor stream with zero acked "
          "loss across the storm",
    steps=(),
    subprocess_only=True,
    runner=run_connect_storm_sharded,
))


#: tier-1 wiring (tests/test_slo.py), chaos_matrix.FAST_SUBSET-style
FAST_SUBSET = ["smoke_fast"]


# ------------------------------------------------------------- orchestrator
async def _poll_live(broker, report: dict, interval: float,
                     stop: asyncio.Event) -> None:
    """Mid-run sampler: RSS peak + the live SLO surface (the acceptance
    point that `/api/v1/slo` shows burn rates DURING a run, not only
    after it)."""
    peak = 0.0
    samples = 0
    max_fast: Dict[str, float] = {}
    while not stop.is_set():
        peak = max(peak, broker.rss())
        try:
            snap = await broker.api("/api/v1/slo")
            samples += 1
            for row in snap.get("objectives", ()):
                burn = row.get("fast", {}).get("burn_rate", 0.0)
                name = row["name"]
                if burn >= max_fast.get(name, 0.0):
                    max_fast[name] = burn
        except Exception:
            pass  # the broker may be busy; the final snapshot still lands
        try:
            await asyncio.wait_for(stop.wait(), interval)
        except asyncio.TimeoutError:
            continue
    report["rss_mb"]["peak"] = round(peak, 1)
    report["slo_live"] = {"samples": samples,
                          "max_fast_burn": {k: round(v, 3)
                                            for k, v in max_fast.items()}}


async def run_profile_async(name, inproc: bool = False,
                            workdir: Optional[str] = None) -> dict:
    """Run one profile (a registered name or a Profile instance — the
    legacy wrappers build scaled copies) end to end; returns the
    ScenarioReport."""
    profile = name if isinstance(name, Profile) else PROFILES[name]
    if profile.runner is not None:
        return await profile.runner(profile, inproc=inproc, workdir=workdir)
    report = base_report(profile.name, "inproc" if inproc else "subprocess")
    report["descr"] = profile.descr
    with tempfile.TemporaryDirectory() as td:
        wd = workdir or td
        broker = ScenarioBroker(profile, wd, inproc=inproc)
        await broker.start()
        stop = asyncio.Event()
        poller = None
        try:
            report["rss_mb"]["start"] = round(broker.rss(), 1)
            m0 = (await broker.api("/api/v1/metrics")).get("metrics", {})
            poller = asyncio.ensure_future(
                _poll_live(broker, report,
                           max(0.3, profile.slo_sample_interval), stop))
            for step in profile.steps:
                rows = await asyncio.gather(
                    *(fn(broker, **params) for _, fn, params in step),
                    return_exceptions=True)
                for (pname, _fn, params), row in zip(step, rows):
                    if isinstance(row, BaseException):
                        report["errors"].append(
                            f"{pname}: {type(row).__name__}: {row}")
                        row = {"ok": False,
                               "error": f"{type(row).__name__}: {row}"}
                    report["phases"].append({"name": pname, **row})
            # one more SLO sample interval so the windows see the tail.
            # Collection failures (a profile that crashed the broker) must
            # not discard the report — the phase rows and errors ARE the
            # diagnostics a failed run exists to deliver.
            latency, slo, m1 = {}, {}, m0
            try:
                await asyncio.sleep(profile.slo_sample_interval * 2)
                latency = await broker.api("/api/v1/latency")
                slo = await broker.api("/api/v1/slo")
                m1 = (await broker.api("/api/v1/metrics")).get("metrics", {})
            except Exception as e:
                report["errors"].append(
                    f"post-run collection: {type(e).__name__}: {e}")
            report["rss_mb"]["end"] = round(broker.rss(), 1)
        finally:
            stop.set()
            if poller is not None:
                try:
                    await asyncio.wait_for(poller, 5.0)
                except Exception:
                    poller.cancel()
            await broker.stop()
    report["latency"] = latency_stages(latency)
    report["drops"] = drop_deltas(m0, m1)
    published = sum(p.get("published", 0) for p in report["phases"])
    delivered = sum(p.get("delivered", 0) for p in report["phases"])
    active_s = sum(p.get("seconds", 0.0) for p in report["phases"])
    report["goodput"] = {
        "published": published,
        "delivered": delivered,
        "phase_seconds": round(active_s, 3),
        "delivered_per_s": round(delivered / active_s, 1) if active_s else 0.0,
    }
    report["slo"] = {
        "state": slo.get("state"),
        "transitions": slo.get("transitions"),
        "objectives": [
            {k: row.get(k) for k in
             ("name", "kind", "state", "target", "ratio", "compliant",
              "budget_remaining")}
            | {"fast_burn": row.get("fast", {}).get("burn_rate"),
               "slow_burn": row.get("slow", {}).get("burn_rate")}
            for row in slo.get("objectives", ())
        ],
    }
    slo_ok = all(o["compliant"] for o in report["slo"]["objectives"])
    phases_ok = all(p.get("ok") for p in report["phases"])
    return finish_report(report,
                         slo_ok and phases_ok and not report["errors"])


def run_profile(name: str, inproc: bool = False) -> dict:
    return asyncio.run(run_profile_async(name, inproc=inproc))
