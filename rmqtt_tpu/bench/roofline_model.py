"""Analytic HBM-traffic model of the partitioned match kernel.

One source of truth for the roofline numbers: ``scripts/roofline.py``
builds tables offline and prints ceilings; ``bench.py`` calls
``model_table`` against the LIVE table of each measured config and embeds
the model next to the measured rate, so every bench artifact carries its
own modeled-vs-measured delta (the "is the bandwidth claim holding?"
check the ISSUE asked to make per-run).

The model (see ``ops/partitioned.pack_device_rows`` /
``pack_device_rows_packed`` for the layouts):

    tile_bytes_legacy  = (L+3) * CHUNK * dtype_size      # int16 field-major
    tile_bytes_packed  = groups * CHUNK * 4              # int32 byte planes
    batch_bytes        = B * NC_eff * tile_bytes         # the scan's gathers
                       + B * NC_eff * WPC * 4            # packed words out
    ceiling            = HBM_BW / bytes_per_topic        # topics/s if bound

plus the fused-pipeline deltas: the words array no longer round-trips
between two dispatches, the device→host wire carries 4 B/route (final
fids) instead of 2 B/route + a host-side chunk-gather + fid-map + sort.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from rmqtt_tpu.ops.partitioned import CHUNK, WORDS_PER_CHUNK

#: default modeled part: v5e HBM bandwidth (GB/s); pass bw_gbps for others
V5E_HBM_GBPS = 819.0


def tile_bytes_legacy(max_levels: int, tok_wide: bool = False) -> int:
    """One gathered tile in the legacy int16/int32 field-major layout."""
    return (max_levels + 3) * CHUNK * (4 if tok_wide else 2)


def tile_bytes_packed(layout) -> int:
    """One gathered tile in the bit-packed int32 byte-plane layout."""
    return layout.groups * CHUNK * 4


def model_table(table, ncs: Sequence[int], bw_gbps: float = V5E_HBM_GBPS,
                measured_topics_per_sec: Optional[float] = None) -> dict:
    """HBM roofline of one table against a MEASURED candidate-count sample
    ``ncs`` (one entry per topic of the real publish stream). When
    ``measured_topics_per_sec`` is given, the modeled-vs-measured fraction
    is included so regressions in either direction are visible per run."""
    ncs = np.asarray(ncs, dtype=np.float64)
    nc_eff = float(ncs.mean()) if ncs.size else 1.0
    layout = table.packed_layout()
    legacy = tile_bytes_legacy(table.max_levels, table._tok_wide)
    ptile = tile_bytes_packed(layout) if layout is not None else None
    out_bytes = nc_eff * WORDS_PER_CHUNK * 4
    bpt_legacy = nc_eff * legacy + out_bytes
    bpt = nc_eff * ptile + out_bytes if ptile is not None else bpt_legacy
    bw = bw_gbps * 1e9
    out = {
        "hbm_gbps": bw_gbps,
        "nc_mean": round(nc_eff, 2),
        "nc_p99": int(np.percentile(ncs, 99)) if ncs.size else 0,
        "tile_bytes_legacy": legacy,
        "tile_bytes_packed": ptile,
        "packed_tile_reduction_x": (
            round(legacy / ptile, 2) if ptile else None),
        "bytes_per_topic_legacy": int(bpt_legacy),
        "bytes_per_topic": int(bpt),
        "hbm_bytes_reduction_x": round(bpt_legacy / bpt, 2),
        "ceiling_topics_per_sec": int(bw / bpt),
        "ceiling_topics_per_sec_legacy": int(bw / bpt_legacy),
        # what the fused pipeline removes per topic: the intermediate
        # [B, NC*WPC] words array written by dispatch 1 and re-read by
        # dispatch 2, and the host decode (chunk gather + fid map + sort);
        # what it costs: 4 B/route on the wire instead of 2
        "fused": {
            "words_roundtrip_bytes_per_topic": int(
                2 * nc_eff * WORDS_PER_CHUNK * 4),
            "wire_bytes_per_route": 4,
            "unfused_wire_bytes_per_route": 2,
            "host_decode_on_wire": False,
        },
    }
    if measured_topics_per_sec is not None:
        out["measured_topics_per_sec"] = round(measured_topics_per_sec, 1)
        out["measured_fraction_of_ceiling"] = round(
            measured_topics_per_sec / max(1.0, out["ceiling_topics_per_sec"]),
            4)
    return out
