"""Minimal Raft consensus for replicated routing state.

The reference's raft mode uses the external `rmqtt-raft` crate (SURVEY.md
§2.3); there is no Python/C++ drop-in in this image, so this is an
independent compact Raft: leader election with randomized timeouts,
AppendEntries log replication with commit on majority, leader forwarding for
proposals, and full-log catch-up for (re)joining nodes. Term/vote and the
log persist to SQLite when a storage is attached (cluster.raft_db), so a
restarted node reloads and re-applies its own log instead of refetching it.

Snapshots + log compaction (Raft §7, mirroring the reference's compressed
snapshot/restore in `rmqtt-plugins/rmqtt-cluster-raft/src/router.rs:387-580`):
when the applied prefix exceeds ``compact_threshold`` entries, the node asks
the application for a full-state snapshot (``snapshot_cb``), compresses it
(zlib over the wire encoding), persists it, and discards the covered log
prefix — bounding both the durable log and restart replay. A leader whose
follower has fallen behind the compacted prefix sends ``raft_snap``
(InstallSnapshot) instead of AppendEntries; the follower restores via
``restore_cb`` and resumes replication from the snapshot index.

RPCs ride the cluster transport (`cluster/transport.py`) with message types
``raft_vote`` / ``raft_append`` / ``raft_propose`` / ``raft_snap``.
"""

from __future__ import annotations

import asyncio
import logging
import random
import zlib
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from rmqtt_tpu.cluster import wire
from rmqtt_tpu.cluster.transport import ClusterReplyError, PeerClient, PeerUnavailable

log = logging.getLogger("rmqtt_tpu.raft")

RAFT_VOTE = "raft_vote"
RAFT_APPEND = "raft_append"
RAFT_PROPOSE = "raft_propose"
RAFT_SNAP = "raft_snap"


def pack_snapshot(data: Any) -> bytes:
    return zlib.compress(wire.dumps(data))


def unpack_snapshot(blob: bytes) -> Any:
    return wire.loads(zlib.decompress(blob))

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class RaftNode:
    def __init__(
        self,
        node_id: int,
        peers: Dict[int, PeerClient],
        apply_cb: Callable[[Any], Awaitable[None]],
        election_timeout: Tuple[float, float] = (0.3, 0.6),
        heartbeat: float = 0.1,
        storage=None,
        snapshot_cb: Optional[Callable[[], Any]] = None,
        restore_cb: Optional[Callable[[Any], Awaitable[None]]] = None,
        compact_threshold: int = 4096,
    ) -> None:
        self.node_id = node_id
        self.peers = peers
        self.apply_cb = apply_cb
        self.election_timeout = election_timeout
        self.heartbeat = heartbeat
        # optional durable state (SqliteStore): term/vote + the log survive
        # restarts, so a rejoining node re-applies its own log instead of
        # refetching everything
        self.storage = storage
        # snapshot_cb (sync) captures the FULL applied state; restore_cb
        # replaces local state with a snapshot. Both unset => no compaction.
        self.snapshot_cb = snapshot_cb
        self.restore_cb = restore_cb
        self.compact_threshold = compact_threshold

        self.term = 0
        self.voted_for: Optional[int] = None
        self.log: List[Tuple[int, Any]] = []  # (term, entry), offset by log_offset
        # log_offset = absolute index of the last snapshot-covered entry;
        # absolute index i lives at self.log[i - log_offset - 1]
        self.log_offset = 0
        self.snap_term = 0  # term at log_offset
        self._snap_blob: Optional[bytes] = None  # latest compressed snapshot
        self._pending_restore: Optional[bytes] = None  # loaded, not yet applied
        if storage is not None:
            meta = storage.get("raft", "meta")
            if meta:
                self.term = int(meta["term"])
                self.voted_for = meta["voted_for"]
            snap = storage.get("raft", "snapshot")
            if snap:
                self.log_offset = int(snap["index"])
                self.snap_term = int(snap["term"])
                self._snap_blob = snap["data"]
                self._pending_restore = snap["data"]
            rows = sorted(
                ((int(k), v) for k, v in storage.scan("raft_log")), key=lambda kv: kv[0]
            )
            self.log = [(int(t), e) for idx, (t, e) in rows if idx > self.log_offset]
        self.commit_index = self.log_offset  # 1-based count of committed entries
        self.last_applied = self.log_offset
        self.state = FOLLOWER
        self.leader_id: Optional[int] = None
        self._next_index: Dict[int, int] = {}
        self._match_index: Dict[int, int] = {}
        self._last_heartbeat = 0.0
        self._tasks: List[asyncio.Task] = []
        self._lead_task: Optional[asyncio.Task] = None
        self._commit_waiters: Dict[int, asyncio.Future] = {}
        self._apply_lock = asyncio.Lock()
        self._snap_inflight: set = set()  # peers with an InstallSnapshot in flight
        self._stopped = False

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._last_heartbeat = loop.time()
        self._tasks = [loop.create_task(self._election_loop())]

    async def stop(self) -> None:
        self._stopped = True
        tasks = list(self._tasks)
        if self._lead_task is not None:
            tasks.append(self._lead_task)
            self._lead_task = None
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._tasks = []

    async def restore_pending(self) -> None:
        """Hand a storage-loaded snapshot to the application. Must run (once)
        before ``start()`` so log re-apply happens on top of snapshot state."""
        if self._pending_restore is not None and self.restore_cb is not None:
            await self.restore_cb(unpack_snapshot(self._pending_restore))
        self._pending_restore = None

    # --------------------------------------------------- log index helpers
    def _last_index(self) -> int:
        return self.log_offset + len(self.log)

    def _term_at(self, idx: int) -> int:
        if idx <= self.log_offset:
            return self.snap_term if idx == self.log_offset and idx > 0 else 0
        return self.log[idx - self.log_offset - 1][0]

    def _save_meta(self) -> None:
        if self.storage is not None:
            self.storage.put("raft", "meta", {"term": self.term, "voted_for": self.voted_for})

    def _persist_append(self, start_idx: int) -> None:
        """Persist log entries from 1-based absolute ``start_idx`` to the end
        — one transaction regardless of batch size (a far-behind follower
        receives its whole backlog in one AppendEntries)."""
        if self.storage is not None:
            self.storage.put_many(
                "raft_log",
                [(str(idx), list(self.log[idx - self.log_offset - 1]))
                 for idx in range(start_idx, self._last_index() + 1)],
            )

    def _persist_truncate(self, new_last: int) -> None:
        """Drop persisted entries with absolute index > ``new_last``."""
        if self.storage is not None:
            idx = new_last + 1
            while self.storage.delete("raft_log", str(idx)):
                idx += 1

    # ---------------------------------------------------------- compaction
    def _maybe_compact(self) -> None:
        """Snapshot applied state + discard the covered log prefix once it
        outgrows the threshold (router.rs:387-580 semantics: full-state
        snapshot with compression; filter ids stay stable because the
        snapshot is of APPLICATION state, not physical layout)."""
        if self.snapshot_cb is None:
            return
        if self.last_applied - self.log_offset < self.compact_threshold:
            return
        self.take_snapshot()

    def take_snapshot(self) -> None:
        """Force a snapshot at ``last_applied`` (also used by tests/admin)."""
        if self.snapshot_cb is None or self.last_applied <= self.log_offset:
            return
        idx = self.last_applied
        term = self._term_at(idx)
        blob = pack_snapshot(self.snapshot_cb())
        self.log = self.log[idx - self.log_offset:]
        old_offset = self.log_offset
        self.log_offset = idx
        self.snap_term = term
        self._snap_blob = blob
        if self.storage is not None:
            self.storage.put("raft", "snapshot", {"index": idx, "term": term, "data": blob})
            self.storage.delete_int_upto("raft_log", idx)
        log.info(
            "raft node %s compacted log through %s (%s entries dropped, snapshot %s bytes)",
            self.node_id, idx, idx - old_offset, len(blob),
        )

    @property
    def is_leader(self) -> bool:
        return self.state == LEADER

    def _quorum(self) -> int:
        return (len(self.peers) + 1) // 2 + 1

    # ------------------------------------------------------------- election
    async def _election_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._stopped:
            timeout = random.uniform(*self.election_timeout)
            await asyncio.sleep(timeout / 4)
            if self.state == LEADER:
                continue
            if loop.time() - self._last_heartbeat >= timeout:
                await self._campaign()

    async def _request_votes(self, term: int, prevote: bool):
        last_idx = self._last_index()
        last_term = self._term_at(last_idx)

        async def ask(peer: PeerClient):
            try:
                body = {
                    "term": term, "candidate": self.node_id,
                    "last_log_index": last_idx, "last_log_term": last_term,
                }
                if prevote:
                    body["prevote"] = True
                return await peer.call(RAFT_VOTE, body, timeout=self.election_timeout[0])
            except (PeerUnavailable, ClusterReplyError):
                return None

        return await asyncio.gather(*(ask(p) for p in self.peers.values()))

    def _heard_from_leader_recently(self) -> bool:
        return (
            asyncio.get_running_loop().time() - self._last_heartbeat
            < self.election_timeout[0]
        )

    async def _campaign(self) -> None:
        # PRE-VOTE (Raft §9.6): ask peers whether they WOULD vote for us at
        # term+1 without disturbing anyone's persistent term. Prevents the
        # election storms a partitioned/restarting node causes by endlessly
        # inflating terms it can never win with.
        if self.peers:
            replies = await self._request_votes(self.term + 1, prevote=True)
            # term catch-up: a denial can carry a newer term (e.g. a peer
            # restarted with an inflated persisted term) — adopt it or this
            # node's pre-votes stay permanently too stale to ever pass
            for r in replies:
                if r is not None and r.get("term", 0) > self.term:
                    self._step_down(r["term"])
                    return  # retry next election tick at the caught-up term
            votes = 1 + sum(1 for r in replies if r is not None and r.get("granted"))
            if votes < self._quorum():
                return
            # the pre-vote round took time: if a live leader (or newer term)
            # showed up meanwhile, stand down instead of disrupting it
            if self._heard_from_leader_recently() or self.state == LEADER:
                return
        self.term += 1
        self.state = CANDIDATE
        self.voted_for = self.node_id
        self._save_meta()
        self.leader_id = None
        term = self.term
        votes = 1
        replies = await self._request_votes(term, prevote=False)
        if self.term != term or self.state != CANDIDATE:
            return  # a newer term interrupted the campaign
        for reply in replies:
            if reply is None:
                continue
            if reply["term"] > self.term:
                self._step_down(reply["term"])
                return
            if reply.get("granted"):
                votes += 1
        if votes >= self._quorum():
            self._become_leader()

    def _become_leader(self) -> None:
        self.state = LEADER
        self.leader_id = self.node_id
        # a fresh leader cannot commit prior-term entries by counting
        # replicas (Raft §5.4.2) — append a current-term no-op (entry=None,
        # outside the application payload space) so the whole log prefix
        # commits through it
        self.log.append((self.term, None))
        self._persist_append(self._last_index())
        nxt = self._last_index() + 1
        self._next_index = {nid: nxt for nid in self.peers}
        self._match_index = {nid: 0 for nid in self.peers}
        log.info("raft node %s became leader (term %s)", self.node_id, self.term)
        if self._lead_task is not None:
            self._lead_task.cancel()
        self._lead_task = asyncio.get_running_loop().create_task(self._lead_loop())

    def _step_down(self, term: int) -> None:
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._save_meta()
        if self.state != FOLLOWER:
            log.info("raft node %s steps down (term %s)", self.node_id, self.term)
        self.state = FOLLOWER

    # ------------------------------------------------------------ leadership
    async def _lead_loop(self) -> None:
        while self.state == LEADER and not self._stopped:
            await self._replicate_all()
            await asyncio.sleep(self.heartbeat)

    async def _replicate_all(self) -> None:
        await asyncio.gather(*(self._replicate(nid) for nid in self.peers))
        self._advance_commit()

    async def _replicate(self, nid: int) -> None:
        if self.state != LEADER:
            return
        peer = self.peers[nid]
        nxt = self._next_index.get(nid, self._last_index() + 1)
        prev_index = nxt - 1
        if prev_index < self.log_offset:
            # follower is behind the compacted prefix: only a snapshot can
            # catch it up (Raft §7 InstallSnapshot)
            await self._send_snapshot(nid)
            return
        prev_term = self._term_at(prev_index)
        entries = self.log[prev_index - self.log_offset:]
        try:
            reply = await peer.call(RAFT_APPEND, {
                "term": self.term, "leader": self.node_id,
                "prev_log_index": prev_index, "prev_log_term": prev_term,
                "entries": [[t, e] for t, e in entries],
                "leader_commit": self.commit_index,
            }, timeout=1.0)
        except (PeerUnavailable, ClusterReplyError):
            return
        if reply["term"] > self.term:
            self._step_down(reply["term"])
            return
        if reply.get("success"):
            self._match_index[nid] = prev_index + len(entries)
            self._next_index[nid] = self._match_index[nid] + 1
        else:
            # follower log diverges/behind: back off (snapshot worst case)
            self._next_index[nid] = max(1, min(nxt - 1, reply.get("match", 0) + 1))

    async def _send_snapshot(self, nid: int) -> None:
        # at most ONE transfer per peer: the heartbeat loop keeps calling
        # _replicate while a big snapshot is still on the wire, and duplicate
        # transfers would multiply bandwidth and re-run restore on the peer
        if self._snap_blob is None or nid in self._snap_inflight:
            return
        self._snap_inflight.add(nid)
        try:
            peer = self.peers[nid]
            body = {
                "term": self.term, "leader": self.node_id,
                "index": self.log_offset, "snap_term": self.snap_term,
                "data": self._snap_blob,
            }
            try:
                reply = await peer.call(RAFT_SNAP, body, timeout=30.0)
            except (PeerUnavailable, ClusterReplyError):
                return
            if reply["term"] > self.term:
                self._step_down(reply["term"])
                return
            if reply.get("success"):
                self._match_index[nid] = max(self._match_index.get(nid, 0), body["index"])
                self._next_index[nid] = self._match_index[nid] + 1
        finally:
            self._snap_inflight.discard(nid)

    def _advance_commit(self) -> None:
        if self.state != LEADER:
            return
        for idx in range(self._last_index(), max(self.commit_index, self.log_offset), -1):
            # only entries from the current term commit by counting (Raft §5.4.2)
            if self._term_at(idx) != self.term:
                break
            votes = 1 + sum(1 for m in self._match_index.values() if m >= idx)
            if votes >= self._quorum():
                self.commit_index = idx
                asyncio.get_running_loop().create_task(self._apply_committed())
                # push the new commit index to followers right away instead
                # of waiting a heartbeat — keeps the replication-visibility
                # window on the routing table tight
                asyncio.get_running_loop().create_task(self._push_commit())
                break

    async def _push_commit(self) -> None:
        if self.state == LEADER:
            await asyncio.gather(*(self._replicate(nid) for nid in self.peers))

    async def _apply_committed(self) -> None:
        async with self._apply_lock:
            while self.last_applied < self.commit_index:
                self.last_applied += 1
                _term, entry = self.log[self.last_applied - self.log_offset - 1]
                if entry is None:
                    pass  # leader-election no-op, not application state
                else:
                    try:
                        await self.apply_cb(entry)
                    except Exception:
                        log.exception("raft apply failed at %s", self.last_applied)
                fut = self._commit_waiters.pop(self.last_applied, None)
                if fut is not None and not fut.done():
                    fut.set_result(True)
            self._maybe_compact()

    # -------------------------------------------------------------- propose
    async def propose(self, entry: Any, timeout: float = 5.0) -> bool:
        """Append via the leader; resolves once the entry is APPLIED locally.
        Followers forward to the leader (reference proposals with retry,
        cluster-raft/src/router.rs:146-196)."""
        deadline = asyncio.get_running_loop().time() + timeout
        backoff = 0.05
        while True:
            if self.state == LEADER:
                self.log.append((self.term, entry))
                idx = self._last_index()
                self._persist_append(idx)
                fut = asyncio.get_running_loop().create_future()
                self._commit_waiters[idx] = fut
                await self._replicate_all()
                try:
                    remaining = deadline - asyncio.get_running_loop().time()
                    await asyncio.wait_for(fut, max(0.05, remaining))
                    return True
                except asyncio.TimeoutError:
                    self._commit_waiters.pop(idx, None)
                    return False
            elif self.leader_id is not None and self.leader_id in self.peers:
                try:
                    reply = await self.peers[self.leader_id].call(
                        RAFT_PROPOSE, {"entry": entry},
                        timeout=max(0.1, deadline - asyncio.get_running_loop().time()),
                    )
                    if reply.get("ok"):
                        try:
                            # wait until the entry reaches *this* node's state
                            await self._wait_applied(reply["index"], deadline)
                            return True
                        except asyncio.TimeoutError:
                            return False  # committed on the leader; local apply lags
                except (PeerUnavailable, ClusterReplyError):
                    pass
            if asyncio.get_running_loop().time() >= deadline:
                return False
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 0.5)

    async def _wait_applied(self, index: int, deadline: float) -> None:
        while self.last_applied < index:
            if asyncio.get_running_loop().time() >= deadline:
                raise asyncio.TimeoutError
            await asyncio.sleep(0.02)

    # ------------------------------------------------------------- handlers
    async def on_message(self, mtype: str, body: Any) -> Optional[dict]:
        """Dispatch raft RPCs (wired into the ClusterServer handler)."""
        if mtype == RAFT_VOTE:
            return self._on_vote(body)
        if mtype == RAFT_APPEND:
            return await self._on_append(body)
        if mtype == RAFT_SNAP:
            return await self._on_snapshot(body)
        if mtype == RAFT_PROPOSE:
            if self.state != LEADER:
                raise ClusterReplyError("not leader")
            self.log.append((self.term, body["entry"]))
            idx = self._last_index()
            self._persist_append(idx)
            fut = asyncio.get_running_loop().create_future()
            self._commit_waiters[idx] = fut
            await self._replicate_all()
            try:
                await asyncio.wait_for(fut, 5.0)
            except asyncio.TimeoutError as e:
                raise ClusterReplyError("commit timeout") from e
            return {"ok": True, "index": idx}
        return None

    def _on_vote(self, body: dict) -> dict:
        term = body["term"]
        my_last_term = self._term_at(self._last_index())
        up_to_date = (body["last_log_term"], body["last_log_index"]) >= (
            my_last_term, self._last_index()
        )
        if body.get("prevote"):
            # pre-vote: no state changes; grant iff we'd grant a real vote
            # at that term AND no leader looks alive — ourselves included
            # (a leader's own _last_heartbeat is not refreshed while leading)
            leader_alive = self.state == LEADER or self._heard_from_leader_recently()
            granted = term >= self.term and up_to_date and not leader_alive
            return {"term": self.term, "granted": granted}
        if term > self.term:
            self._step_down(term)
        granted = False
        if term >= self.term and self.voted_for in (None, body["candidate"]):
            if up_to_date:
                granted = True
                self.voted_for = body["candidate"]
                self._save_meta()
                self._last_heartbeat = asyncio.get_running_loop().time()
        return {"term": self.term, "granted": granted}

    async def _on_append(self, body: dict) -> dict:
        term = body["term"]
        if term < self.term:
            return {"term": self.term, "success": False, "match": self.last_applied}
        if term > self.term:
            self._step_down(term)
        elif self.state != FOLLOWER:
            self.state = FOLLOWER
        self.leader_id = body["leader"]
        self._last_heartbeat = asyncio.get_running_loop().time()
        prev_index = body["prev_log_index"]
        prev_term = body["prev_log_term"]
        entries = body["entries"]
        if prev_index < self.log_offset:
            # the leader's window overlaps our compacted prefix (possible
            # right after an InstallSnapshot): entries up to log_offset are
            # already part of the snapshot — skip them
            skip = self.log_offset - prev_index
            if skip >= len(entries):
                return {"term": self.term, "success": True, "match": self._last_index()}
            entries = entries[skip:]
            prev_index = self.log_offset
            prev_term = self.snap_term
        if prev_index > self._last_index() or self._term_at(prev_index) != prev_term:
            return {"term": self.term, "success": False, "match": self.commit_index}
        # append, truncating only on an actual conflict (Raft §5.3 — a
        # reordered stale AppendEntries must not clobber newer entries)
        appended_from = None
        for i, (t, e) in enumerate(entries):
            pos = prev_index + i  # absolute index of the entry BEFORE this one
            local = pos - self.log_offset
            if local < len(self.log):
                if self.log[local][0] != t:
                    self.log = self.log[:local]
                    self._persist_truncate(pos)
                    self.log.append((t, e))
                    if appended_from is None:
                        appended_from = pos + 1
            else:
                self.log.append((t, e))
                if appended_from is None:
                    appended_from = pos + 1
        if appended_from is not None:
            self._persist_append(appended_from)
        if body["leader_commit"] > self.commit_index:
            self.commit_index = min(body["leader_commit"], self._last_index())
            await self._apply_committed()
        return {"term": self.term, "success": True, "match": self._last_index()}

    async def _on_snapshot(self, body: dict) -> dict:
        """InstallSnapshot (Raft §7): replace local state wholesale."""
        term = body["term"]
        if term < self.term:
            return {"term": self.term, "success": False}
        if term > self.term:
            self._step_down(term)
        elif self.state != FOLLOWER:
            self.state = FOLLOWER
        self.leader_id = body["leader"]
        self._last_heartbeat = asyncio.get_running_loop().time()
        idx, sterm, blob = body["index"], body["snap_term"], body["data"]
        if idx <= self.log_offset:
            return {"term": self.term, "success": True, "match": self._last_index()}
        async with self._apply_lock:
            if self.restore_cb is not None:
                await self.restore_cb(unpack_snapshot(blob))
            if idx < self._last_index() and self._term_at(idx) == sterm:
                # our log extends past the snapshot: keep the suffix (§7)
                self.log = self.log[idx - self.log_offset:]
            else:
                self.log = []
            self.log_offset = idx
            self.snap_term = sterm
            self._snap_blob = blob
            self.last_applied = idx
            self.commit_index = max(self.commit_index, idx)
            if self.storage is not None:
                self.storage.put(
                    "raft", "snapshot", {"index": idx, "term": sterm, "data": blob}
                )
                self.storage.delete_int_upto("raft_log", idx)
                self._persist_truncate(self._last_index())
                self._persist_append(self.log_offset + 1)
        return {"term": self.term, "success": True, "match": self._last_index()}
