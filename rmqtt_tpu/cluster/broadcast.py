"""Broadcast (scatter-gather) cluster mode.

Mirrors `rmqtt-plugins/rmqtt-cluster-broadcast` (SURVEY.md §2.3): no shared
route table — each node routes its local subscriptions; a publish is
broadcast to every peer, each matches locally and delivers its non-shared
subscribers, returning its shared-subscription candidates; the publishing
node then performs the *global* shared-group choice and sends targeted
``ForwardsTo`` (`src/shared.rs:367-560`). Session takeover kicks fan out via
``select_ok`` (`src/lib.rs:179-200`); retained messages are broadcast on set
and synced from peers at startup (`src/lib.rs:146-149`).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from rmqtt_tpu.broker.hooks import HookType
from rmqtt_tpu.broker.session import DeliverItem
from rmqtt_tpu.broker.shared import SessionRegistry
from rmqtt_tpu.broker.tracing import CURRENT_TRACE
from rmqtt_tpu.broker.types import Message
from rmqtt_tpu.cluster import messages as M
from rmqtt_tpu.cluster.membership import (
    _SYNC_UNHANDLED,
    Membership,
    handle_sync_message,
    retain_digest,
    routes_digest,
)
from rmqtt_tpu.cluster.transport import (
    Broadcaster,
    ClusterReplyError,
    ClusterServer,
    PeerClient,
    PeerUnavailable,
)
from rmqtt_tpu.router.base import Id, SubRelation

log = logging.getLogger("rmqtt_tpu.cluster")


_UNHANDLED = object()


def _spawn(cluster, coro) -> None:
    """Strong-ref'd fire-and-forget task (asyncio holds tasks weakly — an
    unreferenced task could be GC'd before it runs)."""
    task = asyncio.get_running_loop().create_task(coro)
    cluster._bg_tasks.add(task)
    task.add_done_callback(cluster._bg_tasks.discard)


def _bg_notify(cluster, peer, mtype: str, body) -> None:
    """Fire-and-forget peer notify from a handler."""

    async def push():
        try:
            await peer.notify(mtype, body)
        except PeerUnavailable:
            log.warning("%s to node %s failed", mtype, peer.node_id)

    _spawn(cluster, push())


class ClusterNode:
    """Peer-mesh behavior shared by both cluster modes: the peer table with
    overload-registry breakers, the membership failure detector
    (cluster/membership.py), DEAD-peer filtering for the fan-out paths, and
    the retain-sync push with reason-labeled loss accounting."""

    def _init_mesh(
        self,
        ctx,
        listen: Tuple[str, int],
        peers: List[Tuple[int, str, int]],
        sync_retains: bool,
        retain_sync_mode: str,
        heartbeat_interval: float = 1.0,
        suspect_timeout: float = 3.0,
        dead_timeout: float = 6.0,
        alive_hold: int = 2,
        anti_entropy: bool = True,
    ) -> None:
        self.ctx = ctx
        self.server = ClusterServer(listen[0], listen[1], self._on_message)
        self.peers: Dict[int, PeerClient] = {
            nid: PeerClient(nid, host, port) for nid, host, port in peers
        }
        # per-peer circuit breakers come FROM the overload registry so the
        # [overload] breaker_* knobs apply to cluster transport and a dead
        # peer is visible in /api/v1/overload and $SYS (broker/overload.py)
        for nid, p in self.peers.items():
            p.breaker = ctx.overload.breaker(f"cluster.peer.{nid}")
        self.bcast = Broadcaster(list(self.peers.values()))
        # "full": replicate every retain set + startup pull; "topic_only":
        # no replication, lazy per-filter fetch at subscribe time
        # (retain.rs:162 RetainSyncMode Full vs TopicOnly)
        self.retain_sync_mode = retain_sync_mode
        self.sync_retains = sync_retains and retain_sync_mode == "full"
        # strong refs: asyncio holds tasks weakly — an unreferenced
        # background task could be GC'd before it runs
        self._bg_tasks: set = set()
        # heartbeat failure detector + anti-entropy driver ([cluster]
        # heartbeat/suspect/dead knobs); reads self.peers live, so peers
        # injected after start() (test meshes) are probed too
        self.membership = Membership(
            self, ctx,
            heartbeat_interval=heartbeat_interval,
            suspect_timeout=suspect_timeout,
            dead_timeout=dead_timeout,
            alive_hold=alive_hold,
            anti_entropy=anti_entropy,
        )
        ctx.retain.on_set = self._on_retain_set

    @property
    def bound_port(self) -> int:
        return self.server.bound_port

    def spawn(self, coro) -> None:
        _spawn(self, coro)

    # ----------------------------------------------------- peer filtering
    def live_peers(self) -> List[PeerClient]:
        """Peers worth scattering to: membership says not DEAD. SUSPECT
        peers still get traffic (they may only be slow); DEAD peers are
        skipped immediately instead of paying a per-call timeout."""
        ms = self.membership
        return [p for p in self.peers.values() if not ms.is_dead(p.node_id)]

    def kickable_peers(self) -> List[PeerClient]:
        """Peers a takeover kick must consult: DEAD peers and circuit-open
        peers (breaker OPEN, probe window not yet due) hold no reachable
        session by definition — treating them as "no session there" keeps
        CONNECT latency bounded by the heartbeat window, not the RPC
        timeout."""
        ms = self.membership
        out = []
        for p in self.peers.values():
            if ms.is_dead(p.node_id):
                continue
            b = p.breaker
            if b.state == b.OPEN and b.remaining() > 0:
                continue
            out.append(p)
        return out

    def snapshot(self) -> dict:
        """/api/v1/cluster body: membership + repair state + the digests
        the anti-entropy exchange compares (convergence is observable).
        The retain digest is revision-cached in the store (exact); the
        subscription-directory digest is an O(routes) pass with no cheap
        version key, so it is TTL-cached here — admin polls see at most
        ``heartbeat_interval`` of staleness instead of hashing a 10M-route
        table per request (the repair path always recomputes)."""
        now = time.monotonic()
        cached = getattr(self, "_routes_digest_cache", None)
        if cached is None or now - cached[0] > self.membership.heartbeat_interval:
            cached = (now, routes_digest(self.ctx.router))
            self._routes_digest_cache = cached
        return {
            "mode": getattr(self, "mode", "broadcast"),
            "retain_sync_mode": self.retain_sync_mode,
            "membership": self.membership.snapshot(),
            "digests": {
                "retain": retain_digest(self.ctx.retain),
                "subs": cached[1],
            },
        }

    # ----------------------------------------------------- retain push
    def _on_retain_set(self, topic: str, msg: Optional[Message]) -> None:
        """Replicate a retained set/clear to peers (full mode). Pushes that
        cannot be delivered — peer DEAD, or the notify fails — are counted
        as reason-labeled drops (``messages.dropped.retain_sync``) so
        divergence is visible until anti-entropy heals it on rejoin."""
        if self.retain_sync_mode != "full":
            return  # TopicOnly: peers fetch lazily at subscribe time
        body = {"topic": topic, "msg": M.msg_to_wire(msg) if msg else None}

        async def push():
            ms = self.membership
            targets, dead = [], 0
            for p in self.peers.values():
                if ms.is_dead(p.node_id):
                    dead += 1
                else:
                    targets.append(p)
            if dead:
                self.ctx.metrics.drop("retain_sync", dead)
            if targets:
                errs = await Broadcaster(targets).join_all_notify(
                    M.SET_RETAIN, body)
                failed = sum(1 for e in errs if e is not None)
                if failed:
                    self.ctx.metrics.drop("retain_sync", failed)

        self.spawn(push())


async def handle_common_message(ctx, mtype: str, body, cluster=None, from_node=None) -> object:
    """RPC handlers shared by broadcast and raft modes (ForwardsTo, Kick,
    retain sync, counters, liveness). Returns ``_UNHANDLED`` for
    mode-specific types."""
    if mtype == M.FORWARDS_TO:
        msg = M.msg_from_wire(body["msg"])
        # adopt the publisher's trace context (optional field, absent from
        # untraced publishes): spans recorded here carry the SAME trace id
        # and are stitched back by the trace API's cluster fetch
        trace = ctx.tracer.from_wire(body.get("trace"), topic=msg.topic)
        t_tr = time.perf_counter_ns() if trace is not None else 0
        count = 0
        recipients: List[str] = []
        if body.get("p2p"):
            target = ctx.registry.get(body["p2p"])
            if target is None:
                raise ClusterReplyError("no-such-client")  # select_ok tries next peer
            target.enqueue(DeliverItem(msg=msg, qos=msg.qos, retain=False,
                                       topic_filter="", trace=trace))
            count, recipients = 1, [body["p2p"]]
        else:
            wire_cache: dict = {}  # shared per inbound fan-out
            for rw in body["rels"]:
                rel = M.relation_from_wire(rw)
                if ctx.registry._deliver_local(rel.id.client_id, rel.topic_filter,
                                               rel.opts, msg, wire_cache, trace):
                    count += 1
                    recipients.append(rel.id.client_id)
        if trace is not None:
            trace.add("cluster.remote_deliver", t_tr,
                      time.perf_counter_ns() - t_tr,
                      {"count": count, "node": ctx.node_id})
            ctx.tracer.finish(trace)
        # fire-and-forget mark-forwarded ack back to the publishing node
        # (cluster-raft/src/shared.rs:596-613 ForwardsToAck); the sender's
        # node id rides in the body (the transport has no peer identity)
        sender = body.get("from_node", from_node)
        if msg.stored_id is not None and recipients and cluster is not None:
            peer = cluster.peers.get(sender)
            if peer is not None:
                _bg_notify(cluster, peer, M.FORWARDS_TO_ACK,
                           {"sid": msg.stored_id, "recipients": recipients,
                            "ttl": msg.expiry_interval})
        return {"count": count}
    if mtype == M.FORWARDS_TO_ACK:
        mgr = getattr(ctx, "message_mgr", None)
        if mgr is not None:
            for cid in body.get("recipients", []):
                mgr.mark_forwarded(body["sid"], cid, ttl=body.get("ttl"))
        return None
    if mtype == M.MESSAGE_GET:
        # merge_on_read fetch (cluster-raft/src/shared.rs:665-699): return
        # this node's unforwarded stored matches, marking them so the
        # requesting node's replay can't repeat on a later subscribe
        mgr = getattr(ctx, "message_mgr", None)
        if mgr is None:
            return {"msgs": []}
        if getattr(mgr, "_net", False):
            # network store: the scan is multiple socket RTTs — off-loop
            import asyncio as _aio

            rows = await _aio.get_running_loop().run_in_executor(
                None, mgr.load_unforwarded, body["filter"],
                body["client_id"], True)
        else:
            rows = mgr.load_unforwarded(body["filter"], body["client_id"],
                                        mark=True)
        return {"msgs": [[sid, M.msg_to_wire(m)] for sid, m in rows]}
    if mtype == M.KICK:
        session = ctx.registry.get(body["client_id"])
        if session is not None:
            if session.state is not None:
                await session.state.close(kicked=True)
                # wait (bounded) for the old loop to unwind so the caller's
                # new session starts after this one is dead
                for _ in range(100):
                    if not session.connected:
                        break
                    await asyncio.sleep(0.01)
            # resumable session + resuming client: hand the state to the new
            # owner node (the reference's SessionStateTransfer,
            # session.rs:1374-1427) before dropping the local copy
            state = None
            if not body.get("clean_start", True) and session.limits.session_expiry > 0:
                from rmqtt_tpu.broker.session import session_snapshot

                # cap for the RPC frame; persistence paths snapshot uncapped
                state = session_snapshot(session, max_queue_items=5000)
            await ctx.registry.terminate(session, "cluster-kick")
            return {"kicked": True, "state": state}
        return {"kicked": False}
    if mtype == M.GET_RETAINS:
        # "match" requests MQTT wildcard semantics ($-topics excluded from
        # wildcards, topic.rs:185-210) — the subscribe-time TopicOnly fetch;
        # the bare "#" form is the full-store replication pull (startup
        # sync), which must include $-topics
        filt = body.get("filter", "#")
        if body.get("match"):
            items = ctx.retain.matches(filt)
        else:
            items = ctx.retain.all_items() if filt == "#" else ctx.retain.matches(filt)
        return {"retains": [[topic, M.msg_to_wire(m)] for topic, m in items]}
    if mtype == M.SET_RETAIN:
        mw = body.get("msg")
        if mw is None:
            ctx.retain.remove_local(body["topic"])
        else:
            ctx.retain.set_local(body["topic"], M.msg_from_wire(mw))
        return None
    if mtype == M.NUMBER_OF_CLIENTS:
        return {"count": ctx.registry.connected_count()}
    if mtype == M.NUMBER_OF_SESSIONS:
        return {"count": ctx.registry.session_count()}
    if mtype == M.ONLINE:
        s = ctx.registry.get(body["client_id"])
        return {"online": bool(s and s.connected)}
    if mtype == M.SESSION_STATUS:
        s = ctx.registry.get(body["client_id"])
        if s is None:
            return {"exists": False}
        return {"exists": True, "online": s.connected, "subs": len(s.subscriptions)}
    if mtype == M.SUBSCRIPTIONS_GET:
        from rmqtt_tpu.broker.http_api import subscription_rows

        return {"subscriptions": subscription_rows(ctx, int(body.get("limit", 100)))}
    if mtype == M.SUBSCRIPTIONS_SEARCH:
        from rmqtt_tpu.broker.http_api import subscription_search

        return {"subscriptions": subscription_search(ctx, body or {})}
    if mtype == M.ROUTES_GET:
        return {"routes": ctx.router.gets(int(body.get("limit", 100)))}
    if mtype == M.ROUTES_GET_BY:
        from rmqtt_tpu.broker.http_api import routes_by_topic

        return {"routes": routes_by_topic(ctx, body["topic"])}
    if mtype == M.CLIENTS_GET:
        from rmqtt_tpu.broker.http_api import client_info

        limit = int(body.get("limit", 100))
        return {"clients": [client_info(s) for s in list(ctx.registry.sessions())[:limit]]}
    if mtype == M.STATS_GET:
        return {"node": ctx.node_id, "stats": ctx.stats().to_json()}
    if mtype == M.DATA:
        # opaque data channel (grpc.rs Message::Data); carries the admin
        # API's cluster queries that have no dedicated variant
        what = (body or {}).get("what")
        if what == "metrics":
            return {"metrics": ctx.metrics.to_json()}
        if what == "latency":
            # per-node latency histograms for /api/v1/latency/sum; buckets
            # merge by addition on the requesting node
            return {"latency": ctx.telemetry.snapshot()}
        if what == "slo":
            # per-node SLO snapshot for /api/v1/slo/sum; (good, total)
            # pairs sum per objective on the requesting node
            return {"slo": ctx.slo.snapshot()}
        if what == "device":
            # per-node device-plane profiler snapshot for
            # /api/v1/device/sum (broker/devprof.py merge_snapshots)
            from rmqtt_tpu.broker.devprof import DEVPROF

            return {"device": DEVPROF.snapshot()}
        if what == "autotune":
            # per-node autotuner snapshot for /api/v1/autotune/sum
            # (broker/autotune.py merge_snapshots: counters sum, state
            # merges by worst; journals stay per-node)
            return {"autotune": ctx.autotune.snapshot()}
        if what == "host":
            # per-node host-plane profiler snapshot for /api/v1/host/sum
            # (broker/hostprof.py merge_snapshots: lag histograms
            # bucket-merge, counters sum)
            from rmqtt_tpu.broker.hostprof import HOSTPROF

            return {"host": HOSTPROF.snapshot()}
        if what == "history":
            # per-node telemetry timeline for /api/v1/history/sum
            # (broker/history.py merge_snapshots: step buckets align,
            # counters sum, quantile/rate series average, states worst);
            # the range/series/step params forward so every node answers
            # the same question
            return {"history": ctx.history.query(
                series=body.get("series"), frm=body.get("from"),
                to=body.get("to"), step=body.get("step"))}
        if what == "hotkeys":
            # per-node hot-key sketch snapshot for /api/v1/hotkeys/sum
            # (broker/hotkeys.py merge_snapshots: top-k lists fold under
            # the mergeable-summaries rule, totals/counters sum)
            return {"hotkeys": ctx.hotkeys.snapshot()}
        if what == "traces":
            # trace-API cluster fetch (broker/tracing.py): by id → this
            # node's spans for that trace (the requester stitches);
            # otherwise recent/slow summaries for the merged listings
            tid = body.get("id")
            if tid is not None:
                return {"trace": ctx.tracer.get(str(tid))}
            limit = int(body.get("limit", 50))
            if body.get("slow"):
                return {"traces": ctx.tracer.slow_traces(limit)}
            return {"traces": ctx.tracer.recent(limit)}
        if what == "offlines":
            from rmqtt_tpu.broker.http_api import client_info

            return {"clients": [client_info(s) for s in ctx.registry.sessions()
                                if not s.connected]}
        if what == "purge_offlines":
            offl = [s for s in ctx.registry.sessions() if not s.connected]
            for s in offl:
                await ctx.registry.terminate(s, "api-purge-offline")
            return {"purged": len(offl)}
        return {"data": None}
    if mtype == M.PING:
        return {"pong": True}
    # membership heartbeats + anti-entropy exchange (cluster/membership.py)
    res = await handle_sync_message(ctx, mtype, body, cluster=cluster)
    if res is not _SYNC_UNHANDLED:
        return res
    return _UNHANDLED


class ClusterRegistryBase(SessionRegistry):
    """Shared cluster-registry behavior: the cross-node kick + session-state
    transfer protocol used by both broadcast and raft modes."""

    def __init__(self, ctx) -> None:
        super().__init__(ctx)
        self.cluster = None

    async def take_or_create(self, ctx, id: Id, connect_info, limits, clean_start: bool):
        # tell peers to drop any session with this id and WAIT for their
        # confirmation (broadcast-mode kick, src/lib.rs:179-200); a resumable
        # session's state comes back in the reply and is rebuilt locally
        # (the reference's SessionStateTransfer). Peers the membership
        # detector marks DEAD — or whose circuit is open — hold no
        # reachable session by definition: they are skipped outright, so a
        # killed node costs CONNECTs nothing once detected (the heartbeat
        # window, not the RPC timeout, bounds the stall) and the rejoin
        # anti-entropy fence pass cleans up any conflict that slips through
        if self.cluster is not None and self.cluster.peers:
            peers = self.cluster.kickable_peers()
            skipped = len(self.cluster.peers) - len(peers)
            if skipped:
                self.ctx.metrics.inc("cluster.kick_skipped", skipped)
            if peers:
                replies = await Broadcaster(peers).join_all_call(
                    M.KICK,
                    {"client_id": id.client_id, "clean_start": clean_start},
                )
                await self._restore_transferred(ctx, id, clean_start, replies)
        return await super().take_or_create(ctx, id, connect_info, limits, clean_start)

    async def retain_load_with(self, topic_filter: str):
        """TopicOnly retain sync (reference retain.rs:162 `retain_sync_mode`
        + :178 `sync_retain_topic`): with no full-store replication, fetch
        the peers' retained matches for exactly this filter at subscribe
        time and dedup by topic keeping the newest create_time
        (shared.rs:1109-1127 dedup_retains_by_topic)."""
        local = self.ctx.retain.matches(topic_filter)
        c = self.cluster
        if c is None or not c.peers or c.retain_sync_mode != "topic_only":
            return local
        best = {topic: msg for topic, msg in local}
        for _nid, reply in await Broadcaster(c.live_peers()).join_all_call(
            M.GET_RETAINS, {"filter": topic_filter, "match": True}
        ):
            if isinstance(reply, Exception):
                continue
            for topic, mw in reply.get("retains", []):
                msg = M.msg_from_wire(mw)
                if msg.is_expired():
                    continue
                cur = best.get(topic)
                if cur is None or msg.create_time > cur.create_time:
                    best[topic] = msg
        return sorted(best.items())

    async def _restore_transferred(self, ctx, id, clean_start: bool, replies) -> None:
        if clean_start or ctx.registry.get(id.client_id) is not None:
            return
        for _nid, reply in replies:
            if isinstance(reply, Exception) or not isinstance(reply, dict):
                continue
            snap = reply.get("state")
            if snap:
                from rmqtt_tpu.broker.session import restore_session

                await restore_session(ctx, snap, node_id=id.node_id)
                return


def _cands_to_wire(shared) -> list:
    return [
        [group, tf, [[sid.node_id, sid.client_id, M.opts_to_wire(opts), online]
                     for sid, opts, online in cands]]
        for (group, tf), cands in shared.items()
    ]


def _cands_from_wire(rows) -> Dict[Tuple[str, str], list]:
    out: Dict[Tuple[str, str], list] = {}
    for group, tf, cands in rows:
        out[(group, tf)] = [
            (Id(n, c), M.opts_from_wire(o), online) for n, c, o, online in cands
        ]
    return out


class ClusterSessionRegistry(ClusterRegistryBase):
    """Registry whose fan-out scatter-gathers across the cluster."""

    async def forwards(self, msg: Message) -> int:
        cluster = self.cluster
        if cluster is None or not cluster.peers:
            return await super().forwards(msg)
        # trace context set by the publish ingress (broker/tracing.py);
        # rides every peer RPC so remote spans share the trace id
        trace = CURRENT_TRACE.get() if self.ctx.telemetry.enabled else None
        tw = M.trace_to_wire(trace)
        if msg.target_clientid is not None:  # p2p: local first, then peers
            if self._sessions.get(msg.target_clientid) is not None:
                return await super().forwards(msg)
            try:
                await Broadcaster(cluster.live_peers()).select_ok(
                    M.FORWARDS_TO, {
                        "msg": M.msg_to_wire(msg),
                        "rels": [],
                        "p2p": msg.target_clientid,
                        "from_node": self.ctx.node_id,
                        "trace": tw,
                    })
                return 1
            except (PeerUnavailable, ClusterReplyError):
                return 0  # no node owns this client
        # 1) local: deliver non-shared, collect shared candidates
        raw = await self.ctx.routing.matches_raw(msg.from_id, msg.topic)
        relmap, shared = raw
        count, _ = self._deliver_relmap(relmap, msg, trace)
        # 2) scatter: LIVE peers deliver their non-shared and reply
        # candidates; membership-DEAD peers are skipped outright (a dead
        # node must not add a call timeout to every publish)
        scatter = cluster.live_peers()
        t_fw = time.perf_counter_ns() if trace is not None else 0
        replies = await Broadcaster(scatter).join_all_call(
            M.FORWARDS, {"msg": M.msg_to_wire(msg), "trace": tw}
        )
        if trace is not None:
            trace.add("cluster.forward", t_fw, time.perf_counter_ns() - t_fw,
                      {"mode": "broadcast", "peers": len(scatter)})
        mgr = getattr(self.ctx, "message_mgr", None)
        merged: Dict[Tuple[str, str], list] = {k: list(v) for k, v in shared.items()}
        for node_id, reply in replies:
            if isinstance(reply, Exception):
                continue
            count += int(reply.get("count", 0))
            # remote live deliveries count as forwarded in this node's store
            # (the broadcast-mode analogue of ForwardsToAck bookkeeping)
            if mgr is not None and msg.stored_id is not None:
                for cid in reply.get("recipients", []):
                    mgr.mark_forwarded(msg.stored_id, cid, ttl=msg.expiry_interval)
            for key, cands in _cands_from_wire(reply.get("shared", [])).items():
                merged.setdefault(key, []).extend(cands)
        # 3) global shared-group choice (src/shared.rs:516-560)
        remote_targets: Dict[int, List[SubRelation]] = {}
        for (group, tf), cands in merged.items():
            idx = self.ctx.router._shared_choice(group, tf, cands)
            if idx is None:
                continue
            sid, opts, _ = cands[idx]
            rel = SubRelation(tf, sid, opts)
            if trace is not None:
                # zero-duration marker: WHO won the cluster-global
                # round-robin for this publish (the decision, not a stage)
                trace.add_wall("shared.choice", 0, {
                    "group": group, "filter": tf,
                    "node": sid.node_id, "client": sid.client_id})
            if sid.node_id == self.ctx.node_id:
                count += self._deliver_local(sid.client_id, tf, opts, msg,
                                             trace=trace)
            else:
                remote_targets.setdefault(sid.node_id, []).append(rel)
        for node_id, rels in remote_targets.items():
            peer = cluster.peers.get(node_id)
            if peer is None:
                continue
            if cluster.membership.is_dead(node_id):
                # targeted shared-sub deliveries to a DEAD node: lost, but
                # lost FAST and reason-labeled (no per-publish timeout)
                self.ctx.metrics.drop("peer_dead", len(rels))
                continue
            try:
                await peer.notify(M.FORWARDS_TO, {
                    "msg": M.msg_to_wire(msg),
                    "rels": [M.relation_to_wire(r) for r in rels],
                    "p2p": None,
                    "from_node": self.ctx.node_id,
                    "trace": tw,
                })
                count += len(rels)
                self.ctx.metrics.inc("cluster.forwards")
            except PeerUnavailable:
                # the targeted shared-sub deliveries are lost: reason-label
                # them (circuit_open when the breaker is holding the peer
                # off, plain unreachable otherwise)
                reason = ("circuit_open"
                          if peer.breaker.state != peer.breaker.CLOSED
                          else "peer_unreachable")
                self.ctx.metrics.drop(reason, len(rels))
                log.warning("ForwardsTo to node %s failed (%s)", node_id, reason)
        return count

    def _deliver_relmap(self, relmap, msg: Message, trace=None) -> Tuple[int, List[str]]:
        count = 0
        recipients: List[str] = []
        wire_cache: dict = {}  # shared per fan-out (frame reuse)
        for _node, rels in relmap.items():
            for rel in rels:
                if self._deliver_local(rel.id.client_id, rel.topic_filter,
                                       rel.opts, msg, wire_cache, trace):
                    count += 1
                    recipients.append(rel.id.client_id)
        return count, recipients

class BroadcastCluster(ClusterNode):
    mode = "broadcast"

    def __init__(
        self,
        ctx,
        listen: Tuple[str, int],
        peers: List[Tuple[int, str, int]],
        sync_retains: bool = True,
        retain_sync_mode: str = "full",
        **membership_opts,
    ) -> None:
        self._init_mesh(ctx, listen, peers, sync_retains, retain_sync_mode,
                        **membership_opts)
        assert isinstance(ctx.registry, ClusterSessionRegistry), (
            "cluster mode needs ServerContext(registry='cluster')"
        )
        ctx.registry.cluster = self

    async def start(self) -> None:
        await self.server.start()
        self.membership.start()

    async def start_sync(self) -> None:
        """Pull retained messages from peers (startup sync, lib.rs:146-149)."""
        if not self.sync_retains:
            return
        for node_id, reply in await Broadcaster(self.live_peers()).join_all_call(
            M.GET_RETAINS, {"filter": "#"}
        ):
            if isinstance(reply, Exception):
                continue
            for topic, mw in reply.get("retains", []):
                msg = M.msg_from_wire(mw)
                self.ctx.retain.set_local(topic, msg)

    async def stop(self) -> None:
        await self.membership.stop()
        await self.server.stop()
        for p in self.peers.values():
            await p.close()

    # ------------------------------------------------------------ inbound
    async def _on_message(self, mtype: str, body: Any, _from_node) -> Any:
        ctx = self.ctx
        # cluster-RPC arrival hook (hook.rs GrpcMessageReceived — our RPC
        # mesh replaces gRPC but keeps the event)
        await ctx.hooks.fire(HookType.GRPC_MESSAGE_RECEIVED, mtype, _from_node, None)
        if mtype == M.FORWARDS:
            # scatter-gather: deliver local non-shared, reply shared candidates
            msg = M.msg_from_wire(body["msg"])
            # adopt the publisher's trace for THIS node's spans (the
            # contextvar makes the local routing queue/match stages stamp
            # them; trace id comes off the wire, so the publisher's trace
            # API fetch stitches the remote hop in)
            trace = ctx.tracer.from_wire(body.get("trace"), topic=msg.topic)
            tok = CURRENT_TRACE.set(trace) if trace is not None else None
            t_tr = time.perf_counter_ns() if trace is not None else 0
            try:
                raw = await ctx.routing.matches_raw(msg.from_id, msg.topic)
                relmap, shared = raw
                count, recipients = ctx.registry._deliver_relmap(relmap, msg, trace)
            finally:
                if tok is not None:
                    CURRENT_TRACE.reset(tok)
            if trace is not None:
                trace.add("cluster.remote_match", t_tr,
                          time.perf_counter_ns() - t_tr,
                          {"count": count, "node": ctx.node_id})
                ctx.tracer.finish(trace)
            return {"count": count, "shared": _cands_to_wire(shared),
                    "recipients": recipients if msg.stored_id is not None else []}
        res = await handle_common_message(ctx, mtype, body, cluster=self, from_node=_from_node)
        if res is not _UNHANDLED:
            return res
        raise ValueError(f"unknown cluster message {mtype!r}")