"""Cluster RPC message vocabulary + (de)serialization of broker DTOs.

Mirrors the reference's 19-variant ``Message`` enum and ``MessageReply``
(`/root/reference/rmqtt/src/grpc.rs:506-535, 616-638`): the same taxonomy —
Forwards / ForwardsTo(+recipient bookkeeping) / Kick / retain sync /
subscription queries / counters / online checks / ping / opaque data —
carried over the asyncio TCP mesh instead of tonic gRPC.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from rmqtt_tpu.broker.types import Message
from rmqtt_tpu.router.base import Id, SubRelation, SubscriptionOptions

# message type tags (grpc.rs Message variants)
FORWARDS = "forwards"
FORWARDS_TO = "forwards_to"
FORWARDS_TO_ACK = "forwards_to_ack"  # mark-forwarded bookkeeping (shared.rs:596-613)
KICK = "kick"
GET_RETAINS = "get_retains"
SET_RETAIN = "set_retain"
NUMBER_OF_CLIENTS = "number_of_clients"
NUMBER_OF_SESSIONS = "number_of_sessions"
ONLINE = "online"
SESSION_STATUS = "session_status"
SUBSCRIPTIONS_GET = "subscriptions_get"
SUBSCRIPTIONS_SEARCH = "subscriptions_search"  # grpc.rs SubscriptionsSearch
CLIENTS_GET = "clients_get"
STATS_GET = "stats_get"
ROUTES_GET = "routes_get"
ROUTES_GET_BY = "routes_get_by"  # grpc.rs RoutesGetBy(Topic)
MESSAGE_GET = "message_get"  # cross-node stored-message fetch (merge_on_read)
PING = "ping"
DATA = "data"
# membership + anti-entropy vocabulary (cluster/membership.py): the failure
# detector's periodic probe (carries incarnation + fence clock) and the
# rejoin repair protocol — digests first, deltas only where they differ
HEARTBEAT = "heartbeat"
SYNC_DIGEST = "sync_digest"  # retained-store + subscription-directory digests
SYNC_RETAIN_SUMMARY = "sync_retain_summary"  # {topic: [ct, payload_hash]}
SYNC_RETAIN_PULL = "sync_retain_pull"  # fetch named topics' retained msgs
SYNC_RETAIN_PUSH = "sync_retain_push"  # deliver newer-here retained msgs
SYNC_SESSIONS = "sync_sessions"  # duplicate-session fence resolution
SYNC_ROUTES = "sync_routes"  # raft-mode route-table pull (repair fallback)

# reply tags
OK = "ok"
ERROR = "error"


def msg_to_wire(m: Message) -> dict:
    return {
        "topic": m.topic,
        "payload": m.payload,
        "qos": m.qos,
        "retain": m.retain,
        "props": [[k, v] for k, v in m.properties.items()],
        "ct": m.create_time,
        "exp": m.expiry_interval,
        "from": [m.from_id.node_id, m.from_id.client_id] if m.from_id else None,
        "target": m.target_clientid,
        "sid": m.stored_id,
    }


def msg_from_wire(d: dict) -> Message:
    props = {}
    for k, v in d.get("props") or []:
        if isinstance(v, list):
            # repeatable props: user-property pairs come back as 2-lists
            v = [tuple(x) if isinstance(x, list) else x for x in v]
        props[k] = v
    frm = d.get("from")
    return Message(
        topic=d["topic"],
        payload=d["payload"],
        qos=d["qos"],
        retain=d["retain"],
        properties=props,
        create_time=d["ct"],
        expiry_interval=d["exp"],
        from_id=Id(frm[0], frm[1]) if frm else None,
        target_clientid=d.get("target"),
        stored_id=d.get("sid"),
    )


def trace_to_wire(trace) -> Optional[list]:
    """Optional trace-context field riding the FORWARDS / FORWARDS_TO
    bodies (broker/tracing.py): ``[trace_id_hex, sampled]``. ``None`` (or
    an absent key) means "untraced" — receivers MUST treat the field as
    optional so frames from nodes without tracing keep decoding; the
    receiving node adopts the id via ``Tracer.from_wire`` so spans recorded
    there stitch back to the publisher's trace."""
    return None if trace is None else [trace.tid, bool(trace.sampled)]


def opts_to_wire(o: SubscriptionOptions) -> list:
    return [o.qos, o.no_local, o.retain_as_published, o.retain_handling,
            list(o.subscription_ids), o.shared_group]


def opts_from_wire(v: list) -> SubscriptionOptions:
    return SubscriptionOptions(
        qos=v[0], no_local=v[1], retain_as_published=v[2], retain_handling=v[3],
        subscription_ids=tuple(v[4]), shared_group=v[5],
    )


def relation_to_wire(r: SubRelation) -> list:
    return [r.topic_filter, r.id.node_id, r.id.client_id, opts_to_wire(r.opts)]


def relation_from_wire(v: list) -> SubRelation:
    return SubRelation(topic_filter=v[0], id=Id(v[1], v[2]), opts=opts_from_wire(v[3]))
