"""Raft cluster mode: strongly-consistent replicated routing.

Mirrors `rmqtt-plugins/rmqtt-cluster-raft` (SURVEY.md §2.3): every node holds
the FULL route table; subscription add/remove go through Raft proposals and
apply on every node (`src/router.rs:146-196, 350-353`), so `matches()` stays
node-local with no per-publish consensus (:199-201). Publish fan-out matches
locally and sends targeted ``ForwardsTo`` to the nodes owning remote
subscribers (`src/shared.rs:454-538`). Cross-node kick and retain sync reuse
the broadcast-mode RPCs.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from rmqtt_tpu.broker.session import DeliverItem
from rmqtt_tpu.broker.shared import SessionRegistry
from rmqtt_tpu.broker.hooks import HookType
from rmqtt_tpu.broker.tracing import CURRENT_TRACE
from rmqtt_tpu.broker.types import HandshakeLockedError, Message
from rmqtt_tpu.cluster import messages as M
from rmqtt_tpu.cluster.broadcast import (
    _UNHANDLED,
    _spawn,
    ClusterNode,
    ClusterRegistryBase,
    handle_common_message,
)
from rmqtt_tpu.cluster.raft import (
    RAFT_APPEND,
    RAFT_PROPOSE,
    RAFT_SNAP,
    RAFT_VOTE,
    RaftNode,
)
from rmqtt_tpu.cluster.transport import (
    Broadcaster,
    ClusterReplyError,
    PeerUnavailable,
)
from rmqtt_tpu.router.base import Id, SubRelation

log = logging.getLogger("rmqtt_tpu.cluster.raft")

# how long a granted handshake lock shields a client id from a competing
# connect on another node (the reference's try-lock timeout,
# cluster-raft/src/shared.rs:71-106)
HS_LOCK_TTL = 10.0


class RaftSessionRegistry(ClusterRegistryBase):
    """Registry whose router mutations go through Raft and whose fan-out
    sends targeted ForwardsTo to subscriber-owning nodes."""

    async def take_or_create(self, ctx, id: Id, connect_info, limits, clean_start: bool):
        """Serialize concurrent connects of the same client id ACROSS nodes
        through a raft-replicated handshake lock (shared.rs:71-106
        HandshakeTryLock) before running the kick/takeover protocol."""
        c = self.cluster
        nonce = None
        if c is not None and c.peers:
            nonce = await c.handshake_try_lock(id.client_id)
            if nonce is None:
                raise HandshakeLockedError(id.client_id)
        try:
            return await super().take_or_create(ctx, id, connect_info, limits, clean_start)
        finally:
            if nonce is not None:
                c.handshake_unlock_bg(id.client_id, nonce)

    # subscription writes → consensus (router.rs:146-196)
    async def router_add(self, stripped: str, id, opts) -> None:
        c = self.cluster
        if c is None or not c.peers:
            self.ctx.router.add(stripped, id, opts)
            return
        ok = await c.raft.propose(
            {"op": "add", "tf": stripped, "node": id.node_id,
             "client": id.client_id, "opts": M.opts_to_wire(opts)}
        )
        if not ok:
            # the entry may still commit later (it stays in the log);
            # compensate so a late commit can't leave a ghost route
            _spawn(c, c.raft.propose({"op": "remove", "tf": stripped,
                                      "node": id.node_id, "client": id.client_id},
                                     timeout=30.0))
            raise ClusterReplyError("raft propose (add) failed")

    def _retry_in_background(self, entry) -> None:
        """Removals must eventually apply — retry with a long deadline when
        consensus is briefly unavailable (no leader / partition)."""
        c = self.cluster
        _spawn(c, c.raft.propose(entry, timeout=120.0))

    async def router_remove(self, stripped: str, id) -> None:
        c = self.cluster
        if c is None or not c.peers:
            self.ctx.router.remove(stripped, id)
            return
        entry = {"op": "remove", "tf": stripped, "node": id.node_id, "client": id.client_id}
        if not await c.raft.propose(entry):
            self._retry_in_background(entry)

    async def router_remove_many(self, items) -> None:
        """One consensus round for a whole session's removals (terminate)."""
        c = self.cluster
        if c is None or not c.peers:
            for stripped, id in items:
                self.ctx.router.remove(stripped, id)
            return
        entry = {
            "op": "remove_many",
            "items": [[stripped, id.node_id, id.client_id] for stripped, id in items],
        }
        if not await c.raft.propose(entry):
            self._retry_in_background(entry)

    async def forwards(self, msg: Message) -> int:
        c = self.cluster
        if c is None or not c.peers:
            return await super().forwards(msg)
        # trace context from the publish ingress (broker/tracing.py); rides
        # the targeted ForwardsTo so the owning nodes' spans stitch back
        trace = CURRENT_TRACE.get() if self.ctx.telemetry.enabled else None
        tw = M.trace_to_wire(trace)
        if msg.target_clientid is not None:
            if self._sessions.get(msg.target_clientid) is not None:
                return await super().forwards(msg)
            try:
                await c.bcast.select_ok(M.FORWARDS_TO, {
                    "msg": M.msg_to_wire(msg), "rels": [], "p2p": msg.target_clientid,
                    "from_node": self.ctx.node_id, "trace": tw,
                })
                return 1
            except (PeerUnavailable, ClusterReplyError):
                return 0
        # match locally over the replicated table (shared.rs:461-467)
        relmap, shared = await self.ctx.routing.matches_raw(msg.from_id, msg.topic)
        count = 0
        remote: Dict[int, List[SubRelation]] = {}
        wire_cache: dict = {}  # shared per fan-out (frame reuse)
        for node_id, rels in relmap.items():
            if node_id == self.ctx.node_id:
                for rel in rels:
                    count += self._deliver_local(rel.id.client_id, rel.topic_filter,
                                                 rel.opts, msg, wire_cache, trace)
            else:
                remote.setdefault(node_id, []).extend(rels)
        # shared groups: all candidates are in the replicated table — choose
        # here, globally (router.rs:236-255 does the choice at match time)
        my_node = self.ctx.node_id
        for (group, tf), cands in shared.items():
            # remote members' liveness is unknown locally — treat them as
            # online so they aren't starved out of the group choice
            cands = [
                (sid, opts, on if sid.node_id == my_node else True)
                for sid, opts, on in cands
            ]
            idx = self.ctx.router._shared_choice(group, tf, cands)
            if idx is None:
                continue
            sid, opts, _ = cands[idx]
            if trace is not None:
                trace.add_wall("shared.choice", 0, {
                    "group": group, "filter": tf,
                    "node": sid.node_id, "client": sid.client_id})
            if sid.node_id == my_node:
                count += self._deliver_local(sid.client_id, tf, opts, msg,
                                             trace=trace)
            else:
                remote.setdefault(sid.node_id, []).append(SubRelation(tf, sid, opts))
        t_fw = time.perf_counter_ns() if (trace is not None and remote) else 0
        for node_id, rels in remote.items():
            peer = c.peers.get(node_id)
            if peer is None:
                continue
            if c.membership.is_dead(node_id):
                # the replicated table still lists the dead node's
                # subscribers; dropping fast + reason-labeled beats paying
                # a breaker-mediated connect attempt per publish
                self.ctx.metrics.drop("peer_dead", len(rels))
                continue
            try:
                await peer.notify(M.FORWARDS_TO, {
                    "msg": M.msg_to_wire(msg),
                    "rels": [M.relation_to_wire(r) for r in rels],
                    "p2p": None,
                    "from_node": self.ctx.node_id,
                    "trace": tw,
                })
                count += len(rels)
                self.ctx.metrics.inc("cluster.forwards")
            except PeerUnavailable:
                log.warning("raft ForwardsTo to node %s failed", node_id)
        if t_fw:
            trace.add("cluster.forward", t_fw, time.perf_counter_ns() - t_fw,
                      {"mode": "raft", "nodes": sorted(remote)})
        return count


class RaftCluster(ClusterNode):
    """Raft node + cluster RPC server, swapped in like the broadcast mode."""

    mode = "raft"

    def __init__(
        self,
        ctx,
        listen: Tuple[str, int],
        peers: List[Tuple[int, str, int]],
        sync_retains: bool = True,
        raft_db: Optional[str] = None,
        retain_sync_mode: str = "full",
        **membership_opts,
    ) -> None:
        self._init_mesh(ctx, listen, peers, sync_retains, retain_sync_mode,
                        **membership_opts)
        storage = None
        if raft_db:
            from rmqtt_tpu.storage.sqlite import SqliteStore

            storage = SqliteStore(raft_db)
        self.raft = RaftNode(
            ctx.node_id, self.peers, self._apply, storage=storage,
            snapshot_cb=self._snapshot_state, restore_cb=self._restore_state,
        )
        assert isinstance(ctx.registry, RaftSessionRegistry), (
            "raft mode needs ServerContext with registry='raft'"
        )
        ctx.registry.cluster = self
        # distributed handshake-lock table (part of the replicated state):
        # client_id -> [node_id, ts, nonce]
        self.hs_locks: Dict[str, list] = {}
        self._hs_results: Dict[str, bool] = {}
        # nonces a local handshake is still awaiting; _apply only records
        # results for these (a lock entry committing after its proposer gave
        # up must not leave an orphan result behind)
        self._hs_pending: set = set()

    async def start(self) -> None:
        await self.server.start()
        # a storage-loaded snapshot must hit the router BEFORE the log
        # re-applies on top of it
        await self.raft.restore_pending()
        self.raft.start()
        self.membership.start()

    async def start_sync(self) -> None:
        if not self.sync_retains or not self.peers:
            return
        for _nid, reply in await Broadcaster(self.live_peers()).join_all_call(
            M.GET_RETAINS, {"filter": "#"}
        ):
            if isinstance(reply, Exception):
                continue
            for topic, mw in reply.get("retains", []):
                self.ctx.retain.set_local(topic, M.msg_from_wire(mw))

    async def stop(self) -> None:
        await self.membership.stop()
        await self.raft.stop()
        await self.server.stop()
        for p in self.peers.values():
            await p.close()
        if self.raft.storage is not None:
            self.raft.storage.close()

    # ------------------------------------------------------- replicated ops
    async def _apply(self, entry: Any) -> None:
        """Apply a committed routing op to the LOCAL router (Store::apply,
        cluster-raft/src/router.rs:269-364)."""
        op = entry.get("op")
        if op == "add":
            self.ctx.router.add(
                entry["tf"], Id(entry["node"], entry["client"]),
                M.opts_from_wire(entry["opts"]),
            )
        elif op == "remove":
            self.ctx.router.remove(entry["tf"], Id(entry["node"], entry["client"]))
        elif op == "remove_many":
            for tf, node, client in entry["items"]:
                self.ctx.router.remove(tf, Id(node, client))
        elif op == "hs_lock":
            # deterministic across nodes: decided purely from entry fields
            # and the replicated lock table, in log order. The TTL staleness
            # check compares proposer wall clocks — deterministic, but like
            # the reference's timeout-based try-lock it assumes roughly
            # NTP-synced cluster clocks (skew > HS_LOCK_TTL could steal a
            # live lock or delay breaking a dead one).
            cur = self.hs_locks.get(entry["client"])
            granted = (
                cur is None
                or entry["ts"] - cur[1] > HS_LOCK_TTL  # stale holder (crashed mid-handshake)
                or cur[0] == entry["node"]  # re-entrant on the same node
            )
            if granted:
                self.hs_locks[entry["client"]] = [entry["node"], entry["ts"], entry["nonce"]]
            if entry["node"] == self.ctx.node_id and entry["nonce"] in self._hs_pending:
                self._hs_results[entry["nonce"]] = granted
        elif op == "hs_unlock":
            # nonce-scoped: releasing one handshake's lock must not release
            # a newer re-entrant lock for the same client on the same node
            cur = self.hs_locks.get(entry["client"])
            if cur is not None and cur[0] == entry["node"] and cur[2] == entry["nonce"]:
                del self.hs_locks[entry["client"]]
        else:
            log.warning("unknown raft entry %r", op)

    # -------------------------------------------------- snapshot callbacks
    def _snapshot_state(self):
        """Full replicated state for raft compaction (router.rs:387-460
        snapshot of relations + client states): every route edge plus the
        handshake-lock table."""
        routes = [
            [tf, sid.node_id, sid.client_id, M.opts_to_wire(opts)]
            for tf, sid, opts in self.ctx.router.dump_routes()
        ]
        return {
            "routes": routes,
            "hs_locks": {cid: list(v) for cid, v in self.hs_locks.items()},
        }

    async def _restore_state(self, snap) -> None:
        """Replace local replicated state with a snapshot (router.rs:462-580
        restore path): clear relations, re-add every route."""
        router = self.ctx.router
        existing = [(tf, sid) for tf, sid, _o in list(router.dump_routes())]
        for tf, sid in existing:
            router.remove(tf, sid)
        for tf, node, client, opts in snap.get("routes", []):
            router.add(tf, Id(node, client), M.opts_from_wire(opts))
        self.hs_locks = {cid: list(v) for cid, v in snap.get("hs_locks", {}).items()}
        log.info(
            "raft node %s restored snapshot: %s routes, %s handshake locks",
            self.ctx.node_id, len(snap.get("routes", [])), len(self.hs_locks),
        )

    # -------------------------------------------------- handshake lock API
    async def handshake_try_lock(self, client_id: str, timeout: float = 5.0) -> Optional[str]:
        """Raft-replicated HandshakeTryLock (shared.rs:71-106): exactly one
        node in the cluster wins the right to handshake ``client_id``.
        Returns the lock nonce on success (pass it to unlock), else None."""
        import time as _time
        import uuid as _uuid

        nonce = _uuid.uuid4().hex
        entry = {
            "op": "hs_lock", "client": client_id, "node": self.ctx.node_id,
            "nonce": nonce, "ts": _time.time(),
        }
        self._hs_pending.add(nonce)
        try:
            if not await self.raft.propose(entry, timeout=timeout):
                # the entry may still commit later; compensate so an
                # unobserved late grant cannot orphan the lock until TTL
                self._hs_results.pop(nonce, None)
                self.handshake_unlock_bg(client_id, nonce)
                return None
            return nonce if self._hs_results.pop(nonce, False) else None
        finally:
            self._hs_pending.discard(nonce)

    def handshake_unlock_bg(self, client_id: str, nonce: str) -> None:
        entry = {
            "op": "hs_unlock", "client": client_id,
            "node": self.ctx.node_id, "nonce": nonce,
        }
        _spawn(self, self.raft.propose(entry, timeout=30.0))

    # -------------------------------------------------------------- inbound
    async def _on_message(self, mtype: str, body: Any, _from_node) -> Any:
        if mtype in (RAFT_VOTE, RAFT_APPEND, RAFT_PROPOSE, RAFT_SNAP):
            # raft heartbeats are too hot for a hook dispatch per message
            return await self.raft.on_message(mtype, body)
        await self.ctx.hooks.fire(HookType.GRPC_MESSAGE_RECEIVED, mtype, _from_node, None)
        if mtype == M.PING:
            return {"pong": True, "leader": self.raft.leader_id, "term": self.raft.term}
        res = await handle_common_message(
            self.ctx, mtype, body, cluster=self, from_node=_from_node
        )
        if res is not _UNHANDLED:
            return res
        raise ValueError(f"unknown cluster message {mtype!r}")
