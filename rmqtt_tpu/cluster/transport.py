"""Asyncio TCP mesh: the cluster's node-to-node RPC transport.

The reference's data plane is handy-grpc/tonic with duplex + fire-and-forget
mailboxes, 2 MB chunking, 4 MB caps, priority queues and a per-client tower
circuit breaker (`rmqtt/src/grpc.rs:107-172, 286-354`). The equivalents here:

- length-prefixed frames (cap enforced) over one TCP connection per peer,
  with lazy connect + exponential backoff reconnect;
- ``notify`` (fire-and-forget) and ``call`` (request/reply with correlation
  ids + timeout);
- a simple circuit breaker per peer (open after N consecutive failures,
  half-open probe after a cooldown) mirroring the reference's breaker config
  (`rmqtt/src/context.rs:585-677`);
- broadcast helpers with the reference's combinator semantics
  (`join_all`/`select_ok`, grpc.rs:718-890).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from rmqtt_tpu.broker.overload import CircuitBreaker
from rmqtt_tpu.cluster import wire
from rmqtt_tpu.utils.failpoints import FAILPOINTS, FailpointError

log = logging.getLogger("rmqtt_tpu.cluster")

#: chaos seam (utils/failpoints.py): fires on outbound publish-forward
#: frames only (FORWARDS / FORWARDS_TO) — an injected error is surfaced as
#: PeerUnavailable and feeds the peer breaker, exactly like a dropped link
_FP_FORWARD = FAILPOINTS.register("cluster.forward")
_FORWARD_TYPES = ("forwards", "forwards_to")  # messages.M constants

#: partition seam: fires on EVERY cluster frame — outbound sends fail fast
#: as PeerUnavailable (feeding the breaker), inbound frames are dropped
#: before dispatch so the sender times out like a blackholed link. Arming
#: ``error`` on one process therefore cuts it off symmetrically: its calls
#: fail, and calls TO it stall to timeout — a network partition the
#: membership detector (cluster/membership.py) must detect and heal from
_FP_RPC = FAILPOINTS.register("cluster.rpc")

MAX_FRAME = wire.MAX_FRAME  # reference caps messages at 4MB (grpc.rs:154)


class PeerUnavailable(ConnectionError):
    pass


class ClusterReplyError(RuntimeError):
    """The peer's handler failed (its error travels as a ``__err`` reply)."""


# length-prefixed framing shared with the intra-node fabric (cluster/wire.py)
async def _read_frame(reader: asyncio.StreamReader) -> Any:
    return await wire.read_frame(reader)


def _frame(obj: Any) -> bytes:
    return wire.frame(obj)


# The per-peer breaker is the SHARED overload-subsystem implementation
# (broker/overload.py CircuitBreaker): closed/open/half-open with
# exponential backoff + jitter. Same contract as the old inline breaker —
# rejected-while-open attempts never re-arm the cooldown (a fast retry loop
# like the raft heartbeat must not be able to hold a peer open forever) —
# plus bounded-backoff probing and snapshot() for /api/v1/overload; the
# import above keeps `transport.CircuitBreaker` a valid name for callers.


class PeerClient:
    """Outbound connection to one peer node (lazy, auto-reconnect)."""

    def __init__(self, node_id: int, host: str, port: int, timeout: float = 5.0) -> None:
        self.node_id = node_id
        self.host = host
        self.port = port
        self.timeout = timeout
        self.breaker = CircuitBreaker()
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._corr = itertools.count(1)
        self._lock = asyncio.Lock()

    @property
    def connected(self) -> bool:
        return self._writer is not None

    async def _ensure(self) -> None:
        if self._writer is not None:
            return
        if not self.breaker.allow():
            raise PeerUnavailable(f"circuit open to node {self.node_id}")
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port), self.timeout
            )
        except (OSError, asyncio.TimeoutError) as e:
            self.breaker.fail()
            raise PeerUnavailable(f"connect to node {self.node_id} failed: {e}") from e
        self._writer = writer
        self._reader_task = asyncio.get_running_loop().create_task(self._read_loop(reader))
        self.breaker.ok()

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                frame = await _read_frame(reader)
                corr = frame.get("corr")
                fut = self._pending.pop(corr, None)
                if fut is not None and not fut.done():
                    fut.set_result(frame.get("reply"))
        except (ConnectionError, asyncio.IncompleteReadError, ValueError):
            pass
        finally:
            self._teardown(ConnectionError("peer connection lost"))

    def _teardown(self, exc: Exception) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(PeerUnavailable(str(exc)))
        self._pending.clear()

    async def _send(self, obj: dict) -> None:
        if _FP_RPC.action is not None:
            try:
                await _FP_RPC.fire_async()
            except FailpointError as e:
                self.breaker.fail()
                raise PeerUnavailable(str(e)) from e
        if _FP_FORWARD.action is not None and obj.get("t") in _FORWARD_TYPES:
            try:
                await _FP_FORWARD.fire_async()
            except FailpointError as e:
                self.breaker.fail()
                raise PeerUnavailable(str(e)) from e
        await self._ensure()
        assert self._writer is not None
        try:
            async with self._lock:
                self._writer.write(_frame(obj))
                await self._writer.drain()
        except (OSError, ConnectionError) as e:
            self.breaker.fail()
            self._teardown(e)
            raise PeerUnavailable(str(e)) from e

    async def notify(self, mtype: str, body: Any = None) -> None:
        """Fire-and-forget (reference fire-and-forget mailbox)."""
        await self._send({"t": mtype, "b": body})

    async def call(self, mtype: str, body: Any = None, timeout: Optional[float] = None) -> Any:
        """Request/reply with correlation id (reference duplex mailbox)."""
        corr = next(self._corr)
        fut = asyncio.get_running_loop().create_future()
        self._pending[corr] = fut
        try:
            await self._send({"t": mtype, "b": body, "corr": corr})
            result = await asyncio.wait_for(fut, timeout or self.timeout)
            self.breaker.ok()
            if isinstance(result, dict) and "__err" in result:
                raise ClusterReplyError(result["__err"])
            return result
        except (asyncio.TimeoutError, PeerUnavailable) as e:
            self.breaker.fail()
            raise PeerUnavailable(f"call {mtype} to node {self.node_id}: {e}") from e
        finally:
            self._pending.pop(corr, None)

    async def close(self) -> None:
        task, self._reader_task = self._reader_task, None
        if task is not None:
            task.cancel()
        self._teardown(ConnectionError("closed"))
        if task is not None:
            # await the cancelled reader so interpreter teardown never sees
            # a half-dead task ("Task was destroyed but it is pending")
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass


# handler(mtype, body, from_node) -> reply value (or None)
Handler = Callable[[str, Any, Optional[int]], Awaitable[Any]]


class ClusterServer:
    """Inbound side: accepts peer connections, dispatches to the handler."""

    def __init__(self, host: str, port: int, handler: Handler) -> None:
        self.host = host
        self.port = port
        self.handler = handler
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: set[asyncio.StreamWriter] = set()

    @property
    def bound_port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # drop live peer connections first: wait_closed (py3.12) waits
            # for the handlers, which would otherwise serve forever
            for w in list(self._conns):
                try:
                    w.close()
                except Exception:
                    pass
            await self._server.wait_closed()

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._conns.add(writer)
        wlock = asyncio.Lock()
        pending: set = set()

        async def dispatch(frame: dict) -> None:
            # handlers run concurrently: a slow handler (e.g. a raft-mode
            # KICK that itself awaits consensus) must not stall heartbeats
            # and votes multiplexed on the same peer connection
            mtype, body, corr = frame.get("t"), frame.get("b"), frame.get("corr")
            try:
                reply = await self.handler(mtype, body, frame.get("node"))
            except ClusterReplyError as e:  # expected, travels to caller
                reply = {"__err": str(e)}
            except Exception as e:  # handler bugs become error replies
                log.exception("cluster handler error for %s", mtype)
                reply = {"__err": str(e)}
            if corr is not None:
                try:
                    async with wlock:
                        writer.write(_frame({"corr": corr, "reply": reply}))
                        await writer.drain()
                except (ConnectionError, OSError):
                    pass

        try:
            while True:
                frame = await _read_frame(reader)
                if _FP_RPC.action is not None:
                    # partition seam, inbound half: drop the frame silently
                    # (the sender sees a stall, not an error — blackhole)
                    try:
                        await _FP_RPC.fire_async()
                    except FailpointError:
                        continue
                task = asyncio.get_running_loop().create_task(dispatch(frame))
                pending.add(task)
                task.add_done_callback(pending.discard)
        except (ConnectionError, asyncio.IncompleteReadError, ValueError):
            pass
        finally:
            self._conns.discard(writer)
            for t in pending:
                t.cancel()
            try:
                writer.close()
            except Exception:
                pass


class Broadcaster:
    """Fan-out combinators over a peer set (grpc.rs MessageBroadcaster)."""

    def __init__(self, peers: List[PeerClient]) -> None:
        self.peers = peers

    async def join_all_notify(self, mtype: str, body: Any = None) -> List[Optional[Exception]]:
        async def one(p: PeerClient):
            try:
                await p.notify(mtype, body)
                return None
            except Exception as e:
                return e

        return list(await asyncio.gather(*(one(p) for p in self.peers)))

    async def join_all_call(
        self, mtype: str, body: Any = None, timeout: Optional[float] = None
    ) -> List[Tuple[int, Any]]:
        """All replies as (node_id, reply-or-exception)."""

        async def one(p: PeerClient):
            try:
                return p.node_id, await p.call(mtype, body, timeout)
            except Exception as e:
                return p.node_id, e

        return list(await asyncio.gather(*(one(p) for p in self.peers)))

    async def select_ok(self, mtype: str, body: Any = None, timeout: Optional[float] = None) -> Any:
        """First successful reply wins (grpc.rs select_ok)."""
        errs = []
        for node_id, reply in await self.join_all_call(mtype, body, timeout):
            if not isinstance(reply, Exception):
                return reply
            errs.append((node_id, reply))
        raise PeerUnavailable(f"no peer answered {mtype}: {errs}")
