"""Compact binary serialization for cluster RPC payloads.

The reference serializes RPC payloads with postcard (compact, schema-less;
`rmqtt/src/grpc.rs:537-545`). This is the equivalent: a small self-describing
binary format for the JSON-ish data model (None/bool/int/float/str/bytes/
list/dict) — no pickle (cluster links shouldn't deserialize arbitrary
objects), no base64 inflation for payload bytes.
"""

from __future__ import annotations

import struct
from typing import Any

#: shared frame cap for length-prefixed links built on this format (the
#: cluster TCP mesh and the intra-node fabric UDS mesh both enforce it;
#: reference caps messages at 4MB, grpc.rs:154)
MAX_FRAME = 8 * 1024 * 1024

_NONE = 0
_TRUE = 1
_FALSE = 2
_INT = 3
_FLOAT = 4
_STR = 5
_BYTES = 6
_LIST = 7
_DICT = 8
_NEGINT = 9


def _write_varint(out: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def dumps(obj: Any) -> bytes:
    out = bytearray()
    _enc(out, obj)
    return bytes(out)


def _enc(out: bytearray, o: Any) -> None:
    if o is None:
        out.append(_NONE)
    elif o is True:
        out.append(_TRUE)
    elif o is False:
        out.append(_FALSE)
    elif isinstance(o, int):
        if o >= 0:
            out.append(_INT)
            _write_varint(out, o)
        else:
            out.append(_NEGINT)
            _write_varint(out, -o)
    elif isinstance(o, float):
        out.append(_FLOAT)
        out += struct.pack(">d", o)
    elif isinstance(o, str):
        b = o.encode("utf-8")
        out.append(_STR)
        _write_varint(out, len(b))
        out += b
    elif isinstance(o, (bytes, bytearray, memoryview)):
        b = bytes(o)
        out.append(_BYTES)
        _write_varint(out, len(b))
        out += b
    elif isinstance(o, (list, tuple)):
        out.append(_LIST)
        _write_varint(out, len(o))
        for item in o:
            _enc(out, item)
    elif isinstance(o, dict):
        out.append(_DICT)
        _write_varint(out, len(o))
        for k, v in o.items():
            _enc(out, k)
            _enc(out, v)
    else:
        raise TypeError(f"unserializable type {type(o).__name__}")


class _Cursor:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes) -> None:
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise ValueError("truncated wire data")
        v = self.buf[self.pos : self.pos + n]
        self.pos += n
        return v

    def varint(self) -> int:
        shift, value = 0, 0
        while True:
            if self.pos >= len(self.buf):
                raise ValueError("truncated varint")
            b = self.buf[self.pos]
            self.pos += 1
            value |= (b & 0x7F) << shift
            if not b & 0x80:
                return value
            shift += 7
            if shift > 70:
                raise ValueError("malformed varint")


def loads(data: bytes) -> Any:
    c = _Cursor(data)
    obj = _dec(c)
    if c.pos != len(data):
        raise ValueError("trailing wire data")
    return obj


def frame(obj: Any, max_frame: int = MAX_FRAME) -> bytes:
    """One length-prefixed frame (4-byte BE length + payload) — the shared
    primitive under every link that speaks this format (cluster transport,
    intra-node fabric)."""
    data = dumps(obj)
    if len(data) > max_frame:
        raise ValueError(f"oversized wire frame: {len(data)}")
    return len(data).to_bytes(4, "big") + data


async def read_frame(reader, max_frame: int = MAX_FRAME) -> Any:
    """Read one length-prefixed frame from an asyncio StreamReader."""
    head = await reader.readexactly(4)
    length = int.from_bytes(head, "big")
    if length > max_frame:
        raise ConnectionError(f"oversized wire frame: {length}")
    return loads(await reader.readexactly(length))


def _dec(c: _Cursor, depth: int = 0) -> Any:
    if depth > 64:
        raise ValueError("wire data too deeply nested")
    tag = c.take(1)[0]
    if tag == _NONE:
        return None
    if tag == _TRUE:
        return True
    if tag == _FALSE:
        return False
    if tag == _INT:
        return c.varint()
    if tag == _NEGINT:
        return -c.varint()
    if tag == _FLOAT:
        return struct.unpack(">d", c.take(8))[0]
    if tag == _STR:
        return c.take(c.varint()).decode("utf-8")
    if tag == _BYTES:
        return c.take(c.varint())
    if tag == _LIST:
        return [_dec(c, depth + 1) for _ in range(c.varint())]
    if tag == _DICT:
        return {_dec(c, depth + 1): _dec(c, depth + 1) for _ in range(c.varint())}
    raise ValueError(f"unknown wire tag {tag}")
