"""Cluster membership: heartbeat failure detector + anti-entropy repair.

The cluster transport (`cluster/transport.py`) already fails *calls* fast
once a peer's circuit breaker opens, but nothing owns the question "is that
node part of the cluster right now?" — so every CONNECT paid a full kick
timeout against a dead peer, a partitioned node missed retain pushes
forever, and a healed partition could leave the same client id alive on two
nodes. This module supplies that missing layer, mirroring the reference's
health surface (`rmqtt/src/node.rs` NodeStatus + the grpc client-status
checks in `grpc.rs:286-354`) with a SWIM-style state machine:

- **Failure detector** (:class:`Membership`): a periodic ``HEARTBEAT``
  call per peer drives ALIVE → SUSPECT → DEAD transitions on *time since
  last contact* (so detection latency is configured, not emergent), with
  the PR4 hysteresis idiom in the other direction — a SUSPECT/DEAD peer
  must answer ``alive_hold`` consecutive heartbeats before it is promoted
  back to ALIVE, so a flapping link can't bounce the fan-out path.
- **Incarnations**: every node stamps its heartbeats with a per-process
  incarnation number; a changed incarnation means the peer restarted
  between two heartbeats, which triggers the same rejoin repair as an
  observed outage (a fast restart must not dodge anti-entropy).
- **Fence clock**: a cluster-synced monotonic epoch counter (piggybacked
  on heartbeats, Lamport-style merge) backing the session fencing epochs
  stamped by ``take_or_create`` — see ``broker/shared.py``.
- **Anti-entropy on rejoin**: when a peer transitions DEAD → ALIVE (or
  silently restarts), exchange content digests (retained store +
  subscription directory) and repair only the deltas: newest-wins retained
  pull/push, fence-resolved duplicate-session kicks, and (raft mode) a
  route-table merge if the raft log alone didn't reconverge.

Everything here is advisory plumbing around the existing data plane: the
detector never closes sockets, and with no peers configured it costs one
idle task.
"""

from __future__ import annotations

import asyncio
import enum
import hashlib
import logging
import time
from typing import Dict, List, Optional, Tuple

from rmqtt_tpu.cluster import messages as M
from rmqtt_tpu.cluster.transport import PeerUnavailable

log = logging.getLogger("rmqtt_tpu.cluster.membership")

#: retained topics per SYNC_RETAIN_PULL / SYNC_RETAIN_PUSH frame — keeps
#: repair frames far under transport.MAX_FRAME even with 1MB payloads
SYNC_CHUNK = 64
#: pagination sizes for the metadata exchanges (summaries / fences /
#: routes): every anti-entropy frame stays bounded no matter how many
#: retained topics, live sessions, or route edges a node holds —
#: transport.MAX_FRAME hard-rejects oversized frames, so an unchunked
#: exchange would make repair permanently impossible exactly at scale
SUMMARY_PAGE = 10_000
SESSIONS_PAGE = 2_000
ROUTES_PAGE = 5_000


class PeerState(enum.IntEnum):
    ALIVE = 0
    SUSPECT = 1
    DEAD = 2


class PeerHealth:
    """Detector state for one peer (all times are ``time.monotonic``)."""

    __slots__ = ("node_id", "state", "last_seen", "since", "fail_streak",
                 "ok_streak", "incarnation", "transitions")

    def __init__(self, node_id: int, now: float) -> None:
        self.node_id = node_id
        self.state = PeerState.ALIVE  # optimistic until proven otherwise
        self.last_seen = now  # last successful contact (or first sight)
        self.since = now  # when the current state was entered
        self.fail_streak = 0
        self.ok_streak = 0
        self.incarnation: Optional[int] = None  # peer's, from its replies
        self.transitions = 0

    def snapshot(self, now: float) -> dict:
        return {
            "node": self.node_id,
            "state": self.state.name,
            "state_value": int(self.state),
            "last_seen_s": round(max(0.0, now - self.last_seen), 3),
            "in_state_s": round(max(0.0, now - self.since), 3),
            "fail_streak": self.fail_streak,
            "incarnation": self.incarnation,
            "transitions": self.transitions,
        }


# --------------------------------------------------------------- digests

def retain_digest(retain) -> dict:
    """Retained-store content digest (RetainStore.digest)."""
    return retain.digest()


def retain_summary(retain) -> Dict[str, list]:
    """Per-topic repair summary (RetainStore.summary)."""
    return retain.summary()


def retain_delta(mine: Dict[str, list], theirs: Dict[str, list]
                 ) -> Tuple[List[str], List[str]]:
    """Newest-wins reconciliation plan: ``(pull, push)`` topic lists.

    A topic goes on ``pull`` when the peer's copy is missing here or newer
    there; on ``push`` when ours is missing there or newer here. Equal
    create_times with different payload hashes tie-break on the hash (any
    deterministic order works — both sides must just pick the SAME side),
    so two nodes that each ran the exchange converge instead of ping-pong.
    Note the scheme is state-based with no tombstones: a topic *removed* on
    one side during a partition is indistinguishable from one it never had,
    so the surviving copy wins (documented in README "Cluster failure
    domains")."""
    pull: List[str] = []
    push: List[str] = []
    for topic, (ct, hh) in theirs.items():
        ours = mine.get(topic)
        if ours is None or (ct, hh) > (ours[0], ours[1]):
            pull.append(topic)
    for topic, (ct, hh) in mine.items():
        rem = theirs.get(topic)
        if rem is None or (ct, hh) > (rem[0], rem[1]):
            push.append(topic)
    return pull, push


def routes_digest(router) -> dict:
    """Digest of the subscription directory (every route edge). Only
    comparable across nodes when the table is replicated (raft mode); in
    broadcast mode each node's directory is local by design and the digest
    is a per-node fingerprint. The match-cache epoch rides along as a cheap
    local version tag (router/base.py epochs)."""
    h = hashlib.sha1()
    n = 0
    for tf, sid, _opts in sorted(
        ((tf, (sid.node_id, sid.client_id), o)
         for tf, sid, o in router.dump_routes()),
        key=lambda r: (r[0], r[1]),
    ):
        h.update(tf.encode())
        h.update(b"\x00")
        h.update(f"{sid[0]}/{sid[1]}".encode())
        h.update(b"\x00")
        n += 1
    ep = getattr(router, "_sub_epochs", None)
    return {"count": n, "digest": h.hexdigest(),
            "epoch": int(getattr(ep, "wild", 0)) if ep is not None else 0}


class Membership:
    """Per-node failure detector + rejoin repair driver.

    One instance per cluster object (broadcast or raft). Reads the peer set
    live from ``cluster.peers`` each round, so peers injected after
    ``start()`` (the in-process test meshes) are picked up without restart.
    """

    def __init__(
        self,
        cluster,
        ctx,
        heartbeat_interval: float = 1.0,
        suspect_timeout: float = 3.0,
        dead_timeout: float = 6.0,
        alive_hold: int = 2,
        anti_entropy: bool = True,
    ) -> None:
        self.cluster = cluster
        self.ctx = ctx
        self.heartbeat_interval = max(0.02, float(heartbeat_interval))
        self.suspect_timeout = max(self.heartbeat_interval,
                                   float(suspect_timeout))
        self.dead_timeout = max(self.suspect_timeout, float(dead_timeout))
        self.alive_hold = max(1, int(alive_hold))
        self.anti_entropy = bool(anti_entropy)
        #: this node's incarnation: new per process start, so peers can
        #: tell "restarted between heartbeats" from "never went away"
        self.incarnation = time.time_ns()
        self.health: Dict[int, PeerHealth] = {}
        self.transitions = 0
        self.repairs_running: set = set()  # node ids with a repair in flight
        self._task: Optional[asyncio.Task] = None
        # anti-entropy outcome counters (also bumped into ctx.metrics)
        self.repairs = 0
        self.retains_pulled = 0
        self.retains_pushed = 0
        self.sessions_fenced = 0
        self.routes_merged = 0

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    # -------------------------------------------------------------- queries
    def _health(self, node_id: int) -> PeerHealth:
        h = self.health.get(node_id)
        if h is None:
            h = self.health[node_id] = PeerHealth(node_id, time.monotonic())
        return h

    def state_of(self, node_id: int) -> PeerState:
        h = self.health.get(node_id)
        return h.state if h is not None else PeerState.ALIVE

    def is_dead(self, node_id: int) -> bool:
        return self.state_of(node_id) == PeerState.DEAD

    def state_counts(self) -> Dict[str, int]:
        out = {"alive": 0, "suspect": 0, "dead": 0}
        for nid in self.cluster.peers:
            out[self.state_of(nid).name.lower()] += 1
        return out

    def snapshot(self) -> dict:
        now = time.monotonic()
        return {
            "incarnation": self.incarnation,
            "heartbeat_interval": self.heartbeat_interval,
            "suspect_timeout": self.suspect_timeout,
            "dead_timeout": self.dead_timeout,
            "transitions": self.transitions,
            "peers": [self._health(nid).snapshot(now)
                      for nid in sorted(self.cluster.peers)],
            "anti_entropy": {
                "enabled": self.anti_entropy,
                "repairs": self.repairs,
                "running": sorted(self.repairs_running),
                "retains_pulled": self.retains_pulled,
                "retains_pushed": self.retains_pushed,
                "sessions_fenced": self.sessions_fenced,
                "routes_merged": self.routes_merged,
            },
        }

    # ------------------------------------------------------------- inbound
    def on_heartbeat(self, body: dict) -> dict:
        """Serve a peer's HEARTBEAT: merge its fence clock and report ours
        (handled via handle_common_message so both modes answer it)."""
        reg = self.ctx.registry
        observe = getattr(reg, "observe_fence", None)
        if observe is not None:
            observe(int(body.get("fence", 0)))
        return {
            "node": self.ctx.node_id,
            "inc": self.incarnation,
            "fence": getattr(reg, "fence_epoch", 0),
        }

    # ------------------------------------------------------------ detector
    async def _loop(self) -> None:
        while True:
            try:
                peers = list(self.cluster.peers.values())
                if peers:
                    await asyncio.gather(*(self._probe(p) for p in peers))
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("membership round failed")
            await asyncio.sleep(self.heartbeat_interval)

    async def _probe(self, peer) -> None:
        from rmqtt_tpu.cluster.transport import ClusterReplyError

        h = self._health(peer.node_id)
        body = {
            "node": self.ctx.node_id,
            "inc": self.incarnation,
            "fence": getattr(self.ctx.registry, "fence_epoch", 0),
        }
        timeout = max(0.2, min(self.heartbeat_interval, 2.0))
        try:
            reply = await peer.call(M.HEARTBEAT, body, timeout=timeout)
        except ClusterReplyError:
            # the peer ANSWERED (it just doesn't speak HEARTBEAT — a
            # rolling-upgrade older node): liveness yes, no inc/fence info
            self._note_success(h, {})
            return
        except Exception:
            self._note_failure(h)
            return
        self._note_success(h, reply if isinstance(reply, dict) else {})

    def _note_failure(self, h: PeerHealth) -> None:
        h.fail_streak += 1
        h.ok_streak = 0
        now = time.monotonic()
        silent = now - h.last_seen
        if h.state == PeerState.ALIVE and silent >= self.suspect_timeout:
            self._transition(h, PeerState.SUSPECT, now)
        if h.state == PeerState.SUSPECT and silent >= self.dead_timeout:
            self._transition(h, PeerState.DEAD, now)

    def _note_success(self, h: PeerHealth, reply: dict) -> None:
        now = time.monotonic()
        h.last_seen = now
        h.fail_streak = 0
        observe = getattr(self.ctx.registry, "observe_fence", None)
        if observe is not None:
            observe(int(reply.get("fence", 0) or 0))
        inc = reply.get("inc")
        restarted = (inc is not None and h.incarnation is not None
                     and inc != h.incarnation)
        if inc is not None:
            h.incarnation = inc
        if h.state != PeerState.ALIVE:
            h.ok_streak += 1
            if h.ok_streak >= self.alive_hold:
                was_dead = h.state == PeerState.DEAD
                self._transition(h, PeerState.ALIVE, now)
                if was_dead or restarted:
                    self._schedule_repair(h.node_id)
        elif restarted:
            # fast restart between heartbeats: the outage was unobserved
            # but its state loss is just as real
            log.info("peer %s restarted (incarnation changed) — repairing",
                     h.node_id)
            self._schedule_repair(h.node_id)

    def _transition(self, h: PeerHealth, state: PeerState, now: float) -> None:
        prev = h.state
        h.state = state
        h.since = now
        h.ok_streak = 0
        h.transitions += 1
        self.transitions += 1
        self.ctx.metrics.inc("cluster.membership.transitions")
        lvl = logging.WARNING if state != PeerState.ALIVE else logging.INFO
        log.log(lvl, "peer %s: %s -> %s (last seen %.2fs ago)",
                h.node_id, prev.name, state.name, now - h.last_seen)

    # --------------------------------------------------------- anti-entropy
    def _schedule_repair(self, node_id: int) -> None:
        if not self.anti_entropy or node_id in self.repairs_running:
            return
        peer = self.cluster.peers.get(node_id)
        if peer is None:
            return
        self.repairs_running.add(node_id)

        async def run():
            try:
                await self.repair_with(peer)
            except PeerUnavailable as e:
                # the repaired peer died (or was killed) mid-exchange — the
                # EXPECTED outcome of racing a crash; the next incarnation
                # change reschedules the repair. One line, no traceback:
                # chaos harnesses treat logged tracebacks as node failures
                log.warning("anti-entropy with node %s interrupted: %s",
                            node_id, e)
            except Exception:
                log.exception("anti-entropy with node %s failed", node_id)
            finally:
                self.repairs_running.discard(node_id)

        self.cluster.spawn(run())

    async def repair_with(self, peer) -> dict:
        """One anti-entropy exchange with a rejoined peer: digests first,
        deltas only where they differ. Returns a stats row (logged + used
        by tests); every counter also lands in ctx.metrics."""
        ctx = self.ctx
        self.repairs += 1
        ctx.metrics.inc("cluster.anti_entropy.runs")
        t0 = time.monotonic()
        stats = {"peer": peer.node_id, "retains_pulled": 0,
                 "retains_pushed": 0, "sessions_fenced": 0,
                 "routes_merged": 0}
        digest = await peer.call(M.SYNC_DIGEST, {"node": ctx.node_id})
        # --- retained store (skipped in topic_only mode: nothing replicated)
        if (getattr(self.cluster, "retain_sync_mode", "full") == "full"
                and digest.get("retain", {}).get("digest")
                != retain_digest(ctx.retain)["digest"]):
            await self._repair_retains(peer, stats)
        # --- duplicate sessions: fence resolution both ways
        await self._repair_sessions(peer, stats)
        # --- subscription directory (raft mode only: replicated table)
        if getattr(self.cluster, "raft", None) is not None:
            await self._repair_routes(peer, digest, stats)
        log.info("anti-entropy with node %s done in %.3fs: %s",
                 peer.node_id, time.monotonic() - t0, stats)
        return stats

    async def _repair_retains(self, peer, stats: dict) -> None:
        ctx = self.ctx
        theirs: Dict[str, list] = {}
        offset = 0
        while True:  # paged summary fetch (SUMMARY_PAGE topics per frame)
            reply = await peer.call(
                M.SYNC_RETAIN_SUMMARY,
                {"offset": offset, "limit": SUMMARY_PAGE})
            theirs.update(reply.get("topics", {}))
            offset = reply.get("next")
            if offset is None:
                break
        pull, push = retain_delta(retain_summary(ctx.retain), theirs)
        for i in range(0, len(pull), SYNC_CHUNK):
            got = await peer.call(M.SYNC_RETAIN_PULL,
                                  {"topics": pull[i:i + SYNC_CHUNK]})
            for topic, mw in got.get("retains", []):
                msg = M.msg_from_wire(mw)
                if not msg.is_expired():
                    ctx.retain.set_local(topic, msg)
                    stats["retains_pulled"] += 1
        for i in range(0, len(push), SYNC_CHUNK):
            items = []
            for topic in push[i:i + SYNC_CHUNK]:
                m = ctx.retain.get(topic)
                if m is not None:
                    items.append([topic, M.msg_to_wire(m)])
            if items:
                await peer.call(M.SYNC_RETAIN_PUSH, {"items": items})
                stats["retains_pushed"] += len(items)
        self.retains_pulled += stats["retains_pulled"]
        self.retains_pushed += stats["retains_pushed"]
        if stats["retains_pulled"]:
            ctx.metrics.inc("cluster.anti_entropy.retains_pulled",
                            stats["retains_pulled"])
        if stats["retains_pushed"]:
            ctx.metrics.inc("cluster.anti_entropy.retains_pushed",
                            stats["retains_pushed"])

    async def _repair_sessions(self, peer, stats: dict) -> None:
        """Resolve duplicate live sessions with the peer: highest
        (epoch, node_id) fence wins; the stale side self-kicks with the
        session-taken-over disconnect. The handler kicks ITS stale copies;
        the reply tells us which of OURS lost."""
        ctx = self.ctx
        rows = [(s.client_id, list(s.fence))
                for s in ctx.registry.sessions() if s.connected]
        for i in range(0, len(rows), SESSIONS_PAGE):
            mine = dict(rows[i:i + SESSIONS_PAGE])
            reply = await peer.call(M.SYNC_SESSIONS,
                                    {"node": ctx.node_id, "sessions": mine})
            for cid, fence in (reply.get("superseded") or {}).items():
                local = ctx.registry.get(cid)
                if (local is not None and local.connected
                        and tuple(fence) > tuple(local.fence)):
                    await fence_kick(ctx, local)
                    stats["sessions_fenced"] += 1
        self.sessions_fenced += stats["sessions_fenced"]

    async def _repair_routes(self, peer, digest: dict, stats: dict) -> None:
        """Raft-mode directory check: the log/snapshot machinery should
        reconverge a rejoiner by itself — give it a couple of heartbeats,
        then verify digests and pull-merge any routes still missing (the
        belt to raft's suspenders; removals stay raft's job)."""
        ctx = self.ctx
        local = routes_digest(ctx.router)
        remote = digest.get("subs", {})
        if remote.get("digest") == local["digest"]:
            return
        await asyncio.sleep(self.heartbeat_interval * 2)
        fresh = await peer.call(M.SYNC_DIGEST, {"node": ctx.node_id})
        remote = fresh.get("subs", {})
        if remote.get("digest") == routes_digest(ctx.router)["digest"]:
            return
        from rmqtt_tpu.router.base import Id
        have = {(tf, sid.node_id, sid.client_id)
                for tf, sid, _o in ctx.router.dump_routes()}
        merged = 0
        offset = 0
        while True:  # paged route pull (ROUTES_PAGE edges per frame)
            reply = await peer.call(M.SYNC_ROUTES,
                                    {"offset": offset, "limit": ROUTES_PAGE})
            for tf, node, client, ow in reply.get("routes", []):
                if (tf, node, client) not in have:
                    ctx.router.add(tf, Id(node, client), M.opts_from_wire(ow))
                    merged += 1
            offset = reply.get("next")
            if offset is None:
                break
        if merged:
            stats["routes_merged"] = merged
            self.routes_merged += merged
            ctx.metrics.inc("cluster.anti_entropy.routes_merged", merged)


async def fence_kick(ctx, session) -> None:
    """Self-kick the stale side of a fence conflict: reason-labeled,
    session-taken-over on v5, terminated with reason ``fence-stale`` so the
    $SYS disconnected event and hooks say WHY the session died. Idempotent
    per session: the caller-side and handler-side repair paths can race on
    the same conflict (both nodes run anti-entropy on heal), and the loser
    must be kicked — and counted — exactly once."""
    if getattr(session, "_fence_kicked", False):
        return
    session._fence_kicked = True
    ctx.metrics.inc("cluster.fence_kicks")
    log.warning("fencing stale session %r (fence %s)",
                session.client_id, session.fence)
    if session.state is not None:
        await session.state.close(kicked=True)
        for _ in range(100):
            if not session.connected:
                break
            await asyncio.sleep(0.01)
    await ctx.registry.terminate(session, "fence-stale")


#: sentinel mirroring broadcast._UNHANDLED without a circular import
_SYNC_UNHANDLED = object()


async def handle_sync_message(ctx, mtype: str, body, cluster=None):
    """Anti-entropy RPC handlers, shared by both cluster modes (wired into
    handle_common_message). Returns ``None``-able replies like the other
    handlers; unknown types fall through to the caller's _UNHANDLED."""
    if mtype == M.HEARTBEAT:
        ms = getattr(cluster, "membership", None) if cluster else None
        if ms is not None:
            return ms.on_heartbeat(body or {})
        return {"node": ctx.node_id, "inc": 0,
                "fence": getattr(ctx.registry, "fence_epoch", 0)}
    if mtype == M.SYNC_DIGEST:
        return {
            "node": ctx.node_id,
            "retain": retain_digest(ctx.retain),
            "subs": routes_digest(ctx.router),
        }
    if mtype == M.SYNC_RETAIN_SUMMARY:
        # paged: sorted-topic order is stable across pages (mutations that
        # land mid-pull are caught by the digest re-check on the next
        # heartbeat round, not by this snapshot)
        body = body or {}
        offset = int(body.get("offset", 0))
        limit = int(body.get("limit", SUMMARY_PAGE))
        full = retain_summary(ctx.retain)
        keys = sorted(full)[offset:offset + limit]
        nxt = offset + limit if offset + limit < len(full) else None
        return {"topics": {t: full[t] for t in keys}, "next": nxt}
    if mtype == M.SYNC_RETAIN_PULL:
        items = []
        for topic in (body or {}).get("topics", []):
            m = ctx.retain.get(topic)
            if m is not None:
                items.append([topic, M.msg_to_wire(m)])
        return {"retains": items}
    if mtype == M.SYNC_RETAIN_PUSH:
        for topic, mw in (body or {}).get("items", []):
            msg = M.msg_from_wire(mw)
            if not msg.is_expired():
                ctx.retain.set_local(topic, msg)
        return {"ok": True}
    if mtype == M.SYNC_SESSIONS:
        # fence resolution, handler side: kick OUR stale copies, report the
        # client ids where OUR fence is higher so the caller kicks its own
        superseded: Dict[str, list] = {}
        ms = getattr(cluster, "membership", None) if cluster else None
        for cid, fence in (body or {}).get("sessions", {}).items():
            local = ctx.registry.get(cid)
            if local is None or not local.connected:
                continue
            if tuple(fence) > tuple(local.fence):
                await fence_kick(ctx, local)
                if ms is not None:
                    ms.sessions_fenced += 1
            else:
                superseded[cid] = list(local.fence)
        return {"superseded": superseded}
    if mtype == M.SYNC_ROUTES:
        body = body or {}
        offset = int(body.get("offset", 0))
        limit = int(body.get("limit", ROUTES_PAGE))
        rows = sorted(
            ((tf, sid.node_id, sid.client_id, M.opts_to_wire(opts))
             for tf, sid, opts in ctx.router.dump_routes()),
            key=lambda r: (r[0], r[1], r[2]),
        )
        nxt = offset + limit if offset + limit < len(rows) else None
        return {"routes": [list(r) for r in rows[offset:offset + limit]],
                "next": nxt}
    return _SYNC_UNHANDLED
