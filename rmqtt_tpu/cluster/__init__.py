"""Multi-node clustering.

The reference offers two cluster modes (SURVEY.md §2.3): raft-replicated
routing (`rmqtt-plugins/rmqtt-cluster-raft`) and scatter-gather broadcast
(`rmqtt-plugins/rmqtt-cluster-broadcast`). The node-to-node data plane is a
message-passing RPC with a 19-variant vocabulary (`rmqtt/src/grpc.rs:506-535`).

Here the control plane is an asyncio TCP mesh with a compact binary wire
format (`cluster.wire`, `cluster.transport`) and the same message taxonomy
(`cluster.messages`); broadcast mode (`cluster.broadcast`) swaps into the
broker through the same seams the reference plugins use (router/registry).
On multi-chip TPU deployments the routing *table* itself is additionally
sharded over the device mesh (`rmqtt_tpu.parallel`) — host RPC for session
ownership, ICI collectives for match aggregation.
"""
