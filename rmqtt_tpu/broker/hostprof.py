"""Host-plane flight recorder: event-loop lag, GC forensics, blocking-call
incidents and process rollups — the devprof symmetric for the HOST runtime.

PR 10 made the *device* plane fully observable (broker/devprof.py: retrace
storms, HBM reconciliation, per-dispatch ring); but every packet still
crosses the *host* plane — one asyncio event loop, a garbage collector
that stops the world, executor thread pools, an fd/socket budget — and
that plane had zero instrumentation even though the telemetry/SLO surfaces
regularly show tail latency no device or routing stage explains. Broker
benchmarking at scale (arxiv 2603.21600) finds host-runtime stalls
dominate p99; this module makes them attributable:

``event-loop lag sampler``
    An asyncio task sleeps a fixed ``tick_s`` and measures the
    scheduled-vs-actual wakeup delta into a PR 2 log2 ``Histogram``
    (mergeable cluster-wide like every latency stage). A tick whose lag
    reaches ``block_ms`` is a *laggy tick*; ``lag_storm_n`` laggy ticks
    inside ``lag_storm_window`` seconds is a **lag storm** (the host
    analogue of devprof's retrace storm): counted, annotated on the
    slow-op ring (``host.lag_storm``) and auto-dumped.

``GC forensics`` (``gc.callbacks``)
    Pause duration histograms per generation, objects collected /
    uncollectable, and — the forensic the flat counters can't give —
    *gc-during-dispatch correlation*: a pause at/over ``gc_slow_ms``
    lands on the slow-op ring (``host.gc_pause``) carrying whether a
    routing dispatch was in flight when the collector stopped the world,
    so "p99 burst at t == gen2 pause" is readable off one timeline.

``blocking-call detector``
    A watchdog daemon thread notices when the sampler task hasn't ticked
    for ``block_ms`` and captures the event-loop thread's live frame
    stack (``sys._current_frames``) into a bounded incident ring — "who
    wedged the loop" becomes answerable in production, not just in a
    debugger. The episode's final duration is recorded when the loop
    resumes; the incident annotates the slow ring (``host.blocked``) and
    auto-dumps.

``process rollups``
    Fixed-interval buckets of loop-lag p50/p99, laggy ticks, GC pauses,
    executor/thread counts, open fds and RSS — time series, not just
    cumulative counters.

Incidents auto-dump (schema ``rmqtt_tpu.hostprof_dump/1``, rate-limited
per reason) on lag storms, blocking-call episodes, SLO BURNING/EXHAUSTED
transitions (broker/slo.py) and overload CRITICAL escalations
(broker/overload.py). Surfaces follow the house pattern: ``/api/v1/host``
(+ cluster ``/host/sum`` via a ``what=host`` DATA query, lag histograms
bucket-merged like latency), ``rmqtt_host_*`` Prometheus families,
``$SYS/brokers/<n>/host/{loop,gc,incidents}``, dashboard "Host plane"
cards, ``stats()`` gauges, ``[observability]`` knobs (``host_profile``,
``block_ms``, ``lag_storm_n``, ``lag_storm_window``).

``enabled=False`` keeps every seam at ONE attribute check — no sampler
task, no gc callback installed, no watchdog thread, no timestamps — while
the surfaces stay shape-stable (zeros). The profiler is process-global
(``HOSTPROF``) like devprof: the loop, the collector and the fd table it
observes are process-global too. ``start()``/``stop()`` are
reference-counted so in-process multi-broker tests share one sampler.
"""

from __future__ import annotations

import asyncio
import gc
import json
import logging
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from rmqtt_tpu.broker.telemetry import Histogram, prom_sanitize

_LOG = logging.getLogger("rmqtt_tpu.hostprof")

DUMP_SCHEMA = "rmqtt_tpu.hostprof_dump/1"

#: GC generations tracked (CPython's three)
_GENS = (0, 1, 2)


def _fd_count() -> int:
    """Open file descriptors (sockets included). /proc is the cheap exact
    source on Linux; elsewhere 0 (the gauge reads "unavailable", never
    raises on the sampler path)."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0


def _executor_stats(loop) -> Dict[str, int]:
    """Default-executor saturation: live worker threads + queued work.
    Reads private ThreadPoolExecutor attributes defensively — a CPython
    layout change degrades to zeros, never breaks the sampler."""
    out = {"threads": 0, "queue": 0, "max_workers": 0}
    ex = getattr(loop, "_default_executor", None)
    if ex is None:
        return out
    try:
        out["threads"] = len(getattr(ex, "_threads", ()) or ())
        out["max_workers"] = int(getattr(ex, "_max_workers", 0) or 0)
        q = getattr(ex, "_work_queue", None)
        if q is not None:
            out["queue"] = q.qsize()
    except Exception:
        pass
    return out


class _Rollup:
    """One fixed-interval host bucket (the time-series element)."""

    __slots__ = ("t", "ticks", "laggy", "hist", "gc_pauses", "gc_pause_ns",
                 "blocked", "fds", "threads", "executor_queue", "rss_mb")

    def __init__(self, t: int) -> None:
        self.t = t
        self.ticks = 0
        self.laggy = 0
        self.hist = Histogram()  # loop-lag ns within this interval
        self.gc_pauses = 0
        self.gc_pause_ns = 0
        self.blocked = 0
        self.fds = 0
        self.threads = 0
        self.executor_queue = 0
        self.rss_mb = 0.0

    def row(self) -> dict:
        return {
            "t": self.t,
            "ticks": self.ticks,
            "laggy": self.laggy,
            "lag_p50_ms": round(self.hist.quantile(0.50) / 1e6, 3),
            "lag_p99_ms": round(self.hist.quantile(0.99) / 1e6, 3),
            "gc_pauses": self.gc_pauses,
            "gc_pause_ms": round(self.gc_pause_ns / 1e6, 3),
            "blocked": self.blocked,
            "fds": self.fds,
            "threads": self.threads,
            "executor_queue": self.executor_queue,
            "rss_mb": self.rss_mb,
        }


class HostProfiler:
    """Process-global host-plane profiler + incident flight recorder."""

    def __init__(
        self,
        enabled: bool = False,
        tick_s: float = 0.05,
        block_ms: float = 150.0,
        lag_storm_n: int = 8,
        lag_storm_window: float = 10.0,
        gc_slow_ms: float = 5.0,
        interval_s: float = 5.0,
        rollup_max: int = 120,
        incident_max: int = 32,
        dump_dir: Optional[str] = None,
    ) -> None:
        self.enabled = enabled
        self.tick_s = max(0.005, tick_s)
        self.block_ms = max(1.0, block_ms)
        self.lag_storm_n = max(2, lag_storm_n)
        self.lag_storm_window = max(0.1, lag_storm_window)
        self.gc_slow_ms = max(0.0, gc_slow_ms)
        self.interval_s = max(0.1, interval_s)
        self.rollup_max = max(2, rollup_max)
        self.incident_max = max(1, incident_max)
        self.dump_dir = dump_dir
        #: telemetry registry whose slow-op ring incidents annotate (wired
        #: by ServerContext); None outside a broker
        self.telemetry = None
        #: callable → in-flight routing batches (wired by ServerContext to
        #: the RoutingService) for the gc-during-dispatch correlation
        self.dispatch_probe: Optional[Callable[[], int]] = None
        self._lock = threading.Lock()
        # lifecycle: reference-counted start/stop (several in-process
        # brokers share the one loop/GC/fd table they'd each observe)
        self._starts = 0
        self._task: Optional[asyncio.Task] = None
        self._watchdog: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._gc_installed = False
        self._loop = None
        self._loop_thread_id: Optional[int] = None
        self._reset_state()

    def _reset_state(self) -> None:
        # loop-lag accounting
        self.lag_hist = Histogram()
        self.ticks = 0
        self.laggy_ticks = 0
        self.max_lag_ms = 0.0
        self.lag_storms = 0
        self.last_storm: Optional[dict] = None
        self._laggy_ts: deque = deque()
        self._last_storm_mono = -1e18
        self._last_tick_mono = 0.0
        # gc accounting
        self._gc_t0: Dict[int, int] = {}
        self.gc_hist: Dict[int, Histogram] = {g: Histogram() for g in _GENS}
        self.gc_pauses: Dict[int, int] = {g: 0 for g in _GENS}
        self.gc_pause_ns: Dict[int, int] = {g: 0 for g in _GENS}
        self.gc_collected: Dict[int, int] = {g: 0 for g in _GENS}
        self.gc_uncollectable: Dict[int, int] = {g: 0 for g in _GENS}
        # blocking-call incidents
        self.blocked_calls = 0
        self.longest_block_ms = 0.0
        self.incidents: deque = deque(maxlen=self.incident_max)
        self._in_block = False
        self._block_incident: Optional[dict] = None
        self._block_start_mono = 0.0
        # rollups
        self._rollups: deque = deque(maxlen=self.rollup_max)
        # dump bookkeeping
        self.dumps_log: deque = deque(maxlen=16)
        self.last_dump: Optional[dict] = None
        self._last_dump_mono: Dict[str, float] = {}

    # ------------------------------------------------------------ lifecycle
    def configure(self, **kw: Any) -> None:
        """Apply [observability] host knobs (ServerContext / tests).
        Counters survive a reconfigure, like devprof."""
        with self._lock:
            for name in ("enabled", "dump_dir", "telemetry", "dispatch_probe"):
                if name in kw:
                    setattr(self, name, kw[name])
            if "tick_s" in kw:
                self.tick_s = max(0.005, float(kw["tick_s"]))
            if "block_ms" in kw:
                self.block_ms = max(1.0, float(kw["block_ms"]))
            if "lag_storm_n" in kw:
                self.lag_storm_n = max(2, int(kw["lag_storm_n"]))
            if "lag_storm_window" in kw:
                self.lag_storm_window = max(0.1, float(kw["lag_storm_window"]))
            if "gc_slow_ms" in kw:
                self.gc_slow_ms = max(0.0, float(kw["gc_slow_ms"]))
            if "interval_s" in kw:
                self.interval_s = max(0.1, float(kw["interval_s"]))
            if "incident_max" in kw and int(kw["incident_max"]) != self.incident_max:
                self.incident_max = max(1, int(kw["incident_max"]))
                self.incidents = deque(self.incidents, maxlen=self.incident_max)
            if ("rollup_max" in kw
                    and max(2, int(kw["rollup_max"])) != self.rollup_max):
                self.rollup_max = max(2, int(kw["rollup_max"]))
                self._rollups = deque(self._rollups,
                                      maxlen=self.rollup_max)

    def reset(self) -> None:
        """Drop every counter/ring (tests; the profiler is process-global,
        so accumulated state would otherwise leak across cases)."""
        with self._lock:
            self._reset_state()

    def start(self) -> None:
        """Arm the sampler task + watchdog + gc callbacks on the RUNNING
        loop. Reference-counted: the first start arms, later starts (a
        second in-process broker) just count; disabled = no-op."""
        if not self.enabled:
            return
        self._starts += 1
        if self._task is not None and not self._task.done():
            return
        loop = asyncio.get_running_loop()
        self._loop = loop
        self._loop_thread_id = threading.get_ident()
        self._last_tick_mono = time.monotonic()
        self._task = loop.create_task(self._sample_loop(), name="hostprof")
        if not self._gc_installed:
            gc.callbacks.append(self._gc_cb)
            self._gc_installed = True
        # each watchdog owns its OWN stop event: a stop() immediately
        # followed by a start() (broker restart in one process) must not
        # clear the set flag before the old thread observes it — that
        # would leak a second concurrent watchdog
        self._stop_evt = threading.Event()
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, args=(self._stop_evt,),
            name="rmqtt-hostprof-watchdog", daemon=True)
        self._watchdog.start()

    async def stop(self) -> None:
        """Release one start; the last release disarms everything."""
        if self._starts > 0:
            self._starts -= 1
        if self._starts > 0:
            return
        self._stop_evt.set()
        if self._gc_installed:
            try:
                gc.callbacks.remove(self._gc_cb)
            except ValueError:
                pass
            self._gc_installed = False
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._watchdog = None
        self._loop = None
        self._loop_thread_id = None

    # ---------------------------------------------------------- loop sampler
    async def _sample_loop(self) -> None:
        loop = asyncio.get_running_loop()
        next_rollup = time.monotonic() + self.interval_s
        while True:
            tick = self.tick_s
            scheduled = loop.time() + tick
            await asyncio.sleep(tick)
            lag_s = max(0.0, loop.time() - scheduled)
            now = time.monotonic()
            self._last_tick_mono = now
            try:
                self.note_lag(int(lag_s * 1e9), now)
                if now >= next_rollup:
                    next_rollup = now + self.interval_s
                    self._proc_rollup(loop)
            except Exception:  # a bookkeeping bug must not kill the sampler
                _LOG.exception("hostprof sample failed")

    def note_lag(self, lag_ns: int, now: Optional[float] = None) -> None:
        """Record one scheduled-vs-actual wakeup delta (test entry point).
        A lag at/over ``block_ms`` is a laggy tick; a burst of them inside
        the storm window is a LAG STORM (counter + slow-ring annotation +
        auto-dump, devprof retrace-storm style)."""
        if now is None:
            now = time.monotonic()
        lag_ms = lag_ns / 1e6
        storm: Optional[dict] = None
        with self._lock:
            self.ticks += 1
            self.lag_hist.record(lag_ns)
            r = self._rollup()
            r.ticks += 1
            r.hist.record(lag_ns)
            if lag_ms > self.max_lag_ms:
                self.max_lag_ms = round(lag_ms, 3)
            if lag_ms >= self.block_ms:
                self.laggy_ticks += 1
                r.laggy += 1
                self._laggy_ts.append(now)
                horizon = now - self.lag_storm_window
                while self._laggy_ts and self._laggy_ts[0] < horizon:
                    self._laggy_ts.popleft()
                if (len(self._laggy_ts) >= self.lag_storm_n
                        and now - self._last_storm_mono
                        >= self.lag_storm_window):
                    self.lag_storms += 1
                    self._last_storm_mono = now
                    storm = self.last_storm = {
                        "ts": round(time.time(), 3),
                        "laggy_in_window": len(self._laggy_ts),
                        "window_s": self.lag_storm_window,
                        "last_lag_ms": round(lag_ms, 3),
                    }
        if storm is not None:
            _LOG.warning(
                "event-loop LAG STORM: %d ticks lagged >= %.0fms in %.1fs "
                "(last %.1fms) — something keeps starving the loop",
                storm["laggy_in_window"], self.block_ms, storm["window_s"],
                storm["last_lag_ms"])
            self._annotate_ring("host.lag_storm", storm)
            self.auto_dump("lag_storm")

    def _rollup(self) -> _Rollup:
        """Current interval bucket (caller holds the lock)."""
        t = int(time.time() // self.interval_s * self.interval_s)
        if not self._rollups or self._rollups[-1].t != t:
            self._rollups.append(_Rollup(t))
        return self._rollups[-1]

    def _proc_rollup(self, loop) -> None:
        """Stamp the process gauges onto the current interval bucket."""
        from rmqtt_tpu.utils.sysmon import rss_mb

        ex = _executor_stats(loop)
        fds = _fd_count()
        rss = rss_mb()
        with self._lock:
            r = self._rollup()
            r.fds = fds
            r.threads = threading.active_count()
            r.executor_queue = ex["queue"]
            r.rss_mb = rss

    # ------------------------------------------------------------- GC seam
    def _gc_cb(self, phase: str, info: dict) -> None:
        """gc.callbacks hook: pause duration per generation + collected
        totals; slow pauses land on the slow-op ring with the in-dispatch
        correlation. Runs on whichever thread triggered collection."""
        gen = info.get("generation", 2)
        if phase == "start":
            self._gc_t0[gen] = time.perf_counter_ns()
            return
        t0 = self._gc_t0.pop(gen, None)
        if t0 is None:
            return
        dur_ns = time.perf_counter_ns() - t0
        collected = int(info.get("collected", 0) or 0)
        uncollectable = int(info.get("uncollectable", 0) or 0)
        with self._lock:
            self.gc_pauses[gen] = self.gc_pauses.get(gen, 0) + 1
            self.gc_pause_ns[gen] = self.gc_pause_ns.get(gen, 0) + dur_ns
            self.gc_collected[gen] = self.gc_collected.get(gen, 0) + collected
            self.gc_uncollectable[gen] = (
                self.gc_uncollectable.get(gen, 0) + uncollectable)
            h = self.gc_hist.get(gen)
            if h is None:
                h = self.gc_hist[gen] = Histogram()
            h.record(dur_ns)
            r = self._rollup()
            r.gc_pauses += 1
            r.gc_pause_ns += dur_ns
        if self.gc_slow_ms and dur_ns >= self.gc_slow_ms * 1e6:
            in_dispatch = 0
            probe = self.dispatch_probe
            if probe is not None:
                try:
                    in_dispatch = int(probe() or 0)
                except Exception:
                    pass
            self._annotate_ring("host.gc_pause", {
                "generation": gen,
                "pause_ms": round(dur_ns / 1e6, 3),
                "collected": collected,
                "uncollectable": uncollectable,
                # the forensic: was the collector stopping the world while
                # routing batches were in flight?
                "in_dispatch": in_dispatch,
            })

    # ------------------------------------------------- blocking-call watchdog
    def _watchdog_loop(self, stop_evt: threading.Event) -> None:
        """Daemon thread: when the sampler task misses its tick for
        ``block_ms``, capture the loop thread's live stack ONCE per
        episode; finalize (duration + slow-ring + auto-dump) when the loop
        resumes. Stack capture happens mid-block by construction — that is
        the entire point of a thread-side watchdog."""
        while not stop_evt.wait(max(0.01, self.block_ms / 1e3 / 4)):
            task = self._task
            if (not self.enabled or task is None or task.done()
                    or self._loop_thread_id is None):
                continue
            gap_s = time.monotonic() - self._last_tick_mono
            blocked = gap_s * 1e3 >= self.block_ms + self.tick_s * 1e3
            if blocked and not self._in_block:
                self._in_block = True
                self._begin_incident(gap_s)
            elif not blocked and self._in_block:
                self._in_block = False
                self._end_incident()

    def _capture_loop_stack(self, limit: int = 24) -> List[str]:
        frame = sys._current_frames().get(self._loop_thread_id)
        if frame is None:
            return []
        return [line.rstrip("\n")
                for line in traceback.format_stack(frame, limit=limit)]

    def _begin_incident(self, gap_s: float) -> None:
        stack = self._capture_loop_stack()
        incident = {
            "kind": "blocking_call",
            "ts": round(time.time(), 3),
            "blocked_ms": round(gap_s * 1e3, 1),  # still running; updated
            "ongoing": True,
            "stack": stack,
        }
        with self._lock:
            self.blocked_calls += 1
            self._block_incident = incident
            # the episode started at the last tick the sampler made, not
            # when the watchdog happened to notice it
            self._block_start_mono = time.monotonic() - gap_s
            self.incidents.append(incident)
            self._rollup().blocked += 1
        _LOG.warning(
            "event loop BLOCKED for %.0fms and counting — culprit stack:\n%s",
            gap_s * 1e3, "\n".join(stack[-6:]))

    def _end_incident(self) -> None:
        with self._lock:
            incident = self._block_incident
            self._block_incident = None
            if incident is None:
                return
            # _last_tick_mono is the sampler's RESUME stamp: the episode
            # ran from the stamp before the block to roughly there
            total_ms = round(
                (self._last_tick_mono - self._block_start_mono) * 1e3, 1)
            incident["ongoing"] = False
            incident["blocked_ms"] = max(incident["blocked_ms"], total_ms)
            if incident["blocked_ms"] > self.longest_block_ms:
                self.longest_block_ms = incident["blocked_ms"]
        self._annotate_ring("host.blocked", {
            "blocked_ms": incident["blocked_ms"],
            "stack_tail": incident["stack"][-3:],
        })
        self.auto_dump("blocking_call")

    # ------------------------------------------------------------ annotations
    def _annotate_ring(self, op: str, detail: dict) -> None:
        """Slow-op ring annotation — host incidents land on the same
        timeline as overload/slo/failover transitions and slow publishes,
        which is what makes cross-plane correlation a single read."""
        tele = self.telemetry
        if tele is not None and getattr(tele, "enabled", False):
            tele.slow_ops.append({
                "op": op, "ms": float(detail.get("blocked_ms")
                                      or detail.get("pause_ms") or 0.0),
                "ts": round(time.time(), 3),
                "detail": detail,
            })

    def rollup_summary(self, since: Optional[float] = None,
                       n: Optional[int] = None) -> dict:
        """Rollup CONSUMER API (devprof's sibling, the history collector's
        signal source): merge the interval buckets at/after ``since`` (or
        the newest ``n``; the newest 6 by default) into one window summary
        — ticks, laggy ticks, lag p50/p99, GC pauses/pause-ms, blocking
        incidents. Cheaper than ``snapshot()`` (no /proc scan, no incident
        tables) so a collector can poll it every few seconds."""
        with self._lock:
            rolls = list(self._rollups)
        if since is not None:
            rolls = [r for r in rolls if r.t + self.interval_s > since]
        elif n is not None:
            rolls = rolls[-max(0, n):]
        else:
            rolls = rolls[-6:]
        hist = Histogram()
        out = {"intervals": len(rolls), "ticks": 0, "laggy": 0,
               "gc_pauses": 0, "gc_pause_ns": 0, "blocked": 0}
        for r in rolls:
            out["ticks"] += r.ticks
            out["laggy"] += r.laggy
            out["gc_pauses"] += r.gc_pauses
            out["gc_pause_ns"] += r.gc_pause_ns
            out["blocked"] += r.blocked
            hist.merge(r.hist)
        out["gc_pause_ms"] = round(out.pop("gc_pause_ns") / 1e6, 3)
        out["lag_p50_ms"] = round(hist.quantile(0.50) / 1e6, 3)
        out["lag_p99_ms"] = round(hist.quantile(0.99) / 1e6, 3)
        return out

    # ------------------------------------------------------------- surfaces
    def snapshot(self) -> dict:
        """The `/api/v1/host` body: shape-stable whether enabled or not."""
        with self._lock:
            gens = {
                str(g): {
                    "pauses": self.gc_pauses.get(g, 0),
                    "pause_ms_total": round(self.gc_pause_ns.get(g, 0) / 1e6, 3),
                    "collected": self.gc_collected.get(g, 0),
                    "uncollectable": self.gc_uncollectable.get(g, 0),
                    "p50_ms": round(self.gc_hist[g].quantile(0.50) / 1e6, 3),
                    "p99_ms": round(self.gc_hist[g].quantile(0.99) / 1e6, 3),
                }
                for g in _GENS
            }
            recent = Histogram()
            for r in list(self._rollups)[-6:]:
                recent.merge(r.hist)
            snap = {
                "enabled": self.enabled,
                "loop": {
                    "ticks": self.ticks,
                    "tick_s": self.tick_s,
                    "laggy_ticks": self.laggy_ticks,
                    "max_lag_ms": self.max_lag_ms,
                    "lag_p50_ms": round(recent.quantile(0.50) / 1e6, 3),
                    "lag_p99_ms": round(recent.quantile(0.99) / 1e6, 3),
                    "storms": self.lag_storms,
                    "last_storm": self.last_storm,
                    "storm_n": self.lag_storm_n,
                    "storm_window_s": self.lag_storm_window,
                    "lag_hist": self.lag_hist.to_json(),
                },
                "gc": {
                    "generations": gens,
                    "pauses": sum(self.gc_pauses.values()),
                    "pause_ms_total": round(
                        sum(self.gc_pause_ns.values()) / 1e6, 3),
                    "thresholds": list(gc.get_threshold()),
                    "slow_ms": self.gc_slow_ms,
                },
                "block": {
                    "block_ms": self.block_ms,
                    "blocked_calls": self.blocked_calls,
                    "longest_block_ms": self.longest_block_ms,
                    "incidents": list(self.incidents),
                },
                "rollups": [r.row() for r in self._rollups],
                "dumps": list(self.dumps_log),
            }
        # process gauges read live (cold path; one /proc scan per snapshot)
        from rmqtt_tpu.utils.sysmon import rss_mb

        loop = self._loop
        snap["proc"] = {
            "fds": _fd_count(),
            "threads": threading.active_count(),
            "rss_mb": rss_mb(),
            **({"executor": _executor_stats(loop)} if loop is not None
               else {"executor": {"threads": 0, "queue": 0, "max_workers": 0}}),
        }
        return snap

    @staticmethod
    def merge_snapshots(base: dict, others: List[dict]) -> dict:
        """Cluster merge (`/api/v1/host/sum`): counters sum, the lag
        histograms BUCKET-MERGE like the latency surface (the whole point
        of fixed log2 buckets), max-lag merges by max, per-node incident
        detail stays per-node (fetch each node's `/api/v1/host`)."""
        others = list(others)
        lag = Histogram()
        out = {
            "nodes": 1 + len(others),
            "enabled": bool(base.get("enabled", False)),
            "loop": {"ticks": 0, "laggy_ticks": 0, "storms": 0,
                     "max_lag_ms": 0.0},
            "gc": {"pauses": 0, "pause_ms_total": 0.0},
            "block": {"blocked_calls": 0, "longest_block_ms": 0.0},
            "proc": {"fds": 0, "threads": 0, "rss_mb": 0.0},
        }
        for snap in [base, *others]:
            lp = snap.get("loop") or {}
            for k in ("ticks", "laggy_ticks", "storms"):
                out["loop"][k] += lp.get(k, 0)
            out["loop"]["max_lag_ms"] = max(out["loop"]["max_lag_ms"],
                                            lp.get("max_lag_ms", 0.0))
            if lp.get("lag_hist"):
                lag.merge(Histogram.from_json(lp["lag_hist"]))
            g = snap.get("gc") or {}
            out["gc"]["pauses"] += g.get("pauses", 0)
            out["gc"]["pause_ms_total"] = round(
                out["gc"]["pause_ms_total"] + g.get("pause_ms_total", 0.0), 3)
            blk = snap.get("block") or {}
            out["block"]["blocked_calls"] += blk.get("blocked_calls", 0)
            out["block"]["longest_block_ms"] = max(
                out["block"]["longest_block_ms"],
                blk.get("longest_block_ms", 0.0))
            p = snap.get("proc") or {}
            for k in ("fds", "threads"):
                out["proc"][k] += p.get(k, 0)
            out["proc"]["rss_mb"] = round(
                out["proc"]["rss_mb"] + p.get("rss_mb", 0.0), 3)
        out["loop"]["lag_p50_ms"] = round(lag.quantile(0.50) / 1e6, 3)
        out["loop"]["lag_p99_ms"] = round(lag.quantile(0.99) / 1e6, 3)
        out["loop"]["lag_hist"] = lag.to_json()
        return out

    def prometheus_lines(self, labels: str) -> List[str]:
        """`rmqtt_host_*` exposition families (grammar-pinned by the full
        scrape test like every other exporter)."""
        with self._lock:
            lag = Histogram().merge(self.lag_hist)
            counters = [
                ("rmqtt_host_loop_ticks_total", "counter", self.ticks),
                ("rmqtt_host_loop_laggy_ticks_total", "counter",
                 self.laggy_ticks),
                ("rmqtt_host_loop_lag_storms_total", "counter",
                 self.lag_storms),
                ("rmqtt_host_blocked_calls_total", "counter",
                 self.blocked_calls),
            ]
            gc_rows = [(g, self.gc_pauses.get(g, 0),
                        self.gc_pause_ns.get(g, 0),
                        self.gc_collected.get(g, 0)) for g in _GENS]
        out: List[str] = []
        for name, typ, val in counters:
            out.append(f"# TYPE {name} {typ}")
            out.append(f"{name}{{{labels}}} {val}")
        # loop-lag histogram family, exported in seconds like the latency
        # stages (inclusive `le` from exclusive log2 uppers, same rule)
        metric = "rmqtt_host_loop_lag_seconds"
        out.append(f"# TYPE {metric} histogram")
        acc = 0
        for i, c in enumerate(lag.counts):
            acc += c
            le = format((Histogram.bucket_upper(i) - 1) * 1e-9, "g")
            out.append(f'{metric}_bucket{{{labels},le="{le}"}} {acc}')
        out.append(f'{metric}_bucket{{{labels},le="+Inf"}} {lag.count}')
        out.append(f"{metric}_sum{{{labels}}} {format(lag.sum * 1e-9, 'g')}")
        out.append(f"{metric}_count{{{labels}}} {lag.count}")
        out.append("# TYPE rmqtt_host_gc_pauses_total counter")
        for g, pauses, _ns, _col in gc_rows:
            out.append(
                f'rmqtt_host_gc_pauses_total{{{labels},generation="{g}"}} '
                f"{pauses}")
        out.append("# TYPE rmqtt_host_gc_pause_seconds_total counter")
        for g, _p, ns, _col in gc_rows:
            out.append(
                f'rmqtt_host_gc_pause_seconds_total{{{labels},'
                f'generation="{g}"}} {format(ns * 1e-9, "g")}')
        out.append("# TYPE rmqtt_host_gc_collected_total counter")
        for g, _p, _ns, col in gc_rows:
            out.append(
                f'rmqtt_host_gc_collected_total{{{labels},generation="{g}"}} '
                f"{col}")
        ex = (_executor_stats(self._loop) if self._loop is not None
              else {"threads": 0, "queue": 0, "max_workers": 0})
        # NOTE: fd/thread gauges export via the generic Stats loop
        # (rmqtt_host_open_fds / rmqtt_host_threads) — re-exporting them
        # here would emit a duplicate TYPE (invalid exposition, the bug
        # class the full-scrape test pins); only the executor gauges,
        # which have no Stats twin, belong to this family
        gauges = [
            ("rmqtt_host_executor_threads", ex["threads"]),
            ("rmqtt_host_executor_queue", ex["queue"]),
        ]
        for name, val in gauges:
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name}{{{labels}}} {val}")
        return out

    # ------------------------------------------------------------- dumping
    def dump(self, reason: str) -> dict:
        """Freeze the host plane into one artifact dict. The telemetry
        slow-op ring tail rides along — incidents correlate against slow
        publishes and slo/overload transitions in ONE artifact."""
        d = {
            "schema": DUMP_SCHEMA,
            "reason": reason,
            "ts": round(time.time(), 3),
            "snapshot": self.snapshot(),
        }
        tele = self.telemetry
        if tele is not None and getattr(tele, "enabled", False):
            d["slow_ops"] = list(tele.slow_ops)[-64:]
        return d

    def dump_to(self, path: str, reason: str) -> Optional[str]:
        """Write a dump artifact; → the path, or None on failure (a dump
        must never take the caller down with it)."""
        try:
            d = self.dump(reason)
            dirname = os.path.dirname(path)
            if dirname:
                os.makedirs(dirname, exist_ok=True)
            with open(path, "w") as f:
                json.dump(d, f, indent=1)
            self.last_dump = d
            self.dumps_log.append({"reason": reason, "ts": d["ts"],
                                   "path": path})
            _LOG.warning("host flight recorder dumped (%s) -> %s",
                         reason, path)
            return path
        except Exception as e:  # pragma: no cover - disk-full etc.
            _LOG.warning("host flight-recorder dump failed (%s): %s",
                         reason, e)
            return None

    def auto_dump(self, reason: str) -> None:
        """Event-triggered dump (lag storm / blocking episode / SLO
        BURNING-EXHAUSTED / overload CRITICAL). Rate-limited per reason
        and OFFLOADED to a daemon thread — the triggers fire on the event
        loop (slo/overload transitions) or the watchdog; serializing the
        rings + a disk write there would stall the broker at its worst
        moment. With no ``dump_dir`` the artifact stays in memory."""
        if not self.enabled:
            return
        now = time.monotonic()
        with self._lock:
            if now - self._last_dump_mono.get(reason, -1e18) < 30.0:
                return
            self._last_dump_mono[reason] = now
        try:
            threading.Thread(target=self._auto_dump_now, args=(reason,),
                             name="rmqtt-hostprof-dump", daemon=True).start()
        except Exception as e:  # pragma: no cover - thread exhaustion
            _LOG.warning("host flight-recorder auto-dump thread failed "
                         "(%s): %s", reason, e)

    def _auto_dump_now(self, reason: str) -> None:
        if self.dump_dir:
            path = os.path.join(
                self.dump_dir,
                f"hostprof_{prom_sanitize(reason)}_{int(time.time())}.json")
            self.dump_to(path, reason)
            return
        self.last_dump = self.dump(reason)
        self.dumps_log.append({"reason": reason,
                               "ts": self.last_dump["ts"], "path": None})
        _LOG.warning("host flight recorder dumped in memory (%s); set "
                     "RMQTT_HOSTPROF_DIR for an on-disk artifact", reason)


#: process-global instance — seams guard on ``HOSTPROF.enabled`` (one
#: attribute check when off); the broker configures it from the
#: [observability] section
HOSTPROF = HostProfiler(
    enabled=os.environ.get("RMQTT_HOST_PROFILE", "") == "1",
    dump_dir=os.environ.get("RMQTT_HOSTPROF_DIR") or None,
)
