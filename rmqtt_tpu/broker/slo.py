"""Live SLO engine: declarative objectives → error budgets → burn rates.

PR2's telemetry answers "how long did ops take", PR4's reason-labeled drop
counters answer "what was lost and why". This layer turns both into the
operator-facing question: **are we inside our service-level objectives,
and how fast are we spending the error budget?**

``Objective``
    One declarative target, parsed from the ``[slo]`` config section (or
    the built-in defaults). Two kinds:

    - ``latency`` — "at least ``target`` of ``stage`` samples complete
      under ``threshold_ms``", evaluated over the telemetry layer's log2
      histograms. Because buckets are powers of two, the threshold is
      quantized UP to the containing bucket's exclusive upper bound
      (``effective_threshold_ms`` in every surface says what was actually
      enforced); good = samples in buckets at or below that bound.
    - ``availability`` — "at least ``target`` of messages are delivered,
      not dropped", over ``messages.delivered`` vs the reason-labeled
      ``messages.dropped.*`` counters. ``exclude_reasons`` removes drops
      that are *policy*, not failure (e.g. ``shed_qos0`` under an overload
      profile that deliberately sheds).

``SloEngine``
    Samples each objective's cumulative (good, total) pair on a fixed
    interval into a bounded ring, then evaluates **multi-window burn
    rates** the Google-SRE way: the error budget is ``1 - target``; the
    burn rate over a window is ``bad_fraction / budget`` (1.0 = spending
    exactly the sustainable rate, N = exhausting N windows' budget per
    window). Two windows per objective — ``fast`` (default 5 m, catches
    cliffs) and ``slow`` (default 1 h, catches slow leaks) — drive a
    per-objective state machine::

        OK → BURNING    fast burn ≥ burn_alert (budget draining fast)
        *  → EXHAUSTED  slow burn ≥ 1.0        (window's whole budget gone)

    Budget-exhaustion transitions land on the same timelines operators
    already watch: a slow-ring annotation (``slo.state``), a
    ``SERVER_SLO`` hook fire (SERVER_OVERLOAD-style), and the
    ``slo.transitions`` counter.

Like the histograms underneath, per-objective samples are **mergeable by
addition** — ``merge_snapshots`` sums (good, total) pairs per objective
name across nodes for cluster-wide ``/api/v1/slo/sum``.

With ``[slo] enable = false`` nothing is sampled and no task starts; the
snapshot surface stays shape-stable (objectives listed with zero data).
"""

from __future__ import annotations

import asyncio
import enum
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from rmqtt_tpu.broker.telemetry import Histogram, prom_sanitize

log = logging.getLogger("rmqtt_tpu.slo")

_BUDGET_FLOOR = 1e-9  # target=1.0 ("no errors ever") still divides cleanly


class SloState(enum.IntEnum):
    OK = 0
    BURNING = 1
    EXHAUSTED = 2


#: objectives used when the [slo] section declares none: a broker-wide
#: latency target on the publish pipeline, a handshake target, and a
#: delivery-availability target over the reason-labeled drop counters
DEFAULT_OBJECTIVES: Tuple[Dict[str, Any], ...] = (
    {"name": "publish-e2e-p99", "kind": "latency", "stage": "publish.e2e",
     "threshold_ms": 100.0, "target": 0.99},
    {"name": "connect-p99", "kind": "latency", "stage": "connect.handshake",
     "threshold_ms": 500.0, "target": 0.99},
    {"name": "delivery", "kind": "availability", "target": 0.999},
)


@dataclass
class Objective:
    """One parsed SLO row; ``from_spec`` validates the declarative dict."""

    name: str
    kind: str  # "latency" | "availability"
    target: float
    stage: str = "publish.e2e"  # latency only
    threshold_ms: float = 100.0  # latency only
    exclude_reasons: Tuple[str, ...] = ()  # availability only
    # derived (latency): the log2 bucket the threshold falls in, and the
    # bucket-quantized bound actually enforced
    _lim_bucket: int = field(default=0, repr=False)
    effective_threshold_ms: float = field(default=0.0, repr=False)

    @classmethod
    def from_spec(cls, spec: Dict[str, Any]) -> "Objective":
        known = {"name", "kind", "target", "stage", "threshold_ms",
                 "exclude_reasons"}
        unknown = set(spec) - known
        if unknown:
            raise ValueError(f"unknown slo objective keys: {sorted(unknown)}")
        kind = str(spec.get("kind", "latency"))
        if kind not in ("latency", "availability"):
            raise ValueError(
                f"slo objective kind must be latency|availability, got {kind!r}")
        target = float(spec.get("target", 0.99))
        if not 0.0 < target <= 1.0:
            raise ValueError(f"slo target must be in (0, 1], got {target}")
        name = str(spec.get("name") or "").strip()
        # names land in $SYS topic levels and Prometheus label values:
        # constrain to a safe charset instead of escaping per surface
        if not name or not all(
            c.isalnum() or c in "._-" for c in name
        ):
            raise ValueError(
                f"slo objective name must be non-empty [A-Za-z0-9._-], "
                f"got {name!r}")
        obj = cls(
            name=name,
            kind=kind,
            target=target,
            stage=str(spec.get("stage", "publish.e2e")),
            threshold_ms=float(spec.get("threshold_ms", 100.0)),
            exclude_reasons=tuple(
                str(r) for r in spec.get("exclude_reasons", ())),
        )
        if kind == "latency":
            if obj.threshold_ms <= 0:
                raise ValueError(
                    f"slo threshold_ms must be > 0, got {obj.threshold_ms}")
            obj._lim_bucket = Histogram.bucket_index(
                int(obj.threshold_ms * 1e6))
            obj.effective_threshold_ms = round(
                Histogram.bucket_upper(obj._lim_bucket) / 1e6, 6)
        return obj

    # ------------------------------------------------------------- sampling
    def cumulative(self, ctx) -> Tuple[int, int]:
        """This objective's (good, total) event counts since process start.
        Monotonic by construction — windows are deltas of these."""
        if self.kind == "latency":
            tele = ctx.telemetry
            tele.flush()
            counts = tele.hist(self.stage).counts
            total = sum(counts)
            good = sum(counts[: self._lim_bucket + 1])
            return good, total
        m = ctx.metrics
        delivered = m.get("messages.delivered")
        bad = m.get("messages.dropped")
        for reason in self.exclude_reasons:
            bad -= m.get("messages.dropped." + reason)
        bad = max(0, bad)
        return delivered, delivered + bad


def _burn(good: int, total: int, target: float) -> Tuple[float, float]:
    """(bad_fraction, burn_rate) for one window's delta. Zero-event windows
    are vacuously healthy (no evidence of burn, no evidence of health)."""
    if total <= 0:
        return 0.0, 0.0
    bad_frac = (total - good) / total
    return bad_frac, bad_frac / max(1.0 - target, _BUDGET_FLOOR)


class SloEngine:
    """Per-node SLO evaluator: the sampling loop + every surface's body.

    Constructed unconditionally on ``ServerContext`` (like the overload
    controller) so `/api/v1/slo`, the gauges and `$SYS` are shape-stable
    whether or not the engine is enabled."""

    def __init__(self, ctx, cfg,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.ctx = ctx
        self.enabled = bool(cfg.slo_enable)
        self.sample_interval = max(0.05, float(cfg.slo_sample_interval))
        self.fast_window_s = max(self.sample_interval,
                                 float(cfg.slo_fast_window_s))
        self.slow_window_s = max(self.fast_window_s,
                                 float(cfg.slo_slow_window_s))
        self.burn_alert = max(1.0, float(cfg.slo_burn_alert))
        specs = list(cfg.slo_objectives) or list(DEFAULT_OBJECTIVES)
        self.objectives: List[Objective] = [
            Objective.from_spec(s) for s in specs]
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate slo objective names: {names}")
        # a typo'd stage name would be silently vacuously healthy forever
        # (hist() auto-creates an empty histogram); plugins may register
        # custom stages after construction, so this warns instead of
        # raising — loudly, at startup, where operators read logs
        known = set(getattr(getattr(ctx, "telemetry", None), "_h", ()) or ())
        for obj in self.objectives:
            if obj.kind == "latency" and known and obj.stage not in known:
                log.warning(
                    "slo objective %r targets unknown telemetry stage %r "
                    "(known: %s) — it will report vacuously healthy until "
                    "that stage records", obj.name, obj.stage,
                    sorted(known))
        self._clock = clock
        # ring of (t, ((good, total), ...)) — one slot per objective per
        # sample, bounded to one slow window (+1 baseline slot so a full
        # window always has a sample at-or-before its left edge)
        slots = int(self.slow_window_s / self.sample_interval) + 2
        self._ring: deque = deque(maxlen=max(4, slots))
        self._states: List[SloState] = [SloState.OK] * len(self.objectives)
        self.transitions = 0
        self._task: Optional[asyncio.Task] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self.enabled and self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.sample_interval)
            try:
                self.tick()
            except Exception:  # an evaluation bug must not kill the engine
                log.exception("slo sample failed")

    # ------------------------------------------------------------- sampling
    def tick(self) -> None:
        """One sample + state evaluation (test entry point)."""
        t = self._clock()
        self._ring.append(
            (t, tuple(o.cumulative(self.ctx) for o in self.objectives)))
        for i, obj in enumerate(self.objectives):
            new = self._evaluate(i, t)
            old = self._states[i]
            if new != old:
                self._states[i] = new
                self._transition(obj, i, old, new)

    def _window_delta(self, i: int, window_s: float,
                      now: float) -> Tuple[int, int, float]:
        """(good, total, coverage) for objective ``i`` over the trailing
        window: newest sample at-or-before the window's left edge is the
        baseline (falling back to the oldest sample when history is
        shorter than the window). ``coverage`` is the fraction of the
        window the delta actually spans — burn rates scale by it, so a
        3-minute-old broker can't claim an hour's budget is spent (the
        un-covered remainder of the window counts as clean)."""
        if not self._ring:
            return 0, 0, 0.0
        cutoff = now - window_s
        base = self._ring[0]
        for entry in self._ring:
            if entry[0] <= cutoff:
                base = entry
            else:
                break
        latest = self._ring[-1]
        g0, t0 = base[1][i]
        g1, t1 = latest[1][i]
        coverage = min(1.0, max(0.0, (latest[0] - base[0]) / window_s))
        return max(0, g1 - g0), max(0, t1 - t0), coverage

    def _window_burn(self, i: int, window_s: float,
                     now: float) -> Tuple[int, int, float, float, float]:
        """(good, total, coverage, bad_fraction, coverage-scaled burn)."""
        good, total, coverage = self._window_delta(i, window_s, now)
        frac, burn = _burn(good, total, self.objectives[i].target)
        return good, total, coverage, frac, burn * coverage

    def _evaluate(self, i: int, now: float) -> SloState:
        *_rest, fast_burn = self._window_burn(i, self.fast_window_s, now)
        *_rest, slow_burn = self._window_burn(i, self.slow_window_s, now)
        if slow_burn >= 1.0:
            return SloState.EXHAUSTED
        if fast_burn >= self.burn_alert:
            return SloState.BURNING
        return SloState.OK

    def _transition(self, obj: Objective, i: int, old: SloState,
                    new: SloState) -> None:
        ctx = self.ctx
        self.transitions += 1
        ctx.metrics.inc("slo.transitions")
        log.warning("slo %s: %s -> %s (target=%s)",
                    obj.name, old.name, new.name, obj.target)
        # slow-ring annotation: budget exhaustion lands on the timeline
        # operators read for stalls and overload transitions
        tele = getattr(ctx, "telemetry", None)
        if tele is not None and tele.enabled:
            tele.slow_ops.append({
                "op": "slo.state", "ms": 0.0, "ts": round(time.time(), 3),
                "detail": {"objective": obj.name, "from": old.name,
                           "to": new.name, "target": obj.target},
            })
        # entering BURNING/EXHAUSTED freezes the host-plane flight
        # recorder (broker/hostprof.py): the budget started draining NOW,
        # and the loop-lag / GC / blocking forensics of the last minutes
        # are exactly what diagnoses it (rate-limited per reason)
        if new > old:
            from rmqtt_tpu.broker.hostprof import HOSTPROF

            if HOSTPROF.enabled:
                HOSTPROF.auto_dump(f"slo_{new.name.lower()}")
        row = self._objective_row(obj, i, self._clock())
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # tick() driven synchronously in tests: no hook task
        from rmqtt_tpu.broker.hooks import HookType

        loop.create_task(
            ctx.hooks.fire(HookType.SERVER_SLO, obj.name, old.name,
                           new.name, row))

    # ----------------------------------------------------------- surfaces
    @property
    def worst_state(self) -> SloState:
        return max(self._states, default=SloState.OK)

    def _objective_row(self, obj: Objective, i: int, now: float) -> dict:
        # cumulative counts read LIVE (not from the last tick) so a
        # snapshot taken right after a burst judges the burst; windows
        # stay tick-sampled. Disabled engines report zeros (shape-stable,
        # no evaluation).
        good, total = obj.cumulative(self.ctx) if self.enabled else (0, 0)
        fg, ft, fcov, fast_frac, fast_burn = self._window_burn(
            i, self.fast_window_s, now)
        sg, st, scov, slow_frac, slow_burn = self._window_burn(
            i, self.slow_window_s, now)
        row = {
            "name": obj.name,
            "kind": obj.kind,
            "target": obj.target,
            "state": self._states[i].name,
            "state_value": int(self._states[i]),
            "good": good,
            "total": total,
            "ratio": round(good / total, 6) if total else 1.0,
            "compliant": (good / total >= obj.target) if total else True,
            "fast": {"window_s": self.fast_window_s, "good": fg, "total": ft,
                     "coverage": round(fcov, 4),
                     "bad_fraction": round(fast_frac, 6),
                     "burn_rate": round(fast_burn, 4)},
            "slow": {"window_s": self.slow_window_s, "good": sg, "total": st,
                     "coverage": round(scov, 4),
                     "bad_fraction": round(slow_frac, 6),
                     "burn_rate": round(slow_burn, 4)},
            "budget_remaining": round(max(0.0, 1.0 - slow_burn), 4),
        }
        if obj.kind == "latency":
            row["stage"] = obj.stage
            row["threshold_ms"] = obj.threshold_ms
            row["effective_threshold_ms"] = obj.effective_threshold_ms
        else:
            row["exclude_reasons"] = list(obj.exclude_reasons)
        return row

    def snapshot(self) -> dict:
        """The `/api/v1/slo` body; shape-stable when disabled (objectives
        listed with zero data, no burn)."""
        now = self._clock()
        worst = self.worst_state
        return {
            "enabled": self.enabled,
            "state": worst.name,
            "state_value": int(worst),
            "transitions": self.transitions,
            "sample_interval": self.sample_interval,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "burn_alert": self.burn_alert,
            "objectives": [
                self._objective_row(obj, i, now)
                for i, obj in enumerate(self.objectives)
            ],
        }

    @staticmethod
    def merge_snapshots(base: dict, others: Iterable[dict]) -> dict:
        """Cluster-wide merge (`/api/v1/slo/sum`): per-objective (good,
        total) pairs — cumulative AND per-window — sum across nodes (the
        same additivity the latency histograms are built on); burn rates
        are recomputed from the merged sums and states merge by worst."""
        others = list(others)
        merged: Dict[str, dict] = {}
        order: List[str] = []
        for snap in [base, *others]:
            for row in snap.get("objectives") or ():
                name = row["name"]
                agg = merged.get(name)
                if agg is None:
                    agg = merged[name] = {
                        k: row[k] for k in row
                        if k not in ("good", "total", "ratio", "compliant",
                                     "fast", "slow", "budget_remaining",
                                     "state", "state_value")
                    }
                    agg.update(good=0, total=0, state_value=0)
                    for w in ("fast", "slow"):
                        agg[w] = {"window_s": row[w]["window_s"],
                                  "good": 0, "total": 0, "coverage": 0.0}
                    order.append(name)
                agg["good"] += row["good"]
                agg["total"] += row["total"]
                agg["state_value"] = max(agg["state_value"],
                                         int(row.get("state_value", 0)))
                for w in ("fast", "slow"):
                    agg[w]["good"] += row[w]["good"]
                    agg[w]["total"] += row[w]["total"]
                    # longest-running node's coverage: the merged deltas
                    # span at most that much of the window
                    agg[w]["coverage"] = max(agg[w]["coverage"],
                                             row[w].get("coverage", 1.0))
        for agg in merged.values():
            target = float(agg.get("target", 0.99))
            g, t = agg["good"], agg["total"]
            agg["ratio"] = round(g / t, 6) if t else 1.0
            agg["compliant"] = (g / t >= target) if t else True
            for w in ("fast", "slow"):
                frac, burn = _burn(agg[w]["good"], agg[w]["total"], target)
                agg[w]["bad_fraction"] = round(frac, 6)
                agg[w]["burn_rate"] = round(burn * agg[w]["coverage"], 4)
            agg["budget_remaining"] = round(
                max(0.0, 1.0 - agg["slow"]["burn_rate"]), 4)
            agg["state"] = SloState(agg["state_value"]).name
        worst = max((a["state_value"] for a in merged.values()), default=0)
        return {
            "nodes": 1 + len(others),
            "enabled": bool(base.get("enabled", False)),
            "state": SloState(worst).name,
            "state_value": worst,
            "objectives": [merged[name] for name in order],
        }

    def prometheus_lines(self, labels: str) -> List[str]:
        """`rmqtt_slo_*` exposition families, one objective-labeled sample
        per row: state / burn rates / budget plus good-vs-bad event
        counters (``result`` label) so dashboards can derive their own
        windows."""
        now = self._clock()
        # NOTE: the worst-state scalar exports as rmqtt_slo_state via the
        # generic Stats-gauge loop (slo_state); the per-objective family
        # must use a DIFFERENT name — two TYPE lines for one metric name
        # are invalid exposition
        gauges = {
            "rmqtt_slo_objective_state": lambda r: r["state_value"],
            "rmqtt_slo_target": lambda r: r["target"],
            "rmqtt_slo_burn_rate_fast": lambda r: r["fast"]["burn_rate"],
            "rmqtt_slo_burn_rate_slow": lambda r: r["slow"]["burn_rate"],
            "rmqtt_slo_budget_remaining": lambda r: r["budget_remaining"],
        }
        rows = [self._objective_row(obj, i, now)
                for i, obj in enumerate(self.objectives)]
        out: List[str] = []
        for metric, getter in gauges.items():
            out.append(f"# TYPE {metric} gauge")
            for row in rows:
                oname = prom_sanitize(row["name"])
                out.append(
                    f'{metric}{{{labels},objective="{oname}"}} '
                    f'{format(getter(row), "g")}')
        out.append("# TYPE rmqtt_slo_events_total counter")
        for row in rows:
            oname = prom_sanitize(row["name"])
            out.append(
                f'rmqtt_slo_events_total{{{labels},objective="{oname}",'
                f'result="good"}} {row["good"]}')
            out.append(
                f'rmqtt_slo_events_total{{{labels},objective="{oname}",'
                f'result="bad"}} {row["total"] - row["good"]}')
        return out
