"""MQTT frame codec: incremental decode + version-dependent encode.

Equivalent of the reference's `MqttCodec` (`rmqtt-codec/src/lib.rs:46-134`):
feed bytes in, complete `Packet`s out; encode `Packet`s per negotiated
protocol version. The CONNECT packet carries its own version (sniffed like
`rmqtt-codec/src/version.rs`); everything after uses the codec's version.
Inbound frames above ``max_inbound_size`` are rejected
(`rmqtt-codec/src/v5/codec.rs:250`).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

from rmqtt_tpu.broker.codec import packets as pk
from rmqtt_tpu.broker.codec.packets import (
    Auth,
    Connack,
    Connect,
    Disconnect,
    Packet,
    Pingreq,
    Pingresp,
    Puback,
    Pubcomp,
    Publish,
    Pubrec,
    Pubrel,
    Suback,
    SubOpts,
    Subscribe,
    Unsuback,
    Unsubscribe,
    Will,
)
from rmqtt_tpu.broker.codec.primitives import (
    ProtocolViolation as ProtocolError,
    Reader,
    encode_binary,
    encode_utf8,
    encode_varint,
)
from rmqtt_tpu.broker.codec.props import decode_properties, encode_properties

_PROTO_NAMES = {b"MQIsdp": pk.V31, b"MQTT": None}  # None → level byte decides

# native frame scanner (runtime/codec.cc): None = not probed, False = absent
_native = None
# per-call crossover: below this buffered size the scan wrapper's ~10µs
# (array alloc + ctypes marshalling) outweighs the Python decode it saves.
# tests derive their chunk sizes from this so native coverage survives tuning
NATIVE_MIN_BYTES = 512


def _native_lib():
    global _native
    if _native is None:
        try:
            from rmqtt_tpu import runtime as _rt

            _native = _rt.load() or False
        except Exception:
            _native = False
    return _native or None


_SCAN_ERRORS = {
    1: "malformed remaining length",
    2: "packet too large",
    3: "invalid QoS 3",
    4: "malformed PUBLISH",
    5: "malformed properties length",
}


class MqttCodec:
    """Incremental decoder + encoder for one connection."""

    def __init__(self, version: int = pk.V311, max_inbound_size: int = 1024 * 1024) -> None:
        self.version = version
        self.max_inbound_size = max_inbound_size
        self._buf = bytearray()
        # set when a frame fails to decode: earlier valid packets from the
        # same feed() are still returned; callers must check it after
        # processing them (and then close the connection)
        self.pending_error: Optional[ProtocolError] = None

    # ------------------------------------------------------------- decode
    def feed(self, data: bytes) -> List[Packet]:
        if self.pending_error is not None:
            raise self.pending_error
        self._buf += data
        out: List[Packet] = []
        # the native wrapper costs ~10µs per call (array alloc + ctypes);
        # it wins on coalesced multi-frame reads, loses on tiny interactive
        # feeds — only engage above the crossover size
        lib = _native_lib() if len(self._buf) >= NATIVE_MIN_BYTES else None
        if lib is not None and self._have_complete_frame():
            # C++ fast path: scan all complete frames at once, PUBLISH
            # pre-parsed (runtime/codec.cc). Stops at CONNECT/incomplete;
            # the Python loop below handles whatever remains. The cheap
            # completeness peek keeps large fragmented packets O(1) per
            # chunk (no buffer snapshot until a frame can actually decode).
            self._feed_native(lib, out)
            if self.pending_error is not None:
                if out:
                    return out
                raise self.pending_error
        while True:
            try:
                frame = self._next_frame()
            except ProtocolError as e:
                self.pending_error = e
                if out:
                    return out  # deliver what decoded before the bad frame
                raise
            if frame is None:
                return out
            first, body = frame
            try:
                out.append(self._decode(first, body))
            except ProtocolError as e:
                self.pending_error = e
                if out:
                    return out
                raise

    def _have_complete_frame(self) -> bool:
        """Fixed-header peek: is at least one full frame buffered? (Also
        true for frames the scan should reject — it surfaces the error.)"""
        buf = self._buf
        if len(buf) < 2:
            return False
        mult, length, i = 1, 0, 1
        while True:
            if i >= len(buf):
                return False
            b = buf[i]
            length += (b & 0x7F) * mult
            i += 1
            if not b & 0x80:
                break
            mult *= 128
            if mult > 128**3:
                return True  # malformed: let the scan report it
        return length > self.max_inbound_size or len(buf) >= i + length

    def _feed_native(self, lib, out: List[Packet]) -> None:
        from rmqtt_tpu import runtime as rt

        v5 = self.version == pk.V5
        while True:
            buf = bytes(self._buf)
            rows, consumed, err, hit_cap = rt.codec_scan(lib, buf, v5, self.max_inbound_size)
            if consumed:
                del self._buf[:consumed]
            for m in rows:
                first = m[0]
                try:
                    if first >> 4 == pk.TYPE_PUBLISH:
                        out.append(self._build_publish(buf, m, v5))
                    else:
                        out.append(self._decode(first, buf[m[1] : m[1] + m[2]]))
                except ProtocolError as e:
                    self.pending_error = e
                    return
            if err:
                self.pending_error = ProtocolError(
                    _SCAN_ERRORS.get(err, f"scan error {err}"),
                    reason_code=0x95 if err == 2 else 0x81,
                )
                return
            if not hit_cap:
                return

    def _build_publish(self, buf: bytes, m, v5: bool) -> Publish:
        first = m[0]
        qos = (first >> 1) & 0x3
        try:
            topic = buf[m[3] : m[3] + m[4]].decode("utf-8")
        except UnicodeDecodeError as e:
            raise ProtocolError(f"invalid utf8: {e}") from e
        props = {}
        if v5 and m[7] > 1:  # a single byte is the zero-length varint
            props = decode_properties(Reader(buf[m[6] : m[6] + m[7]]))
        # positional: ~350ns/pkt cheaper than kwargs on the hot path
        return Publish(
            topic,
            buf[m[8] : m[8] + m[9]],
            qos,
            bool(first & 0x1),
            bool(first & 0x8),
            m[5] if m[5] >= 0 else None,
            props,
        )

    def _next_frame(self) -> Optional[Tuple[int, bytes]]:
        buf = self._buf
        if len(buf) < 2:
            return None
        # fixed header: 1 byte type/flags + varint remaining length
        mult, length, i = 1, 0, 1
        while True:
            if i >= len(buf):
                return None  # varint incomplete
            b = buf[i]
            length += (b & 0x7F) * mult
            i += 1
            if not b & 0x80:
                break
            mult *= 128
            if mult > 128**3:
                raise ProtocolError("malformed remaining length")
        if length > self.max_inbound_size:
            raise ProtocolError(
                f"packet too large: {length} > {self.max_inbound_size}",
                reason_code=0x95,
            )
        if len(buf) < i + length:
            return None
        first = buf[0]
        body = bytes(buf[i : i + length])
        del buf[: i + length]
        return first, body

    def _decode(self, first: int, body: bytes) -> Packet:
        ptype, flags = first >> 4, first & 0x0F
        r = Reader(body)
        v5 = self.version == pk.V5
        if ptype == pk.TYPE_CONNECT:
            return self._decode_connect(r)
        if ptype == pk.TYPE_CONNACK:
            session_present = bool(r.u8() & 0x01)
            reason = r.u8()
            props = decode_properties(r) if v5 else {}
            return Connack(session_present, reason, props)
        if ptype == pk.TYPE_PUBLISH:
            qos = (flags >> 1) & 0x3
            if qos == 3:
                raise ProtocolError("invalid QoS 3")
            topic = r.utf8()
            packet_id = r.u16() if qos else None
            props = decode_properties(r) if v5 else {}
            return Publish(
                topic,
                r.rest(),
                qos,
                bool(flags & 0x1),
                bool(flags & 0x8),
                packet_id,
                props,
            )
        if ptype in (pk.TYPE_PUBACK, pk.TYPE_PUBREC, pk.TYPE_PUBREL, pk.TYPE_PUBCOMP):
            if ptype == pk.TYPE_PUBREL and flags != 0x2:
                raise ProtocolError("bad PUBREL flags")
            pid = r.u16()
            reason, props = 0, {}
            if v5 and r.remaining():
                reason = r.u8()
                if r.remaining():
                    props = decode_properties(r)
            cls = {
                pk.TYPE_PUBACK: Puback,
                pk.TYPE_PUBREC: Pubrec,
                pk.TYPE_PUBREL: Pubrel,
                pk.TYPE_PUBCOMP: Pubcomp,
            }[ptype]
            return cls(pid, reason, props)
        if ptype == pk.TYPE_SUBSCRIBE:
            if flags != 0x2:
                raise ProtocolError("bad SUBSCRIBE flags")
            pid = r.u16()
            props = decode_properties(r) if v5 else {}
            filters = []
            while r.remaining():
                tf = r.utf8()
                filters.append((tf, SubOpts.decode(r.u8())))
            if not filters:
                raise ProtocolError("SUBSCRIBE with no filters")
            return Subscribe(pid, filters, props)
        if ptype == pk.TYPE_SUBACK:
            pid = r.u16()
            props = decode_properties(r) if v5 else {}
            return Suback(pid, list(r.rest()), props)
        if ptype == pk.TYPE_UNSUBSCRIBE:
            if flags != 0x2:
                raise ProtocolError("bad UNSUBSCRIBE flags")
            pid = r.u16()
            props = decode_properties(r) if v5 else {}
            filters = []
            while r.remaining():
                filters.append(r.utf8())
            if not filters:
                raise ProtocolError("UNSUBSCRIBE with no filters")
            return Unsubscribe(pid, filters, props)
        if ptype == pk.TYPE_UNSUBACK:
            pid = r.u16()
            props = decode_properties(r) if v5 else {}
            return Unsuback(pid, list(r.rest()) if v5 else [], props)
        if ptype == pk.TYPE_PINGREQ:
            return Pingreq()
        if ptype == pk.TYPE_PINGRESP:
            return Pingresp()
        if ptype == pk.TYPE_DISCONNECT:
            reason, props = 0, {}
            if v5 and r.remaining():
                reason = r.u8()
                if r.remaining():
                    props = decode_properties(r)
            return Disconnect(reason, props)
        if ptype == pk.TYPE_AUTH:
            if not v5:
                raise ProtocolError("AUTH requires MQTT 5")
            reason, props = 0, {}
            if r.remaining():
                reason = r.u8()
                if r.remaining():
                    props = decode_properties(r)
            return Auth(reason, props)
        raise ProtocolError(f"unknown packet type {ptype}")

    def _decode_connect(self, r: Reader) -> Connect:
        name = r.binary()
        level = r.u8()
        if name == b"MQIsdp" and level == 3:
            version = pk.V31
        elif name == b"MQTT" and level in (4, 5):
            version = pk.V311 if level == 4 else pk.V5
        else:
            raise ProtocolError(f"unsupported protocol {name!r} level {level}")
        self.version = version
        cflags = r.u8()
        if cflags & 0x01:
            raise ProtocolError("CONNECT reserved flag set")
        keepalive = r.u16()
        props = decode_properties(r) if version == pk.V5 else {}
        client_id = r.utf8()
        will = None
        if cflags & 0x04:
            wprops = decode_properties(r) if version == pk.V5 else {}
            wtopic = r.utf8()
            wpayload = r.binary()
            will = Will(
                topic=wtopic,
                payload=wpayload,
                qos=(cflags >> 3) & 0x3,
                retain=bool(cflags & 0x20),
                properties=wprops,
            )
            if will.qos == 3:
                raise ProtocolError("invalid will QoS")
        elif cflags & 0x38:
            raise ProtocolError("will flags without will")
        username = r.utf8() if cflags & 0x80 else None
        password = r.binary() if cflags & 0x40 else None
        return Connect(
            client_id=client_id,
            protocol=version,
            clean_start=bool(cflags & 0x02),
            keepalive=keepalive,
            username=username,
            password=password,
            will=will,
            properties=props,
        )

    # ------------------------------------------------------------- encode
    def encode(self, p: Packet) -> bytes:
        v5 = self.version == pk.V5
        if isinstance(p, Connect):
            return self._encode_connect(p)
        if isinstance(p, Connack):
            body = bytes([0x01 if p.session_present else 0x00, p.reason_code])
            if v5:
                body += encode_properties(p.properties)
            return self._frame(pk.TYPE_CONNACK, 0, body)
        if isinstance(p, Publish):
            if p.qos and p.packet_id is None:
                raise ProtocolError("QoS>0 PUBLISH needs packet_id")
            # C++ fast path (runtime/codec.cc rt_codec_encode_publish):
            # the whole frame — header byte, varint, topic, packet id,
            # props blob, payload — is assembled in one native call. Byte
            # equality with the Python arm below is property-tested; only
            # engage above the same crossover the scanner uses (the ctypes
            # marshalling costs more than small frames save)
            if len(p.payload) >= NATIVE_MIN_BYTES:
                lib = _native_lib()
                topic_b = p.topic.encode("utf-8")
                if lib is not None and len(topic_b) <= 0xFFFF:
                    from rmqtt_tpu.runtime import codec_encode_publish

                    data = codec_encode_publish(
                        lib, topic_b, bytes(p.payload),
                        encode_properties(p.properties) if v5 else b"",
                        p.qos, p.retain, p.dup, p.packet_id)
                    if data is not None:
                        return data
            flags = (0x8 if p.dup else 0) | ((p.qos & 0x3) << 1) | (0x1 if p.retain else 0)
            body = bytearray(encode_utf8(p.topic))
            if p.qos:
                body += p.packet_id.to_bytes(2, "big")
            if v5:
                body += encode_properties(p.properties)
            body += p.payload
            return self._frame(pk.TYPE_PUBLISH, flags, bytes(body))
        if isinstance(p, (Puback, Pubrec, Pubrel, Pubcomp)):
            t = {
                Puback: pk.TYPE_PUBACK,
                Pubrec: pk.TYPE_PUBREC,
                Pubrel: pk.TYPE_PUBREL,
                Pubcomp: pk.TYPE_PUBCOMP,
            }[type(p)]
            flags = 0x2 if t == pk.TYPE_PUBREL else 0
            body = bytearray(p.packet_id.to_bytes(2, "big"))
            if v5 and (p.reason_code or p.properties):
                body.append(p.reason_code)
                if p.properties:
                    body += encode_properties(p.properties)
            return self._frame(t, flags, bytes(body))
        if isinstance(p, Subscribe):
            body = bytearray(p.packet_id.to_bytes(2, "big"))
            if v5:
                body += encode_properties(p.properties)
            for tf, opts in p.filters:
                body += encode_utf8(tf)
                body.append(opts.encode() if v5 else opts.qos & 0x3)
            return self._frame(pk.TYPE_SUBSCRIBE, 0x2, bytes(body))
        if isinstance(p, Suback):
            body = bytearray(p.packet_id.to_bytes(2, "big"))
            if v5:
                body += encode_properties(p.properties)
            body += bytes(p.reason_codes)
            return self._frame(pk.TYPE_SUBACK, 0, bytes(body))
        if isinstance(p, Unsubscribe):
            body = bytearray(p.packet_id.to_bytes(2, "big"))
            if v5:
                body += encode_properties(p.properties)
            for tf in p.filters:
                body += encode_utf8(tf)
            return self._frame(pk.TYPE_UNSUBSCRIBE, 0x2, bytes(body))
        if isinstance(p, Unsuback):
            body = bytearray(p.packet_id.to_bytes(2, "big"))
            if v5:
                body += encode_properties(p.properties)
                body += bytes(p.reason_codes)
            return self._frame(pk.TYPE_UNSUBACK, 0, bytes(body))
        if isinstance(p, Pingreq):
            return self._frame(pk.TYPE_PINGREQ, 0, b"")
        if isinstance(p, Pingresp):
            return self._frame(pk.TYPE_PINGRESP, 0, b"")
        if isinstance(p, Disconnect):
            body = b""
            if v5 and (p.reason_code or p.properties):
                body = bytes([p.reason_code]) + (
                    encode_properties(p.properties) if p.properties else b""
                )
            return self._frame(pk.TYPE_DISCONNECT, 0, body)
        if isinstance(p, Auth):
            body = b""
            if p.reason_code or p.properties:
                body = bytes([p.reason_code]) + encode_properties(p.properties)
            return self._frame(pk.TYPE_AUTH, 0, body)
        raise ProtocolError(f"cannot encode {type(p).__name__}")

    def _encode_connect(self, p: Connect) -> bytes:
        # mirror _decode_connect: the negotiated version governs all
        # subsequent packets on this codec (client-side use)
        self.version = p.protocol
        v5 = p.protocol == pk.V5
        if p.protocol == pk.V31:
            head = encode_binary(b"MQIsdp") + bytes([3])
        else:
            head = encode_binary(b"MQTT") + bytes([4 if p.protocol == pk.V311 else 5])
        cflags = 0
        if p.clean_start:
            cflags |= 0x02
        if p.will:
            cflags |= 0x04 | ((p.will.qos & 0x3) << 3) | (0x20 if p.will.retain else 0)
        if p.username is not None:
            cflags |= 0x80
        if p.password is not None:
            cflags |= 0x40
        body = bytearray(head)
        body.append(cflags)
        body += p.keepalive.to_bytes(2, "big")
        if v5:
            body += encode_properties(p.properties)
        body += encode_utf8(p.client_id)
        if p.will:
            if v5:
                body += encode_properties(p.will.properties)
            body += encode_utf8(p.will.topic)
            body += encode_binary(p.will.payload)
        if p.username is not None:
            body += encode_utf8(p.username)
        if p.password is not None:
            body += encode_binary(p.password)
        return self._frame(pk.TYPE_CONNECT, 0, bytes(body))

    def _frame(self, ptype: int, flags: int, body: bytes) -> bytes:
        return bytes([(ptype << 4) | flags]) + encode_varint(len(body)) + body
