"""Version-unified MQTT packet model.

One dataclass per control packet, shared across v3.1/v3.1.1/v5 — the
reference's ``MqttPacket`` unification (`rmqtt-codec/src/lib.rs:60-67`,
v3 `src/v3/packet.rs:126`, v5 `src/v5/packet/mod.rs:29`). v5-only fields
(properties, reason codes) are simply empty/zero under v3.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

# protocol levels (CONNECT byte 7/8): 3 = MQTT 3.1, 4 = MQTT 3.1.1, 5 = MQTT 5.0
V31, V311, V5 = 3, 4, 5

Properties = Dict[int, object]  # property id → value ([(k,v)...] for user props)


@dataclass
class Will:
    topic: str
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    properties: Properties = field(default_factory=dict)


@dataclass
class Connect:
    client_id: str = ""
    protocol: int = V311
    clean_start: bool = True
    keepalive: int = 60
    username: Optional[str] = None
    password: Optional[bytes] = None
    will: Optional[Will] = None
    properties: Properties = field(default_factory=dict)


@dataclass
class Connack:
    session_present: bool = False
    reason_code: int = 0
    properties: Properties = field(default_factory=dict)


# slots=True on the per-message hot classes: ~30% cheaper construction and
# no per-instance __dict__ (the broker creates one Publish per inbound
# message and one per delivery); subclasses declare empty __slots__ so they
# don't silently grow a __dict__ back
@dataclass(slots=True)
class Publish:
    topic: str
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    dup: bool = False
    packet_id: Optional[int] = None
    properties: Properties = field(default_factory=dict)


@dataclass(slots=True)
class _Ack:
    packet_id: int
    reason_code: int = 0
    properties: Properties = field(default_factory=dict)


class Puback(_Ack):
    __slots__ = ()


class Pubrec(_Ack):
    __slots__ = ()


class Pubrel(_Ack):
    __slots__ = ()


class Pubcomp(_Ack):
    __slots__ = ()


@dataclass
class SubOpts:
    """SUBSCRIBE per-filter options byte (v5 3.8.3.1; v3: qos only)."""

    qos: int = 0
    no_local: bool = False
    retain_as_published: bool = False
    retain_handling: int = 0

    def encode(self) -> int:
        return (
            (self.qos & 0x3)
            | (0x04 if self.no_local else 0)
            | (0x08 if self.retain_as_published else 0)
            | ((self.retain_handling & 0x3) << 4)
        )

    @classmethod
    def decode(cls, b: int) -> "SubOpts":
        return cls(
            qos=b & 0x3,
            no_local=bool(b & 0x04),
            retain_as_published=bool(b & 0x08),
            retain_handling=(b >> 4) & 0x3,
        )


@dataclass
class Subscribe:
    packet_id: int
    filters: List[Tuple[str, SubOpts]] = field(default_factory=list)
    properties: Properties = field(default_factory=dict)


@dataclass
class Suback:
    packet_id: int
    reason_codes: List[int] = field(default_factory=list)
    properties: Properties = field(default_factory=dict)


@dataclass
class Unsubscribe:
    packet_id: int
    filters: List[str] = field(default_factory=list)
    properties: Properties = field(default_factory=dict)


@dataclass
class Unsuback:
    packet_id: int
    reason_codes: List[int] = field(default_factory=list)
    properties: Properties = field(default_factory=dict)


@dataclass
class Pingreq:
    pass


@dataclass
class Pingresp:
    pass


@dataclass
class Disconnect:
    reason_code: int = 0
    properties: Properties = field(default_factory=dict)


@dataclass
class Auth:
    reason_code: int = 0
    properties: Properties = field(default_factory=dict)


Packet = Union[
    Connect,
    Connack,
    Publish,
    Puback,
    Pubrec,
    Pubrel,
    Pubcomp,
    Subscribe,
    Suback,
    Unsubscribe,
    Unsuback,
    Pingreq,
    Pingresp,
    Disconnect,
    Auth,
]

# control packet type ids (MQTT spec 2.1.2)
TYPE_CONNECT = 1
TYPE_CONNACK = 2
TYPE_PUBLISH = 3
TYPE_PUBACK = 4
TYPE_PUBREC = 5
TYPE_PUBREL = 6
TYPE_PUBCOMP = 7
TYPE_SUBSCRIBE = 8
TYPE_SUBACK = 9
TYPE_UNSUBSCRIBE = 10
TYPE_UNSUBACK = 11
TYPE_PINGREQ = 12
TYPE_PINGRESP = 13
TYPE_DISCONNECT = 14
TYPE_AUTH = 15
