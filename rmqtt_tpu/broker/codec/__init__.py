"""MQTT v3.1 / v3.1.1 / v5.0 wire codec.

The equivalent of the reference's `rmqtt-codec` crate
(`/root/reference/rmqtt-codec/src/lib.rs:46-67`: a version-unified
``MqttCodec``/``MqttPacket``): one packet model for all protocol versions,
with version-dependent encode/decode and CONNECT version sniffing
(`rmqtt-codec/src/version.rs`). Size-capped decoding mirrors
``set_max_inbound_size`` (`rmqtt-codec/src/v5/codec.rs:250`).
"""

from rmqtt_tpu.broker.codec.packets import (
    Auth,
    Connack,
    Connect,
    Disconnect,
    Packet,
    Pingreq,
    Pingresp,
    Puback,
    Pubcomp,
    Publish,
    Pubrec,
    Pubrel,
    Subscribe,
    Suback,
    SubOpts,
    Unsuback,
    Unsubscribe,
    Will,
)
from rmqtt_tpu.broker.codec.codec import MqttCodec, ProtocolError
from rmqtt_tpu.broker.codec import props
