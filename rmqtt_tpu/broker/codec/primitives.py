"""MQTT wire primitives: varint, UTF-8 strings, binary data, fixed ints."""

from __future__ import annotations


class ProtocolViolation(ValueError):
    """Wire-level violation. ``reason_code`` is the v5 DISCONNECT reason the
    server should send before closing (0x81 malformed packet by default;
    the codec's size cap uses 0x95 packet-too-large)."""

    def __init__(self, msg: str, reason_code: int = 0x81) -> None:
        super().__init__(msg)
        self.reason_code = reason_code


def encode_varint(n: int) -> bytes:
    """Variable byte integer (MQTT 1.5.5), up to 268 435 455."""
    if n < 0 or n > 0x0FFFFFFF:
        raise ProtocolViolation(f"varint out of range: {n}")
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_utf8(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 0xFFFF:
        raise ProtocolViolation("utf8 string too long")
    return len(b).to_bytes(2, "big") + b


def encode_binary(b: bytes) -> bytes:
    if len(b) > 0xFFFF:
        raise ProtocolViolation("binary data too long")
    return len(b).to_bytes(2, "big") + b


class Reader:
    """Cursor over one packet body; all reads bounds-checked."""

    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf: bytes, pos: int = 0, end: int | None = None) -> None:
        self.buf = buf
        self.pos = pos
        self.end = len(buf) if end is None else end

    def _need(self, n: int) -> None:
        if self.pos + n > self.end:
            raise ProtocolViolation("truncated packet")

    def remaining(self) -> int:
        return self.end - self.pos

    def u8(self) -> int:
        self._need(1)
        v = self.buf[self.pos]
        self.pos += 1
        return v

    def u16(self) -> int:
        self._need(2)
        v = int.from_bytes(self.buf[self.pos : self.pos + 2], "big")
        self.pos += 2
        return v

    def u32(self) -> int:
        self._need(4)
        v = int.from_bytes(self.buf[self.pos : self.pos + 4], "big")
        self.pos += 4
        return v

    def varint(self) -> int:
        mult, value = 1, 0
        for _ in range(4):
            b = self.u8()
            value += (b & 0x7F) * mult
            if not b & 0x80:
                return value
            mult *= 128
        raise ProtocolViolation("malformed varint")

    def take(self, n: int) -> bytes:
        self._need(n)
        v = self.buf[self.pos : self.pos + n]
        self.pos += n
        return bytes(v)

    def rest(self) -> bytes:
        return self.take(self.end - self.pos)

    def utf8(self) -> str:
        n = self.u16()
        try:
            return self.take(n).decode("utf-8")
        except UnicodeDecodeError as e:
            raise ProtocolViolation(f"invalid utf8: {e}") from e

    def binary(self) -> bytes:
        return self.take(self.u16())
