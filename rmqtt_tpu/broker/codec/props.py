"""MQTT v5 property encode/decode.

The property table of MQTT 5.0 §2.2.2 — the reference implements this in
`rmqtt-codec/src/v5/{encode,decode}.rs`. Properties travel as
``dict[property_id, value]``; ``USER_PROPERTY`` and ``SUBSCRIPTION_IDENTIFIER``
accumulate into lists since they may repeat.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from rmqtt_tpu.broker.codec.primitives import (
    ProtocolViolation,
    Reader,
    encode_binary,
    encode_utf8,
    encode_varint,
)

# property ids (MQTT-5.0 2.2.2.2)
PAYLOAD_FORMAT_INDICATOR = 0x01
MESSAGE_EXPIRY_INTERVAL = 0x02
CONTENT_TYPE = 0x03
RESPONSE_TOPIC = 0x08
CORRELATION_DATA = 0x09
SUBSCRIPTION_IDENTIFIER = 0x0B
SESSION_EXPIRY_INTERVAL = 0x11
ASSIGNED_CLIENT_IDENTIFIER = 0x12
SERVER_KEEP_ALIVE = 0x13
AUTHENTICATION_METHOD = 0x15
AUTHENTICATION_DATA = 0x16
REQUEST_PROBLEM_INFORMATION = 0x17
WILL_DELAY_INTERVAL = 0x18
REQUEST_RESPONSE_INFORMATION = 0x19
RESPONSE_INFORMATION = 0x1A
SERVER_REFERENCE = 0x1C
REASON_STRING = 0x1F
RECEIVE_MAXIMUM = 0x21
TOPIC_ALIAS_MAXIMUM = 0x22
TOPIC_ALIAS = 0x23
MAXIMUM_QOS = 0x24
RETAIN_AVAILABLE = 0x25
USER_PROPERTY = 0x26
MAXIMUM_PACKET_SIZE = 0x27
WILDCARD_SUBSCRIPTION_AVAILABLE = 0x28
SUBSCRIPTION_IDENTIFIER_AVAILABLE = 0x29
SHARED_SUBSCRIPTION_AVAILABLE = 0x2A

# property id → wire type
_U8 = "u8"
_U16 = "u16"
_U32 = "u32"
_VARINT = "varint"
_UTF8 = "utf8"
_BIN = "bin"
_PAIR = "pair"

_TYPES: Dict[int, str] = {
    PAYLOAD_FORMAT_INDICATOR: _U8,
    MESSAGE_EXPIRY_INTERVAL: _U32,
    CONTENT_TYPE: _UTF8,
    RESPONSE_TOPIC: _UTF8,
    CORRELATION_DATA: _BIN,
    SUBSCRIPTION_IDENTIFIER: _VARINT,
    SESSION_EXPIRY_INTERVAL: _U32,
    ASSIGNED_CLIENT_IDENTIFIER: _UTF8,
    SERVER_KEEP_ALIVE: _U16,
    AUTHENTICATION_METHOD: _UTF8,
    AUTHENTICATION_DATA: _BIN,
    REQUEST_PROBLEM_INFORMATION: _U8,
    WILL_DELAY_INTERVAL: _U32,
    REQUEST_RESPONSE_INFORMATION: _U8,
    RESPONSE_INFORMATION: _UTF8,
    SERVER_REFERENCE: _UTF8,
    REASON_STRING: _UTF8,
    RECEIVE_MAXIMUM: _U16,
    TOPIC_ALIAS_MAXIMUM: _U16,
    TOPIC_ALIAS: _U16,
    MAXIMUM_QOS: _U8,
    RETAIN_AVAILABLE: _U8,
    USER_PROPERTY: _PAIR,
    MAXIMUM_PACKET_SIZE: _U32,
    WILDCARD_SUBSCRIPTION_AVAILABLE: _U8,
    SUBSCRIPTION_IDENTIFIER_AVAILABLE: _U8,
    SHARED_SUBSCRIPTION_AVAILABLE: _U8,
}

# properties that may appear more than once → list-valued
_REPEATABLE = {USER_PROPERTY, SUBSCRIPTION_IDENTIFIER}


def encode_properties(props: Dict[int, object]) -> bytes:
    body = bytearray()
    for pid, value in props.items():
        ptype = _TYPES.get(pid)
        if ptype is None:
            raise ProtocolViolation(f"unknown property id {pid}")
        values = value if pid in _REPEATABLE and isinstance(value, list) else [value]
        for v in values:
            body += encode_varint(pid)
            if ptype == _U8:
                body.append(int(v) & 0xFF)
            elif ptype == _U16:
                body += int(v).to_bytes(2, "big")
            elif ptype == _U32:
                body += int(v).to_bytes(4, "big")
            elif ptype == _VARINT:
                body += encode_varint(int(v))
            elif ptype == _UTF8:
                body += encode_utf8(str(v))
            elif ptype == _BIN:
                body += encode_binary(bytes(v))
            elif ptype == _PAIR:
                k, val = v
                body += encode_utf8(str(k)) + encode_utf8(str(val))
    return bytes(encode_varint(len(body))) + bytes(body)


def decode_properties(r: Reader) -> Dict[int, object]:
    length = r.varint()
    end = r.pos + length
    props: Dict[int, object] = {}
    while r.pos < end:
        pid = r.varint()
        ptype = _TYPES.get(pid)
        if ptype is None:
            raise ProtocolViolation(f"unknown property id {pid}")
        if ptype == _U8:
            v: object = r.u8()
        elif ptype == _U16:
            v = r.u16()
        elif ptype == _U32:
            v = r.u32()
        elif ptype == _VARINT:
            v = r.varint()
        elif ptype == _UTF8:
            v = r.utf8()
        elif ptype == _BIN:
            v = r.binary()
        else:  # _PAIR
            v = (r.utf8(), r.utf8())
        if pid in _REPEATABLE:
            props.setdefault(pid, [])
            props[pid].append(v)  # type: ignore[union-attr]
        else:
            if pid in props:
                raise ProtocolViolation(f"duplicate property id {pid}")
            props[pid] = v
    if r.pos != end:
        raise ProtocolViolation("property length mismatch")
    return props
