"""Broker-wide latency telemetry: log2 histograms + slow-op ring log.

The counter surface (`broker/metrics.py`) says how OFTEN things happen;
this layer says how LONG they take. Three pieces:

``Histogram``
    A fixed power-of-two-bucket latency histogram (ns resolution).
    Bucket ``i`` covers ``[2^i, 2^(i+1))`` ns (bucket 0 additionally
    absorbs 0), the top bucket absorbs overflow (2^39 ns ≈ 9 min — far
    past anything a broker op should take). Recording is two int ops
    (``bit_length`` + list increment); quantile estimation walks the 40
    counts and returns the containing bucket's upper bound, so an
    estimate always brackets the exact sorted-oracle value within one
    bucket boundary (a factor of 2). Histograms MERGE by bucket-wise
    addition — the property that makes per-node histograms summable
    cluster-wide (`/api/v1/latency/sum`) and across scrape intervals,
    which order statistics (raw percentiles) never are.

``Telemetry``
    The stage registry. The hot-path contract is near-zero overhead:

    - enabled: ONE ``perf_counter_ns()`` pair + one ``record()`` (a dict
      lookup, a bit_length, two int adds, one compare) per stage;
    - disabled: hot paths guard on ``tele.enabled`` so the cost is a
      single attribute load + branch — no timestamp is ever taken, no
      histogram is touched, no slow-log append happens (the acceptance
      bar for ``[observability] enable = false``).

    ``span()`` wraps the pair as a context manager — the API plugins and
    extensions should reach for when timing their own stages (the built-in
    hot paths inline the pair + ``recorder()`` instead, where the context
    manager's enter/exit dispatch would be measurable); when disabled it
    returns a shared no-op object.

slow-op ring
    A bounded ``deque`` capturing any nanosecond-stage op at or over
    ``slow_ms`` with op name, duration, timestamp and caller detail
    (topic, batch size, cache hit/miss) — the "what was that stall?"
    log that histograms by design cannot answer.

Stage names are pre-registered (``STAGES``) so every surface — JSON
endpoints, Prometheus, $SYS, the dashboard — is shape-stable whether or
not traffic (or telemetry itself) has happened yet.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional

from rmqtt_tpu.broker.tracing import CURRENT_TRACE

NBUCKETS = 40  # [2^0, 2^40) ns ≈ up to ~18 min; top bucket absorbs overflow

# canonical broker stages (unit: ns unless listed in UNITS)
STAGES = (
    "connect.handshake",   # accept → CONNACK sent (server.py)
    "publish.e2e",         # publish ingress → last forward enqueued (shared.py)
    "publish.cache_hit",   # match-cache hit path: lookup+derive+collapse
    "publish.cache_miss",  # miss path: full batcher round trip
    "routing.queue_wait",  # batcher ingress-queue park time per item
    "routing.match",       # per-dispatch backend match latency (batch)
    "routing.batch_size",  # dispatch batch-size distribution (count, not ns)
    "deliver.ack_rtt",     # QoS1/2 delivery → PUBACK/PUBCOMP round trip
    "kernel.dispatch",     # router kernel/trie match call (native/xla)
)

UNITS: Dict[str, str] = {"routing.batch_size": "count"}

# recorder buffer fold threshold: big enough to amortize the fold loop,
# small enough that a mid-burst fold stall is microseconds
_FOLD_AT = 512


def _slow_entry(name: str, dur_ns: int, detail: Any, trace: Any) -> dict:
    """One slow-op ring row (cold path — only built at/over ``slow_ms``).
    Falls back to the tracing contextvar so entries recorded in the
    publish-ingress task gain the active trace id (broker/tracing.py);
    cross-task recorders pass their trace explicitly."""
    if trace is None:
        trace = CURRENT_TRACE.get()
    entry = {
        "op": name,
        "ms": round(dur_ns / 1e6, 3),
        "ts": round(time.time(), 3),
        "detail": detail,
    }
    if trace is not None:
        entry["trace"] = trace.tid
    return entry


def prom_sanitize(name: str) -> str:
    """Exposition-format metric-name scrub: grammar allows [a-zA-Z0-9_:];
    metric keys here are dotted and plugin counters may carry arbitrary
    chars. Single definition shared by every exporter."""
    return re.sub(r"[^a-zA-Z0-9_]", "_", name)


class Histogram:
    """Fixed log2-bucket histogram; ns-resolution; mergeable by addition.

    ``count`` is DERIVED from the buckets on read: the recording paths run
    per publish, and one fewer read-modify-write per record is a measured
    win (bench cfg7); every read path is cold."""

    __slots__ = ("counts", "sum")

    def __init__(self) -> None:
        self.counts: List[int] = [0] * NBUCKETS
        self.sum = 0

    @property
    def count(self) -> int:
        return sum(self.counts)

    @staticmethod
    def bucket_index(value: int) -> int:
        if value <= 1:
            return 0
        return min(value.bit_length() - 1, NBUCKETS - 1)

    @staticmethod
    def bucket_upper(i: int) -> int:
        """Exclusive upper bound of bucket ``i`` (top bucket: +inf proxy)."""
        return 1 << (i + 1)

    def record(self, value: int) -> None:
        self.counts[self.bucket_index(value)] += 1
        self.sum += value

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-th sample (0 if empty).

        The exact q-th order statistic lies in the same bucket, so the
        estimate is exact-to-one-bucket: ``upper/2 <= exact < upper``
        (bucket 0: ``0 <= exact < 2``)."""
        total = self.count
        if total == 0:
            return 0.0
        rank = max(1, int(q * total + 0.999999))  # ceil, 1-based
        rank = min(rank, total)
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank:
                return float(self.bucket_upper(i))
        return float(self.bucket_upper(NBUCKETS - 1))

    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def merge(self, other: "Histogram") -> "Histogram":
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.sum += other.sum
        return self

    def to_json(self) -> dict:
        return {"count": self.count, "sum": self.sum, "buckets": list(self.counts)}

    @classmethod
    def from_json(cls, d: dict) -> "Histogram":
        h = cls()
        buckets = list(d.get("buckets", ()))[:NBUCKETS]
        h.counts[: len(buckets)] = [int(b) for b in buckets]
        h.sum = int(d.get("sum", 0))
        return h

    def snapshot(self, unit: str = "ns") -> dict:
        """JSON row for the admin surfaces: counts + quantile estimates in
        the recorded unit (callers convert ns → ms for display)."""
        return {
            "count": self.count,
            "sum": self.sum,
            "unit": unit,
            "mean": round(self.mean(), 1),
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
            "p999": self.quantile(0.999),
            "buckets": list(self.counts),
        }


class _Span:
    """Enabled-mode timer: one perf_counter_ns pair around the block."""

    __slots__ = ("_tele", "_name", "_detail", "_t0")

    def __init__(self, tele: "Telemetry", name: str, detail: Any) -> None:
        self._tele = tele
        self._name = name
        self._detail = detail

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> bool:
        self._tele.record(self._name, time.perf_counter_ns() - self._t0, self._detail)
        return False


class _NullSpan:
    """Disabled-mode span: never takes a timestamp."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NULL_SPAN = _NullSpan()


class Telemetry:
    """Per-node latency registry: stage histograms + the slow-op ring."""

    __slots__ = ("enabled", "slow_ms", "slow_ns", "slow_ops", "_h",
                 "_recorders", "_folds", "_reg_lock")

    def __init__(
        self,
        enabled: bool = True,
        slow_ms: float = 100.0,
        slow_log_max: int = 256,
        stages: Iterable[str] = STAGES,
    ) -> None:
        self.enabled = enabled
        self.slow_ms = slow_ms
        self.slow_ns = int(slow_ms * 1e6)
        self.slow_ops: deque = deque(maxlen=max(1, slow_log_max))
        self._h: Dict[str, Histogram] = {name: Histogram() for name in stages}
        self._recorders: Dict[str, Callable] = {}
        self._folds: Dict[str, Callable[[], None]] = {}
        # guards recorder CREATION (rare): first calls can come from
        # executor threads (kernel.dispatch), and an unlocked insert could
        # both race flush()'s iteration and build duplicate closures whose
        # buffered samples would never fold
        self._reg_lock = threading.Lock()

    def hist(self, name: str) -> Histogram:
        h = self._h.get(name)
        if h is None:
            h = self._h[name] = Histogram()
        return h

    def record(self, name: str, dur_ns: int, detail: Any = None,
               trace: Any = None) -> None:
        """Record one op. Callers on hot paths guard with ``self.enabled``
        (so the disabled cost is one branch); the guard here keeps
        un-guarded callers correct, not fast. The histogram update is
        inlined (not ``hist().record()``) — this runs several times per
        publish and the two extra method dispatches measurably widen the
        telemetry-on overhead (bench cfg7)."""
        if not self.enabled:
            return
        try:
            h = self._h[name]
        except KeyError:
            h = self._h[name] = Histogram()
        i = dur_ns.bit_length() - 1
        if i < 0:
            i = 0
        elif i >= NBUCKETS:
            i = NBUCKETS - 1
        h.counts[i] += 1
        h.sum += dur_ns
        # non-ns stages (batch size) are not durations: never slow-log
        if dur_ns >= self.slow_ns and name not in UNITS:
            self.slow_ops.append(_slow_entry(name, dur_ns, detail, trace))

    def span(self, name: str, detail: Any = None):
        """Context-manager timer; a shared no-op when disabled."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name, detail)

    def recorder(self, name: str) -> Callable[[int, Any], None]:
        """A per-stage fast-path recorder closure (memoized per stage).

        ``record()`` pays a name lookup + several attribute loads + the
        histogram update per call; at publish rates that is the single
        biggest telemetry cost (bench cfg7). The recorder instead buffers
        the raw duration with one C-level ``deque.append`` and folds the
        buffer into the histogram AMORTIZED — every ``_FOLD_AT`` ops or on
        the next read (``flush()``) — so the per-op cost is an append, one
        slow-threshold compare (slow ops keep their true timestamps and
        details, checked eagerly), and a length check. Totals stay exact:
        folding only defers the bucket increments, it never drops them.
        When disabled this returns a shared no-op so un-guarded calls
        stay correct."""
        rec = self._recorders.get(name)  # lock-free fast path (dict get)
        if rec is not None:
            return rec
        with self._reg_lock:
            return self._make_recorder(name)

    def _make_recorder(self, name: str) -> Callable[[int, Any], None]:
        rec = self._recorders.get(name)  # re-check under the lock
        if rec is not None:
            return rec
        if not self.enabled:
            rec = self._recorders[name] = (
                lambda dur_ns, detail=None, trace=None: None)
            return rec
        h = self.hist(name)
        counts = h.counts
        slow_ns = self.slow_ns
        slow_ops = self.slow_ops
        is_ns = name not in UNITS
        top = NBUCKETS - 1
        pending: deque = deque()
        append = pending.append
        popleft = pending.popleft
        fold_lock = threading.Lock()

        def fold() -> None:
            # executor threads record concurrently with the loop (kernel
            # dispatch runs off-loop): the hot append is GIL-atomic, and
            # the lock serializes the bucket/sum read-modify-writes so a
            # concurrent double-fold can't lose increments — totals stay
            # exact. Cold: taken every _FOLD_AT ops or per read.
            with fold_lock:
                s = 0
                while True:
                    try:
                        v = popleft()
                    except IndexError:
                        break
                    i = v.bit_length() - 1
                    counts[0 if i < 0 else (top if i > top else i)] += 1
                    s += v
                h.sum += s

        self._folds[name] = fold

        def rec(dur_ns: int, detail: Any = None, trace: Any = None) -> None:
            append(dur_ns)
            if dur_ns >= slow_ns and is_ns:
                slow_ops.append(_slow_entry(name, dur_ns, detail, trace))
            if len(pending) >= _FOLD_AT:
                fold()

        self._recorders[name] = rec
        return rec

    def flush(self) -> None:
        """Fold every recorder's pending samples into its histogram; all
        read paths call this, so readers always see exact totals. The
        list() snapshot keeps a concurrent first-recorder registration
        (executor thread) from invalidating the iteration."""
        for fold in list(self._folds.values()):
            fold()

    # ------------------------------------------------------------- surfaces
    def p_ms(self, name: str, q: float) -> float:
        """Quantile of a ns-stage in milliseconds (admin/stat gauges)."""
        self.flush()
        return round(self.hist(name).quantile(q) / 1e6, 3)

    def snapshot(self) -> dict:
        """The `/api/v1/latency` body: shape-stable in disabled mode (all
        pre-registered stages present with zero counts, empty slow log)."""
        self.flush()
        return {
            "enabled": self.enabled,
            "slow_threshold_ms": self.slow_ms,
            "histograms": {
                name: h.snapshot(UNITS.get(name, "ns"))
                for name, h in sorted(list(self._h.items()))
            },
            "slow_ops": list(self.slow_ops),
        }

    @staticmethod
    def merge_snapshots(base: dict, others: Iterable[dict]) -> dict:
        """Cluster-wide merge (`/api/v1/latency/sum`): bucket-wise addition
        of each node's histograms — the whole point of fixed buckets."""
        others = list(others)
        merged: Dict[str, Histogram] = {}
        units: Dict[str, str] = {}
        for snap in [base, *others]:
            for name, row in (snap.get("histograms") or {}).items():
                units.setdefault(name, row.get("unit", "ns"))
                h = merged.get(name)
                if h is None:
                    merged[name] = Histogram.from_json(row)
                else:
                    h.merge(Histogram.from_json(row))
        return {
            "nodes": 1 + len(others),
            "enabled": bool(base.get("enabled", False)),
            "histograms": {
                name: h.snapshot(units.get(name, "ns"))
                for name, h in sorted(merged.items())
            },
        }

    def prometheus_lines(self, labels: str) -> List[str]:
        """Exposition-format histogram families. ``labels`` is the shared
        label body (e.g. ``node="1"``). ns stages export in SECONDS (the
        Prometheus base-unit convention) as ``rmqtt_latency_<stage>_seconds``;
        count stages export raw as ``rmqtt_<stage>``."""
        self.flush()
        out: List[str] = []
        for name, h in sorted(self._h.items()):
            unit = UNITS.get(name, "ns")
            safe = prom_sanitize(name)
            if unit == "ns":
                metric = f"rmqtt_latency_{safe}_seconds"
                scale = 1e-9
            else:
                metric = f"rmqtt_{safe}"
                scale = 1.0
            out.append(f"# TYPE {metric} histogram")
            acc = 0
            for i, c in enumerate(h.counts):
                acc += c
                # exposition `le` is INCLUSIVE; our buckets have exclusive
                # uppers, so bucket i's inclusive max is upper-1 (a
                # boundary-exact sample — e.g. a 64-item batch — belongs
                # to the next bucket and must not be claimed by this le)
                le = format((h.bucket_upper(i) - 1) * scale, "g")
                out.append(f'{metric}_bucket{{{labels},le="{le}"}} {acc}')
            out.append(f'{metric}_bucket{{{labels},le="+Inf"}} {h.count}')
            out.append(f"{metric}_sum{{{labels}}} {format(h.sum * scale, 'g')}")
            out.append(f"{metric}_count{{{labels}}} {h.count}")
        return out


# module-level disabled singleton: subsystems constructed without a broker
# context (bare RoutingService in unit tests, standalone routers) share one
# no-op registry instead of None-checking on the hot path
NULL_TELEMETRY = Telemetry(enabled=False, slow_log_max=1)
