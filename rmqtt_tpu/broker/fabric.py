"""Intra-node routing fabric: one device-table owner per node, UDS mesh.

``--workers N`` used to peer the SO_REUSEPORT workers as a localhost
*broadcast cluster*: every cross-worker publish paid full cluster-RPC
serialization against every peer (an O(workers) scatter-gather match), and
every CONNECT paid an O(workers) kick scatter. This module replaces that
with a node-local fabric:

- **Router owner.** One worker (``fabric.owner_id``, worker 1 by default)
  holds the node's single authoritative device table: every worker forwards
  its subscription mutations to the owner, so the owner's router — and only
  the owner's — sees the union. Publishes are *submitted* to the owner in
  batches over a per-worker UDS link; the owner runs match once per batch on
  the shared device plane (through its normal ``RoutingService``, so the
  match cache, micro-batcher and pipelined device dispatch all apply) and
  returns per-worker fan-out plans.

- **UDS mesh, length-prefixed frames.** Every worker listens on
  ``<fabric.dir>/fabric-<wid>.sock``; links are lazy outbound connections
  carrying ``cluster/wire.py``-encoded frames (4-byte BE length prefix) with
  optional correlation ids — the wire primitives without the full cluster
  RPC stack (no breakers, no membership; a dead link IS a dead worker and
  the supervisor's problem).

- **Zero-copy fan-out.** The submitting worker delivers its own slice of
  the plan locally and writes one ``deliver`` frame per remote worker:
  message + relations + the per-(version, retain) QoS0 wire frames already
  encoded for the plan's subscriber population. Receivers seed each
  ``DeliverItem``'s shared ``wire_cache`` with those bytes, so a
  10K-subscriber fan-out encodes each (version, flags) frame once
  node-wide and peer workers write bytes, not re-encoded Message objects.

- **Subscription directory.** The owner maintains ``client_id →
  (worker, online, protocol)`` and replicates it to workers as compact
  epoch-tagged deltas over the same links. CONNECT-time kicks become O(1):
  a directory miss is *no RPC at all* (the common case — a fresh client),
  a hit is one targeted kick to the owning worker. The directory also
  backs the owner router's shared-subscription liveness.

- **Fault handling.** Workers detect owner death on the UDS link; submits
  park (bounded by ``submit_deadline_s``) while a keeper reconnects with
  backoff, then **re-register** — full session/subscription/retained state
  — so a respawned owner rebuilds the table and directory from worker
  replicas. Past the deadline a publish degrades to worker-local match
  (reason-counted) instead of stalling forever. The ``fabric.submit``
  failpoint injects exactly this seam for chaos drills.

Without ``[fabric] enable``, none of this is constructed and ``--workers``
behaves exactly as before (localhost broadcast cluster) — pinned by test.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from rmqtt_tpu.broker.hooks import HookType
from rmqtt_tpu.broker.session import DeliverItem, restore_session, session_snapshot
from rmqtt_tpu.broker.shared import SessionRegistry
from rmqtt_tpu.broker.tracing import CURRENT_TRACE
from rmqtt_tpu.broker.types import Message
from rmqtt_tpu.cluster import wire
from rmqtt_tpu.cluster.messages import (
    msg_from_wire,
    msg_to_wire,
    opts_from_wire,
    opts_to_wire,
    relation_from_wire,
    relation_to_wire,
)
from rmqtt_tpu.router.base import Id, SubRelation
from rmqtt_tpu.utils.failpoints import FAILPOINTS, FailpointError

log = logging.getLogger("rmqtt_tpu.fabric")

#: chaos seam (utils/failpoints.py): fires on every publish submission to
#: the router owner — an injected error degrades that publish to
#: worker-local match, exactly like an owner outage past the deadline
_FP_SUBMIT = FAILPOINTS.register("fabric.submit")

# frame vocabulary (all frames: {"t": type, "b": body, "corr"?: int})
F_REGISTER = "register"  # worker → owner: full state (sessions/subs/retains)
F_ATTACH = "attach"      # worker → owner: session connected here
F_DETACH = "detach"      # worker → owner: session terminated here
F_ONLINE = "online"      # worker → owner: online-flag flip (durable offline)
F_SUB_ADD = "sub_add"    # worker → owner: subscription added
F_SUB_DEL = "sub_del"    # worker → owner: subscription removed
F_SUBMIT = "submit"      # worker → owner: publish batch → fan-out plans
F_DELIVER = "deliver"    # worker → worker: message + rels + QoS0 frames
F_KICK = "kick"          # worker → worker: targeted takeover kick
F_DIR = "dir"            # owner → worker: epoch-tagged directory delta
F_DIR_SYNC = "dir_sync"  # worker → owner: full directory pull (gap repair)
F_RETAIN = "retain"      # retained set/clear replication (owner relays)
F_GEN = "gen"            # owner → worker: table-generation bump (plan cache)


class FabricUnavailable(ConnectionError):
    """The owner link is down (or the ``fabric.submit`` failpoint fired)
    and the bounded wait expired: the caller degrades to local-only
    routing for this publish."""


class _Link:
    """One lazy outbound UDS connection to a peer worker.

    ``call`` (correlation id + timeout) and ``notify`` (fire-and-forget),
    like the cluster ``PeerClient`` but without the breaker/backoff
    machinery: fabric links are node-local — a connect failure means the
    peer process is dead, which the supervisor handles. Frames arriving
    WITHOUT a correlation id are owner→worker pushes (directory deltas,
    retain replication) and dispatch into ``handler``."""

    def __init__(self, fabric: "FabricService", wid: int, path: str) -> None:
        self.fabric = fabric
        self.wid = wid
        self.path = path
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._corr = itertools.count(1)
        self._wlock = asyncio.Lock()
        self._connect_lock = asyncio.Lock()

    @property
    def connected(self) -> bool:
        return self._writer is not None

    async def _ensure(self) -> None:
        if self._writer is not None:
            return
        # serialize: concurrent senders on a fresh link (keeper register vs
        # an attach, kick vs deliver flush) must not open duplicate
        # connections — the loser's orphaned read-loop would later tear
        # down the healthy winner
        async with self._connect_lock:
            if self._writer is not None:
                return
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_unix_connection(self.path),
                    self.fabric.call_timeout)
            except (OSError, asyncio.TimeoutError) as e:
                raise FabricUnavailable(
                    f"fabric worker {self.wid} unreachable: {e}") from e
            self._writer = writer
            self._reader_task = asyncio.get_running_loop().create_task(
                self._read_loop(reader))

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                frame = await wire.read_frame(reader)
                corr = frame.get("corr")
                if corr is not None and "reply" in frame:
                    fut = self._pending.pop(corr, None)
                    if fut is not None and not fut.done():
                        fut.set_result(frame["reply"])
                    continue
                # owner → worker push riding the worker-initiated link
                self.fabric._dispatch_push(frame)
        except (ConnectionError, asyncio.IncompleteReadError, ValueError):
            pass
        finally:
            self.teardown(ConnectionError("fabric link lost"))
            self.fabric._on_link_down(self.wid)

    def teardown(self, exc: Exception) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None
        for fut in self._pending.values():
            if not fut.done():
                try:
                    fut.set_exception(FabricUnavailable(str(exc)))
                except RuntimeError:
                    pass  # event loop already closed (interpreter teardown)
        self._pending.clear()

    async def close(self) -> None:
        task, self._reader_task = self._reader_task, None
        if task is not None:
            task.cancel()
        self.teardown(ConnectionError("closed"))
        if task is not None:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass

    async def _send(self, obj: dict) -> None:
        await self._ensure()
        data = wire.frame(obj)
        try:
            async with self._wlock:
                writer = self._writer  # a concurrent teardown may None it
                if writer is None:
                    raise FabricUnavailable(
                        f"fabric link to worker {self.wid} lost")
                writer.write(data)
                await writer.drain()
        except (OSError, ConnectionError) as e:
            self.teardown(e)
            raise FabricUnavailable(str(e)) from e
        self.fabric.bytes_out += len(data)

    async def notify(self, mtype: str, body: Any = None) -> None:
        await self._send({"t": mtype, "b": body})

    async def call(self, mtype: str, body: Any = None,
                   timeout: Optional[float] = None) -> Any:
        corr = next(self._corr)
        fut = asyncio.get_running_loop().create_future()
        self._pending[corr] = fut
        try:
            await self._send({"t": mtype, "b": body, "corr": corr})
            reply = await asyncio.wait_for(
                fut, timeout or self.fabric.call_timeout)
            if isinstance(reply, dict) and "__err" in reply:
                raise FabricUnavailable(reply["__err"])
            return reply
        except asyncio.TimeoutError as e:
            raise FabricUnavailable(
                f"fabric call {mtype} to worker {self.wid} timed out") from e
        finally:
            self._pending.pop(corr, None)


class FabricService:
    """Per-worker fabric runtime: the UDS server + link table, plus the
    owner's table/directory state or the worker's replica/submit queue."""

    def __init__(self, ctx, cfg) -> None:
        self.ctx = ctx
        self.worker_id = int(cfg.fabric_worker_id or cfg.node_id)
        self.owner_id = int(cfg.fabric_owner_id)
        self.sock_dir = cfg.fabric_dir
        self.is_owner = self.worker_id == self.owner_id
        self.batch_max = max(1, int(cfg.fabric_batch_max))
        self.call_timeout = float(cfg.fabric_call_timeout_s)
        self.submit_deadline = float(cfg.fabric_submit_deadline_s)
        self.expected_workers = int(cfg.fabric_workers)
        self.warm_grace = float(cfg.fabric_warm_grace_s)
        self.running = False
        self._server = None
        self._links: Dict[int, _Link] = {}
        # ---- counters (RoutingService.stats() → every admin surface)
        self.batches = 0          # submit batches (client: sent; owner: served)
        self.items = 0            # publishes through submit batches
        self.bytes_out = 0        # bytes written on fabric links
        self.deliver_out = 0      # deliver frames sent to peers
        self.deliver_in = 0       # deliver frames received
        self.kicks_o1 = 0         # CONNECTs whose kick resolved via directory
        self.kick_rpcs = 0        # of those, targeted kick RPCs (≤1 each)
        self.plan_hits = 0        # publishes served from the worker plan cache
        self.owner_reconnects = 0
        self.submit_fallbacks = 0  # publishes degraded to local-only match
        self.submit_ms_total = 0.0   # client-side submit→plan wall
        self.fanout_ms_total = 0.0   # client-side remote deliver-frame wall
        # ---- owner state
        self.directory: Dict[str, list] = {}  # cid → [wid, online, ver]
        self.dir_epoch = 0
        self._worker_subs: Dict[int, set] = {}   # wid → {(tf, cid)}
        self._worker_conns: Dict[int, tuple] = {}  # wid → (writer, wlock)
        # cid → live subscription count in the owner table: directory ops
        # for a subscription-LESS client (the bulk of a connect storm)
        # cannot change any fan-out plan, so they must not invalidate the
        # node's plan caches (_dir_mutate consults this before bumping)
        self._cid_subs: Dict[str, int] = {}
        # ---- owner table generation: bumped on every SUBSCRIPTION-TABLE
        # mutation (sub add/remove, register, purge) and on directory ops
        # touching clients that hold subscriptions, then pushed to workers
        # — the validity stamp of worker plan caches
        self.table_gen = 0
        # ---- worker state
        self.replica: Dict[str, list] = {}
        self.replica_epoch = 0
        # worker-side fan-out PLAN cache (the match-cache discipline at the
        # fabric seam): a plan the owner marked cacheable (no shared-group
        # choice involved) is reused for repeat (topic, publisher, qos,
        # retain) publishes while the owner's table generation is unchanged
        # — hot cross-worker publishes then pay ZERO submit RPCs node-wide.
        # Any table/directory mutation bumps the generation (pushed as
        # F_GEN / riding dir deltas and submit replies), invalidating every
        # cached plan at once — coarse, but stale serves are bounded by one
        # push latency, never by a TTL.
        self.remote_gen = -1  # unknown until the first owner contact
        self._plan_cache: Dict[tuple, tuple] = {}  # key → (gen, plan)
        self._owner_link: Optional[_Link] = None
        self._owner_up = asyncio.Event()
        self._keeper: Optional[asyncio.Task] = None
        self._submit_task: Optional[asyncio.Task] = None
        self._submit_q: list = []  # [(fut, item, deadline_monotonic)]
        self._submit_evt = asyncio.Event()
        # pipelined submission (the RoutingService pipeline_depth idea at
        # the fabric seam): up to 4 submit batches in flight to the owner,
        # so sustained throughput is not capped at batch_max per UDS RTT
        self._submit_sem = asyncio.Semaphore(4)
        self._bg: set = set()
        self._conns: set = set()  # inbound writers (closed on stop)
        # deliver coalescing: concurrent publishes targeting the same peer
        # worker merge into ONE frame per flush cycle (the deliver-side
        # analogue of submit batching — frame overhead amortizes across a
        # burst instead of costing one frame per publish per worker)
        self._dq: Dict[int, list] = {}
        self._dq_evt = asyncio.Event()
        self._deliver_task: Optional[asyncio.Task] = None
        # owner warm-up gate: a (re)spawned owner must not plan fan-outs
        # against a table still missing workers' re-registrations — early
        # submits would be acked yet silently skip their subscribers. The
        # gate opens when every expected worker has registered, or after
        # warm_grace seconds (a permanently-dead worker must not stall the
        # node forever).
        self._warm = asyncio.Event()

    # ------------------------------------------------------------ lifecycle
    def sock_path(self, wid: int) -> str:
        return os.path.join(self.sock_dir, f"fabric-{wid}.sock")

    async def start(self) -> None:
        os.makedirs(self.sock_dir, exist_ok=True)
        path = self.sock_path(self.worker_id)
        try:
            os.unlink(path)  # stale socket from a previous incarnation
        except FileNotFoundError:
            pass
        self._server = await asyncio.start_unix_server(self._on_conn, path)
        self.running = True
        self._deliver_task = asyncio.get_running_loop().create_task(
            self._deliver_flush_loop())
        if self.is_owner:
            self._wrap_online()
            self._owner_up.set()
            if self.expected_workers <= 1:
                self._warm.set()
            else:
                self._spawn(self._warm_grace_timer())
        else:
            self._owner_link = _Link(self, self.owner_id,
                                     self.sock_path(self.owner_id))
            self._keeper = asyncio.get_running_loop().create_task(
                self._owner_keeper())
            self._submit_task = asyncio.get_running_loop().create_task(
                self._submit_loop())
        # retained replication: every local retain set/clear crosses the
        # fabric (owner applies + relays), so subscribe-time replay works
        # on whichever worker a client lands on
        self.ctx.retain.on_set = self._on_retain_set
        # durable sessions going offline flip the directory online flag so
        # the owner's shared-subscription liveness stays honest node-wide
        self.ctx.hooks.register(
            HookType.CLIENT_DISCONNECTED, self._on_client_disconnected)
        log.info("fabric worker %s%s listening on %s", self.worker_id,
                 " (owner)" if self.is_owner else "", path)

    async def stop(self) -> None:
        self.running = False
        for t in (self._keeper, self._submit_task, self._deliver_task):
            if t is not None:
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
        self._keeper = self._submit_task = self._deliver_task = None
        for fut, _item, _dl in self._submit_q:
            if not fut.done():
                fut.set_exception(FabricUnavailable("fabric stopped"))
        self._submit_q.clear()
        if self._owner_link is not None:
            await self._owner_link.close()
        for link in self._links.values():
            await link.close()
        self._links.clear()
        for t in list(self._bg):
            t.cancel()
        if self._server is not None:
            self._server.close()
            # close live inbound links too: peers must see EOF NOW (their
            # owner-down detection), and py3.12 wait_closed would otherwise
            # wait on connection handlers that serve forever
            for w in list(self._conns):
                try:
                    w.close()
                except Exception:
                    pass
            await self._server.wait_closed()
            self._server = None
        try:
            os.unlink(self.sock_path(self.worker_id))
        except OSError:
            pass

    def _spawn(self, coro) -> None:
        task = asyncio.get_running_loop().create_task(coro)
        self._bg.add(task)
        task.add_done_callback(self._bg.discard)

    async def _warm_grace_timer(self) -> None:
        await asyncio.sleep(self.warm_grace)
        if not self._warm.is_set():
            log.warning(
                "fabric owner warm-up grace expired with %d/%d workers "
                "registered; serving submits anyway",
                len(self._worker_conns), max(0, self.expected_workers - 1))
            self._warm.set()

    async def warm_wait(self) -> None:
        """Block until the owner's table covers every expected worker (or
        the grace expired) — one event check once warm."""
        if not self._warm.is_set():
            await self._warm.wait()

    def _wrap_online(self) -> None:
        """Owner: liveness for remote workers' clients comes from the
        directory, not the local registry (the cache's captured closure is
        re-pointed too — it was bound at ServerContext construction)."""
        router = self.ctx.router
        orig = getattr(router, "_is_online", lambda cid: True)

        def online(cid: str) -> bool:
            ent = self.directory.get(cid)
            if ent is not None:
                return bool(ent[1])
            return orig(cid)

        router._is_online = online
        cache = getattr(self.ctx.routing, "cache", None)
        if cache is not None:
            cache._is_online = online

    # ------------------------------------------------------- link plumbing
    def link(self, wid: int) -> _Link:
        if wid == self.owner_id and self._owner_link is not None:
            return self._owner_link
        link = self._links.get(wid)
        if link is None:
            link = self._links[wid] = _Link(self, wid, self.sock_path(wid))
        return link

    def _on_link_down(self, wid: int) -> None:
        if wid == self.owner_id and not self.is_owner and self.running:
            self._owner_up.clear()

    def _dispatch_push(self, frame: dict) -> None:
        """A push frame (no reply expected) arriving on an outbound link."""
        self._spawn(self._handle(frame.get("t"), frame.get("b"), None))

    async def _on_conn(self, reader, writer) -> None:
        from types import SimpleNamespace

        # handler context: the inbound push channel + (after a REGISTER
        # frame) the connected worker's identity
        conn = SimpleNamespace(writer=writer, wlock=asyncio.Lock(), wid=None)
        self._conns.add(writer)
        pending: set = set()

        async def dispatch(frame: dict) -> None:
            mtype, body, corr = frame.get("t"), frame.get("b"), frame.get("corr")
            try:
                reply = await self._handle(mtype, body, conn)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.exception("fabric handler error for %s", mtype)
                reply = {"__err": f"{type(e).__name__}: {e}"}
            if corr is not None:
                try:
                    data = wire.frame({"corr": corr, "reply": reply})
                    async with conn.wlock:
                        writer.write(data)
                        await writer.drain()
                    self.bytes_out += len(data)
                except (ConnectionError, OSError):
                    pass

        try:
            while True:
                frame = await wire.read_frame(reader)
                task = asyncio.get_running_loop().create_task(dispatch(frame))
                pending.add(task)
                task.add_done_callback(pending.discard)
        except (ConnectionError, asyncio.IncompleteReadError, ValueError):
            pass
        finally:
            self._conns.discard(writer)
            for t in pending:
                t.cancel()
            if conn.wid is not None and self.is_owner:
                # the worker's register link died: that worker is gone —
                # purge its table slice and directory entries so matches
                # stop planning deliveries into a dead process
                if self._worker_conns.get(conn.wid, (None,))[0] is writer:
                    del self._worker_conns[conn.wid]
                    self._purge_worker(conn.wid)
            try:
                writer.close()
            except Exception:
                pass

    # --------------------------------------------------------- worker side
    async def _owner_keeper(self) -> None:
        """Keep the owner link registered: (re)connect with backoff, replay
        full local state, seed the directory replica, release submits."""
        backoff = 0.05
        while True:
            if self._owner_up.is_set():
                await asyncio.sleep(0.2)
                continue
            try:
                reply = await self._owner_link.call(
                    F_REGISTER, self._register_body(), timeout=self.call_timeout)
                self.replica = {cid: list(ent) for cid, ent in
                                (reply.get("directory") or {}).items()}
                self.replica_epoch = int(reply.get("epoch", 0))
                self._observe_gen(reply.get("gen"))
                for topic, mw in reply.get("retains", []):
                    self._merge_retain(topic, mw)
                self.owner_reconnects += 1
                self._owner_up.set()
                self._submit_evt.set()
                backoff = 0.05
                log.info("fabric worker %s registered with owner (epoch %s)",
                         self.worker_id, self.replica_epoch)
            except (FabricUnavailable, OSError):
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 1.0)

    def _register_body(self) -> dict:
        from rmqtt_tpu.core.topic import strip_prefixes

        sessions, subs = [], []
        for s in self.ctx.registry.sessions():
            sessions.append([s.client_id, bool(s.connected),
                             int(s.connect_info.protocol)])
            for full_filter, opts in s.subscriptions.items():
                try:
                    stripped = strip_prefixes(full_filter)
                except Exception:
                    stripped = full_filter
                subs.append([stripped, s.client_id, opts_to_wire(opts)])
        retains = [[t, msg_to_wire(m)] for t, m in self.ctx.retain.all_items()]
        return {"wid": self.worker_id, "sessions": sessions, "subs": subs,
                "retains": retains}

    async def submit_publish(self, msg: Message) -> dict:
        """Queue one publish for batched submission to the owner; returns
        the decoded fan-out plan. Raises :class:`FabricUnavailable` when
        the owner stayed unreachable past ``submit_deadline_s`` (or the
        ``fabric.submit`` failpoint is armed)."""
        if _FP_SUBMIT.action is not None:
            try:
                await _FP_SUBMIT.fire_async()
            except FailpointError as e:
                raise FabricUnavailable(str(e)) from e
        fid = msg.from_id
        cid = fid.client_id if fid else ""
        key = (msg.topic, cid, int(msg.qos), bool(msg.retain))
        ent = self._plan_cache.get(key)
        if ent is not None and ent[0] == self.remote_gen:
            # hot path: the owner's plan for this (topic, publisher) is
            # still valid under the current table generation — zero RPCs
            self.plan_hits += 1
            return ent[1]
        item = [fid.node_id if fid else self.worker_id, cid, msg.topic,
                int(msg.qos), bool(msg.retain)]
        fut = asyncio.get_running_loop().create_future()
        self._submit_q.append(
            (fut, item, time.monotonic() + self.submit_deadline))
        self._submit_evt.set()
        plan = await fut
        if plan.get("c") and plan.get("_gen") == self.remote_gen:
            self._plan_cache[key] = (plan["_gen"], plan)
        return plan

    async def _submit_loop(self) -> None:
        while True:
            await self._submit_evt.wait()
            if not self._submit_q:
                self._submit_evt.clear()
                continue
            if not self._owner_up.is_set():
                # owner down: park until the keeper re-registers, bounded
                # by the OLDEST queued item's deadline — then degrade
                timeout = self._submit_q[0][2] - time.monotonic()
                if timeout > 0:
                    try:
                        await asyncio.wait_for(self._owner_up.wait(), timeout)
                    except asyncio.TimeoutError:
                        pass
                if not self._owner_up.is_set():
                    now = time.monotonic()
                    keep = []
                    for fut, item, dl in self._submit_q:
                        if dl <= now:
                            if not fut.done():
                                fut.set_exception(FabricUnavailable(
                                    "router owner unreachable"))
                        else:
                            keep.append((fut, item, dl))
                    self._submit_q[:] = keep
                    continue
            batch, self._submit_q[:] = (self._submit_q[:self.batch_max],
                                        self._submit_q[self.batch_max:])
            await self._submit_sem.acquire()
            self._spawn(self._submit_one(batch))

    async def _submit_one(self, batch: list) -> None:
        t0 = time.perf_counter()
        try:
            # a (re)spawned owner may legitimately HOLD submits behind its
            # warm-up gate for up to warm_grace seconds — the call timeout
            # must cover that, or every timeout triggers a spurious full
            # re-register storm during recovery
            reply = await self._owner_link.call(
                F_SUBMIT, {"items": [it for _f, it, _d in batch]},
                timeout=self.call_timeout + self.warm_grace)
        except FabricUnavailable:
            self._owner_up.clear()
            self._submit_q[:0] = batch  # retry after re-register
            self._submit_evt.set()
            return
        finally:
            self._submit_sem.release()
        self.submit_ms_total += (time.perf_counter() - t0) * 1e3
        self.batches += 1
        self.items += len(batch)
        self._observe_gen(reply.get("gen"))
        gen = reply.get("gen")
        plans = reply.get("plans") or []
        for (fut, _item, _dl), plan in zip(batch, plans):
            if fut.done():
                continue
            if "err" in plan:
                fut.set_exception(FabricUnavailable(plan["err"]))
            else:
                plan["_gen"] = gen  # plan-cache validity stamp
                fut.set_result(plan)
        for fut, _item, _dl in batch[len(plans):]:
            if not fut.done():
                fut.set_exception(FabricUnavailable("short plan reply"))

    # ---------------------------------------------------------- owner side
    def _dir_mutate(self, ops: List[list]) -> None:
        """Apply directory ops locally and push one epoch-tagged delta to
        every registered worker. Op row: [cid, wid_or_None, online, ver].

        The table generation only bumps when an op touches a client that
        HOLDS subscriptions (its ver/online/worker feed plan frame specs
        and shared liveness); attach/detach of subscription-less clients —
        the bulk of a connect storm — leave every worker's plan cache
        intact."""
        for cid, wid, online, ver in ops:
            if wid is None:
                self.directory.pop(cid, None)
            else:
                self.directory[cid] = [wid, bool(online), int(ver)]
        if any(self._cid_subs.get(op[0], 0) > 0 for op in ops):
            self.table_gen += 1
        prev = self.dir_epoch
        self.dir_epoch += 1
        body = {"prev": prev, "epoch": self.dir_epoch, "ops": ops,
                "gen": self.table_gen}
        for wid, (writer, wlock) in list(self._worker_conns.items()):
            self._spawn(self._push(wid, writer, wlock, F_DIR, body))

    def _bump_gen(self) -> None:
        """Table mutation outside a directory delta (sub add/remove,
        register, purge): invalidate every worker's plan cache NOW."""
        self.table_gen += 1
        body = {"gen": self.table_gen}
        for wid, (writer, wlock) in list(self._worker_conns.items()):
            self._spawn(self._push(wid, writer, wlock, F_GEN, body))

    def _observe_gen(self, gen) -> None:
        """Worker: adopt a newer table generation (cached plans stamped
        with an older one stop serving instantly — the stamp check)."""
        if gen is not None and int(gen) > self.remote_gen:
            self.remote_gen = int(gen)
            if len(self._plan_cache) > 8192:
                self._plan_cache.clear()  # bound memory across many gens

    async def _push(self, wid: int, writer, wlock, mtype: str, body) -> None:
        try:
            data = wire.frame({"t": mtype, "b": body})
            async with wlock:
                writer.write(data)
                await writer.drain()
            self.bytes_out += len(data)
        except (ConnectionError, OSError):
            log.warning("fabric push %s to worker %s failed", mtype, wid)

    def _purge_worker(self, wid: int) -> None:
        self._bump_gen()
        router = self.ctx.router
        for tf, cid in self._worker_subs.pop(wid, set()):
            try:
                router.remove(tf, Id(wid, cid))
            except Exception:
                pass
            self._cid_subs_add(cid, -1)
        ops = [[cid, None, False, 0] for cid, ent in self.directory.items()
               if ent[0] == wid]
        if ops:
            self._dir_mutate(ops)
        log.info("fabric owner purged worker %s (%d sessions)", wid, len(ops))

    def _apply_register(self, body: dict, conn) -> dict:
        wid = int(body["wid"])
        self._bump_gen()
        router = self.ctx.router
        # replace any previous incarnation's state wholesale
        if wid in self._worker_conns:
            self._worker_conns.pop(wid, None)
        self._purge_worker(wid)
        subs = set()
        for tf, cid, ow in body.get("subs", []):
            router.add(tf, Id(wid, cid), opts_from_wire(ow))
            subs.add((tf, cid))
            self._cid_subs_add(cid)
        self._worker_subs[wid] = subs
        ops = [[cid, wid, online, ver]
               for cid, online, ver in body.get("sessions", [])]
        if conn is not None:
            conn.wid = wid
            self._worker_conns[wid] = (conn.writer, conn.wlock)
        if len(self._worker_conns) >= self.expected_workers - 1:
            self._warm.set()
        if ops:
            self._dir_mutate(ops)
        for topic, mw in body.get("retains", []):
            self._merge_retain(topic, mw, relay_from=wid)
        return {
            "epoch": self.dir_epoch,
            "gen": self.table_gen,
            "directory": {cid: list(ent)
                          for cid, ent in self.directory.items()},
            "retains": [[t, msg_to_wire(m)]
                        for t, m in self.ctx.retain.all_items()],
        }

    def partition_plan(self, relmap, qos: int, retain: bool,
                       local_wid: int) -> Tuple[List[SubRelation], dict, list]:
        """Split a collapsed relation map into (local rels, {wid: [rel
        wire]}, needed QoS0 frame specs). Relations carry their owning
        worker in ``Id.node_id`` (that is the id each worker registers
        under), so partitioning needs no directory lookups."""
        local: List[SubRelation] = []
        remote: Dict[int, list] = {}
        specs = set()
        for node_id, rels in relmap.items():
            for rel in rels:
                wid = rel.id.node_id
                if wid == local_wid:
                    local.append(rel)
                    continue
                remote.setdefault(wid, []).append(relation_to_wire(rel))
                if (min(rel.opts.qos, qos) == 0
                        and not rel.opts.subscription_ids):
                    ent = self.directory.get(rel.id.client_id)
                    ver = ent[2] if ent else 4
                    specs.add((ver, retain and rel.opts.retain_as_published))
        return local, remote, [[v, r] for v, r in specs]

    async def _plan_items(self, items: List[list]) -> List[dict]:
        """Owner: match a submitted batch once on the shared device plane
        and return per-worker fan-out plans. Items run concurrently so the
        owner's RoutingService batcher coalesces them into real device
        batches (one match per batch node-wide)."""
        routing = self.ctx.routing
        router = self.ctx.router

        async def one(item):
            node, cid, topic, qos, retain = item
            from_id = Id(int(node), cid) if cid else None
            raw = await routing.matches_raw(from_id, topic)
            # shared-group choice is per publish (round robin): a plan
            # that involved one must never be reused from a worker cache
            cacheable = not raw[1]
            relmap = router.collapse(raw)
            _local, remote, specs = self.partition_plan(
                relmap, int(qos), bool(retain), local_wid=int(node))
            # the submitter's own slice rides under its wid so one loop on
            # the far side delivers everything (local + remote view)
            if _local:
                remote[int(node)] = [relation_to_wire(r) for r in _local]
            plan = {"rels": remote, "fspecs": specs}
            if cacheable:
                plan["c"] = 1
            return plan

        results = await asyncio.gather(
            *(one(it) for it in items), return_exceptions=True)
        plans = []
        for res in results:
            if isinstance(res, BaseException):
                plans.append({"err": f"{type(res).__name__}: {res}"})
            else:
                plans.append(res)
        return plans

    # ------------------------------------------------------------ delivery
    def encode_frames(self, msg: Message, specs: List[list],
                      wire_cache: dict) -> List[list]:
        """Encode the plan's QoS0 frame specs ONCE (into the local fan-out's
        ``wire_cache`` too, so local deliver loops reuse the same bytes) and
        return the shippable [version, retain, rem, frame] rows."""
        from rmqtt_tpu.broker.session import encode_qos0_frame

        if msg.qos != 0 or not specs:
            return []
        rem = msg.remaining_expiry()
        rows = []
        for ver, retain in specs:
            key = (int(ver), bool(retain), rem)
            data = wire_cache.get(key)
            if data is None:
                data = wire_cache[key] = encode_qos0_frame(
                    msg, int(ver), bool(retain), rem)
            rows.append([key[0], key[1], rem, data])
        return rows

    async def deliver_remote(self, wid: int, msg: Message, rel_rows: list,
                             frames: List[list],
                             p2p: Optional[str] = None) -> bool:
        """One ``deliver`` frame to a peer worker (fire-and-forget, like the
        broadcast mode's targeted ForwardsTo notify). False = the peer is
        unreachable and the rels are lost (reason-counted by the caller)."""
        body = {"msg": msg_to_wire(msg), "rels": rel_rows,
                "frames": frames, "p2p": p2p}
        try:
            await self.link(wid).notify(F_DELIVER, body)
        except FabricUnavailable:
            return False
        self.deliver_out += 1
        return True

    def deliver_enqueue(self, wid: int, body: dict) -> None:
        """Coalescing fast path: queue one publish's deliver body for
        ``wid``; the flush loop merges everything queued per peer into ONE
        frame. Loss (peer unreachable at flush) is reason-counted there."""
        self._dq.setdefault(wid, []).append(body)
        self._dq_evt.set()

    async def _deliver_flush_loop(self) -> None:
        while True:
            await self._dq_evt.wait()
            self._dq_evt.clear()
            if not self._dq:
                continue
            batches, self._dq = self._dq, {}
            for wid, bodies in batches.items():
                try:
                    await self.link(wid).notify(F_DELIVER, {"many": bodies})
                    self.deliver_out += 1
                except FabricUnavailable:
                    lost = sum(max(1, len(b.get("rels") or ()))
                               for b in bodies)
                    self.ctx.metrics.drop("fabric_peer_down", lost)

    def _handle_deliver(self, body: dict) -> int:
        many = body.get("many")
        if many is not None:
            return sum(self._handle_deliver_one(b) for b in many)
        return self._handle_deliver_one(body)

    def _handle_deliver_one(self, body: dict) -> int:
        msg = msg_from_wire(body["msg"])
        self.deliver_in += 1
        registry = self.ctx.registry
        if body.get("p2p"):
            target = registry.get(body["p2p"])
            if target is None:
                self.ctx.metrics.drop("no_session")
                return 0
            target.enqueue(DeliverItem(msg=msg, qos=msg.qos, retain=False,
                                       topic_filter=""))
            return 1
        # seed the shared per-fanout encode cache with the frames the
        # publishing worker already built: same-version QoS0 subscribers
        # here write those bytes straight to their sockets
        wire_cache = {(int(v), bool(r), rem): bytes(data)
                      for v, r, rem, data in body.get("frames", [])}
        count = 0
        for rw in body.get("rels", []):
            rel = relation_from_wire(rw)
            count += registry._deliver_local(
                rel.id.client_id, rel.topic_filter, rel.opts, msg, wire_cache)
        return count

    # ------------------------------------------------------------ retained
    def _merge_retain(self, topic: str, mw: Optional[dict],
                      relay_from: Optional[int] = None) -> None:
        """Apply one replicated retained set/clear, newest create_time wins
        (the broadcast cluster's dedup rule). The owner relays to every
        other registered worker so all stores converge."""
        retain = self.ctx.retain
        if mw is None:
            retain.remove_local(topic)
        else:
            msg = msg_from_wire(mw)
            cur = retain.get(topic)
            if cur is None or msg.create_time >= cur.create_time:
                retain.set_local(topic, msg)
        if self.is_owner:
            body = {"topic": topic, "msg": mw}
            for wid, (writer, wlock) in list(self._worker_conns.items()):
                if wid != relay_from:
                    self._spawn(self._push(wid, writer, wlock, F_RETAIN, body))

    def _on_retain_set(self, topic: str, msg: Optional[Message]) -> None:
        """ctx.retain.on_set hook: replicate a local retained mutation."""
        mw = msg_to_wire(msg) if msg is not None else None
        if self.is_owner:
            self._merge_retain(topic, mw, relay_from=self.worker_id)
            return

        async def push():
            try:
                await self._owner_link.notify(
                    F_RETAIN, {"topic": topic, "msg": mw})
            except FabricUnavailable:
                self.ctx.metrics.drop("retain_sync")

        self._spawn(push())

    # ----------------------------------------------------------- directory
    def directory_entry(self, cid: str) -> Optional[list]:
        table = self.directory if self.is_owner else self.replica
        return table.get(cid)

    def _arbitrate_attach(self, cid: str, new_wid: int) -> None:
        """Owner: two near-simultaneous CONNECTs for one client id can land
        on two workers and BOTH win their directory-miss kick check. The
        owner is the serialization point: an attach that conflicts with a
        live entry on a DIFFERENT worker kicks the earlier copy (arrival
        order at the owner decides — the MQTT newest-wins takeover rule).
        Normal takeovers never get here: their kick+terminate detached the
        old entry before the new attach arrives."""
        old = self.directory.get(cid)
        if old is None or int(old[0]) == new_wid or not old[1]:
            return
        old_wid = int(old[0])
        if old_wid == self.worker_id:
            # stale copy is local to the owner: close it directly
            async def kick_local():
                await self._handle_kick({"cid": cid, "clean_start": True})

            self._spawn(kick_local())
            return

        async def kick_remote():
            try:
                await self.link(old_wid).call(
                    F_KICK, {"cid": cid, "clean_start": True})
            except FabricUnavailable:
                pass  # dead worker: its session is already gone

        self._spawn(kick_remote())
        self.ctx.metrics.inc("fabric.attach_conflicts")

    async def attach(self, cid: str, ver: int, online: bool = True) -> None:
        """Session (re)connected on this worker → directory update."""
        if self.is_owner:
            self._arbitrate_attach(cid, self.worker_id)
            self._dir_mutate([[cid, self.worker_id, online, int(ver)]])
            return
        self.replica[cid] = [self.worker_id, online, int(ver)]
        await self._owner_call_quiet(
            F_ATTACH, {"cid": cid, "wid": self.worker_id,
                       "ver": int(ver), "online": online})

    async def detach(self, cid: str) -> None:
        if self.is_owner:
            self._dir_detach(cid, self.worker_id)
            return
        self.replica.pop(cid, None)
        await self._owner_call_quiet(
            F_DETACH, {"cid": cid, "wid": self.worker_id})

    def _dir_detach(self, cid: str, wid: int) -> None:
        """Owner: drop a directory entry — but only the DETACHING worker's
        own entry. After an attach-conflict arbitration the loser's kick
        fires a detach too; without the wid guard it would erase the
        winner's fresh row."""
        ent = self.directory.get(cid)
        if ent is not None and int(ent[0]) == wid:
            self._dir_mutate([[cid, None, False, 0]])

    async def set_online(self, cid: str, online: bool) -> None:
        if self.is_owner:
            ent = self.directory.get(cid)
            if (ent is not None and int(ent[0]) == self.worker_id
                    and bool(ent[1]) != online):
                self._dir_mutate([[cid, ent[0], online, ent[2]]])
            return
        ent = self.replica.get(cid)
        if ent is not None and int(ent[0]) == self.worker_id:
            ent[1] = online
        await self._owner_call_quiet(
            F_ONLINE, {"cid": cid, "wid": self.worker_id, "online": online})

    async def _owner_call_quiet(self, mtype: str, body) -> None:
        """Directory/subscription bookkeeping call: best-effort — a failure
        means the owner is down, and the re-register replay on reconnect
        restores exactly this state."""
        try:
            await self._owner_link.call(mtype, body)
        except FabricUnavailable:
            self.ctx.metrics.inc("fabric.owner_call_failures")

    def _cid_subs_add(self, cid: str, n: int = 1) -> None:
        cur = self._cid_subs.get(cid, 0) + n
        if cur > 0:
            self._cid_subs[cid] = cur
        else:
            self._cid_subs.pop(cid, None)

    async def sub_add(self, stripped: str, cid: str, opts) -> None:
        if self.is_owner:
            self._cid_subs_add(cid)
            self._bump_gen()  # the local router add WAS the table add
            return
        await self._owner_call_quiet(
            F_SUB_ADD, {"tf": stripped, "cid": cid, "wid": self.worker_id,
                        "opts": opts_to_wire(opts)})

    async def sub_del(self, stripped: str, cid: str) -> None:
        if self.is_owner:
            self._cid_subs_add(cid, -1)
            self._bump_gen()
            return
        await self._owner_call_quiet(
            F_SUB_DEL, {"tf": stripped, "cid": cid, "wid": self.worker_id})

    def _apply_dir_delta(self, body: dict) -> None:
        if int(body.get("prev", -1)) != self.replica_epoch:
            # gap (missed delta): pull the full directory
            self._spawn(self._dir_resync())
            return
        for cid, wid, online, ver in body.get("ops", []):
            if wid is None:
                self.replica.pop(cid, None)
            else:
                self.replica[cid] = [int(wid), bool(online), int(ver)]
        self.replica_epoch = int(body["epoch"])

    async def _dir_resync(self) -> None:
        try:
            reply = await self._owner_link.call(F_DIR_SYNC, {})
        except FabricUnavailable:
            return  # keeper will re-register, which seeds the replica
        self.replica = {cid: list(ent) for cid, ent in
                        (reply.get("directory") or {}).items()}
        self.replica_epoch = int(reply.get("epoch", 0))

    # ----------------------------------------------------------------- kick
    async def kick_via_directory(self, cid: str,
                                 clean_start: bool) -> Optional[dict]:
        """O(1) CONNECT kick: a directory miss is no RPC at all; a hit on
        another worker is ONE targeted kick (never an O(workers) scatter).
        Returns the kick reply (with any transferred session state)."""
        self.kicks_o1 += 1
        ent = self.directory_entry(cid)
        if ent is None or ent[0] == self.worker_id:
            return None  # fresh client or local session: registry handles it
        self.kick_rpcs += 1
        try:
            return await self.link(int(ent[0])).call(
                F_KICK, {"cid": cid, "clean_start": clean_start})
        except FabricUnavailable:
            # owning worker is dead: its session died with it; the owner's
            # purge-on-disconnect removes the stale directory entry
            return None

    async def _handle_kick(self, body: dict) -> dict:
        """Targeted takeover kick (the cluster M.KICK contract: close, wait,
        snapshot resumable state, terminate)."""
        ctx = self.ctx
        session = ctx.registry.get(body["cid"])
        if session is None:
            return {"kicked": False}
        if session.state is not None:
            await session.state.close(kicked=True)
            for _ in range(100):
                if not session.connected:
                    break
                await asyncio.sleep(0.01)
        state = None
        if not body.get("clean_start", True) and session.limits.session_expiry > 0:
            state = session_snapshot(session, max_queue_items=5000)
        await ctx.registry.terminate(session, "cluster-kick")
        return {"kicked": True, "state": state}

    # ------------------------------------------------------------- handlers
    async def _handle(self, mtype: str, body, conn) -> Any:
        if mtype == F_SUBMIT:
            await self.warm_wait()
            self.batches += 1
            items = body.get("items", [])
            self.items += len(items)
            return {"plans": await self._plan_items(items),
                    "gen": self.table_gen}
        if mtype == F_DELIVER:
            return {"count": self._handle_deliver(body)}
        if mtype == F_KICK:
            return await self._handle_kick(body)
        if mtype == F_REGISTER:
            return self._apply_register(body, conn)
        if mtype == F_ATTACH:
            wid = (conn.wid if conn is not None and conn.wid is not None
                   else int(body.get("wid", 0)))
            self._arbitrate_attach(body["cid"], wid)
            self._dir_mutate([[body["cid"], wid, body.get("online", True),
                               int(body.get("ver", 4))]])
            return {"epoch": self.dir_epoch}
        if mtype == F_DETACH:
            wid = (conn.wid if conn is not None and conn.wid is not None
                   else int(body.get("wid", 0)))
            self._dir_detach(body["cid"], wid)
            return {"epoch": self.dir_epoch}
        if mtype == F_ONLINE:
            wid = (conn.wid if conn is not None and conn.wid is not None
                   else int(body.get("wid", 0)))
            ent = self.directory.get(body["cid"])
            if ent is not None and int(ent[0]) == wid:
                self._dir_mutate([[body["cid"], ent[0],
                                   bool(body.get("online", False)), ent[2]]])
            return {"epoch": self.dir_epoch}
        if mtype == F_SUB_ADD:
            wid = (conn.wid if conn is not None and conn.wid is not None
                   else int(body.get("wid", 0)))
            self.ctx.router.add(body["tf"], Id(wid, body["cid"]),
                                opts_from_wire(body["opts"]))
            self._worker_subs.setdefault(wid, set()).add(
                (body["tf"], body["cid"]))
            self._cid_subs_add(body["cid"])
            self._bump_gen()
            return None
        if mtype == F_SUB_DEL:
            wid = (conn.wid if conn is not None and conn.wid is not None
                   else int(body.get("wid", 0)))
            try:
                self.ctx.router.remove(body["tf"], Id(wid, body["cid"]))
            except Exception:
                pass
            self._worker_subs.get(wid, set()).discard(
                (body["tf"], body["cid"]))
            self._cid_subs_add(body["cid"], -1)
            self._bump_gen()
            return None
        if mtype == F_DIR:
            self._apply_dir_delta(body)
            self._observe_gen(body.get("gen"))
            return None
        if mtype == F_GEN:
            self._observe_gen(body.get("gen"))
            return None
        if mtype == F_DIR_SYNC:
            return {"epoch": self.dir_epoch,
                    "directory": {cid: list(ent)
                                  for cid, ent in self.directory.items()}}
        if mtype == F_RETAIN:
            wid = conn.wid if conn is not None and conn.wid else None
            self._merge_retain(body["topic"], body.get("msg"), relay_from=wid)
            return None
        raise ValueError(f"unknown fabric frame {mtype!r}")

    async def _on_client_disconnected(self, _htype, args, _prev):
        sid = args[0]
        s = self.ctx.registry.get(sid.client_id)
        if s is not None and not s.connected and self.running:
            await self.set_online(sid.client_id, False)
        return None

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> dict:
        """/api/v1/fabric body."""
        return {
            "enabled": True,
            "running": self.running,
            "worker_id": self.worker_id,
            "owner_id": self.owner_id,
            "role": "owner" if self.is_owner else "worker",
            "socket": self.sock_path(self.worker_id),
            "owner_up": self.is_owner or self._owner_up.is_set(),
            "directory": {
                "epoch": self.dir_epoch if self.is_owner else self.replica_epoch,
                "size": len(self.directory if self.is_owner else self.replica),
            },
            "table_gen": self.table_gen if self.is_owner else self.remote_gen,
            "plan_cache_size": len(self._plan_cache),
            "links": sorted(
                [wid for wid, lk in self._links.items() if lk.connected]
                + ([self.owner_id] if self._owner_link is not None
                   and self._owner_link.connected else [])),
            "registered_workers": sorted(self._worker_conns)
            if self.is_owner else None,
            "counters": {
                "batches": self.batches,
                "items": self.items,
                "bytes_out": self.bytes_out,
                "deliver_in": self.deliver_in,
                "deliver_out": self.deliver_out,
                "kicks_o1": self.kicks_o1,
                "kick_rpcs": self.kick_rpcs,
                "plan_hits": self.plan_hits,
                "owner_reconnects": self.owner_reconnects,
                "submit_fallbacks": self.submit_fallbacks,
                "submit_ms_total": round(self.submit_ms_total, 3),
                "fanout_ms_total": round(self.fanout_ms_total, 3),
            },
        }


class FabricSessionRegistry(SessionRegistry):
    """Session registry whose cross-worker paths ride the fabric: publishes
    submit to the router owner for one node-wide match, kicks resolve O(1)
    through the directory replica, subscription mutations replicate to the
    owner's table. With the fabric not running (startup, owner outage past
    the deadline) every path degrades to the plain local registry."""

    async def forwards(self, msg: Message) -> int:
        fab = self.ctx.fabric
        if fab is None or not fab.running:
            return await super().forwards(msg)
        trace = CURRENT_TRACE.get() if self.ctx.telemetry.enabled else None
        if msg.target_clientid is not None:
            if self._sessions.get(msg.target_clientid) is not None:
                return await super().forwards(msg)
            ent = fab.directory_entry(msg.target_clientid)
            if ent is None or ent[0] == fab.worker_id:
                return 0
            ok = await fab.deliver_remote(int(ent[0]), msg, [], [],
                                          p2p=msg.target_clientid)
            if not ok:
                self.ctx.metrics.drop("fabric_peer_down")
                return 0
            self._mark_forwarded(msg, msg.target_clientid)
            return 1
        if fab.is_owner:
            # the owner's local router IS the node table: match here, then
            # partition by owning worker (behind the same warm-up gate a
            # submitted batch takes — a just-respawned owner's table may
            # still be missing workers' re-registrations)
            await fab.warm_wait()
            raw = await self.ctx.routing.matches_raw(msg.from_id, msg.topic)
            relmap = self.ctx.router.collapse(raw)
            local, remote, specs = fab.partition_plan(
                relmap, msg.qos, msg.retain, local_wid=fab.worker_id)
        else:
            try:
                plan = await fab.submit_publish(msg)
            except FabricUnavailable:
                # bounded degradation: serve this worker's own subscribers
                # from the local router instead of stalling the publisher
                fab.submit_fallbacks += 1
                self.ctx.metrics.inc("fabric.submit_fallbacks")
                return await super().forwards(msg)
            remote = {int(w): rows for w, rows in
                      (plan.get("rels") or {}).items()}
            local_rows = remote.pop(fab.worker_id, [])
            local = [relation_from_wire(rw) for rw in local_rows]
            specs = plan.get("fspecs") or []
        count = 0
        wire_cache: dict = {}
        frames = fab.encode_frames(msg, specs, wire_cache) if remote else []
        for rel in local:
            count += self._deliver_local(rel.id.client_id, rel.topic_filter,
                                         rel.opts, msg, wire_cache, trace)
        if remote:
            t0 = time.perf_counter()
            mw = msg_to_wire(msg)  # serialized ONCE for every peer worker
            for wid, rows in remote.items():
                fab.deliver_enqueue(wid, {"msg": mw, "rels": rows,
                                          "frames": frames})
                count += len(rows)
                self.ctx.metrics.inc("cluster.forwards")
            fab.fanout_ms_total += (time.perf_counter() - t0) * 1e3
        return count

    async def take_or_create(self, ctx, id: Id, connect_info, limits,
                             clean_start: bool):
        fab = ctx.fabric
        if (fab is not None and fab.running
                and self._sessions.get(id.client_id) is None):
            reply = await fab.kick_via_directory(id.client_id, clean_start)
            if (reply and reply.get("state") and not clean_start
                    and self._sessions.get(id.client_id) is None):
                await restore_session(ctx, reply["state"], node_id=id.node_id)
        session, present = await super().take_or_create(
            ctx, id, connect_info, limits, clean_start)
        if fab is not None and fab.running:
            await fab.attach(id.client_id, ver=connect_info.protocol)
        return session, present

    async def terminate(self, session, reason: str) -> None:
        existed = self._sessions.get(session.client_id) is session
        await super().terminate(session, reason)
        fab = self.ctx.fabric
        if existed and fab is not None and fab.running:
            await fab.detach(session.client_id)

    async def router_add(self, stripped: str, id, opts) -> None:
        await super().router_add(stripped, id, opts)
        fab = self.ctx.fabric
        if fab is not None and fab.running:
            await fab.sub_add(stripped, id.client_id, opts)

    async def router_remove(self, stripped: str, id) -> None:
        await super().router_remove(stripped, id)
        fab = self.ctx.fabric
        if fab is not None and fab.running:
            await fab.sub_del(stripped, id.client_id)
