"""Per-listener bounded handshake executor.

Mirrors the reference `HandshakeExecutor` (`rmqtt/src/executor.rs:66-137`):
each listener port gets its own execution entry with a concurrency bound
(``workers`` = the listener's max_handshaking limit) and a pending-queue
bound (``queue_max`` = max_connections); the port counts as BUSY once its
active handshakes exceed 35% of the worker bound (executor.rs:100-106
dynamic busy limit), which feeds the server-wide overload gate.

asyncio translation: a semaphore is the worker pool, bounded waiting is the
queue; a connection that cannot even queue is refused immediately. In
normal operation the server's busy gate refuses connections at the 35%
rule BEFORE the semaphore ever blocks (same as the reference, whose
frontends consult is_busy at accept) — the worker/queue bounds are the
hard backstop for paths that race the gate.
"""

from __future__ import annotations

import asyncio
from typing import Dict

BUSY_FRACTION = 0.35  # executor.rs:100: busy at 35% of the handshake limit


class ExecutorFull(Exception):
    """The listener's pending-handshake queue is at capacity."""


class ListenerExecutor:
    def __init__(self, workers: int, queue_max: int) -> None:
        self.workers = max(1, workers)
        self.queue_max = max(1, queue_max)
        self.busy_limit = max(1, int(self.workers * BUSY_FRACTION))
        self._sem = asyncio.Semaphore(self.workers)
        self.active = 0
        self.waiting = 0

    @property
    def is_busy(self) -> bool:
        return self.active >= self.busy_limit

    async def acquire(self) -> None:
        if self.waiting >= self.queue_max:
            raise ExecutorFull()
        self.waiting += 1
        try:
            await self._sem.acquire()
        finally:
            self.waiting -= 1
        self.active += 1

    def release(self) -> None:
        self.active -= 1
        self._sem.release()


class HandshakeExecutor:
    """Per-port entries, lazily created (executor.rs get())."""

    def __init__(self, workers: int, queue_max: int) -> None:
        self.workers = workers
        self.queue_max = queue_max
        self._entries: Dict[int, ListenerExecutor] = {}

    def entry(self, port: int) -> ListenerExecutor:
        e = self._entries.get(port)
        if e is None:
            e = self._entries[port] = ListenerExecutor(self.workers, self.queue_max)
        return e

    def active_count(self) -> int:
        return sum(e.active for e in self._entries.values())

    def is_busy(self) -> bool:
        return any(e.is_busy for e in self._entries.values())
