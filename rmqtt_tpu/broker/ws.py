"""MQTT-over-WebSocket transport (RFC 6455, server side).

The reference supports WS/WSS listeners (`rmqtt-net/src/ws.rs`, builder
listeners `rmqtt-net/src/builder.rs`). This is a dependency-free WebSocket
server endpoint: HTTP upgrade with ``Sec-WebSocket-Accept``, the ``mqtt``
subprotocol, binary frames (client→server masked per spec), fragmentation
reassembly, ping/pong, close — adapted to the broker's reader/writer duck
type so the same connection handler serves TCP and WS.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import struct
from typing import Optional, Tuple

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT, OP_TEXT, OP_BIN, OP_CLOSE, OP_PING, OP_PONG = 0x0, 0x1, 0x2, 0x8, 0x9, 0xA


async def websocket_accept(reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                           timeout: float = 10.0) -> bool:
    """Perform the server-side HTTP upgrade. Returns False on a bad request."""
    try:
        request = await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), timeout)
    except (asyncio.TimeoutError, asyncio.IncompleteReadError, asyncio.LimitOverrunError):
        return False
    lines = request.decode("latin1").split("\r\n")
    headers = {}
    for line in lines[1:]:
        k, _, v = line.partition(":")
        if v:
            headers[k.strip().lower()] = v.strip()
    key = headers.get("sec-websocket-key")
    if key is None or "websocket" not in headers.get("upgrade", "").lower():
        writer.write(b"HTTP/1.1 400 Bad Request\r\nContent-Length: 0\r\n\r\n")
        await writer.drain()
        return False
    accept = base64.b64encode(hashlib.sha1((key + _WS_GUID).encode()).digest()).decode()
    proto = ""
    offered = [p.strip() for p in headers.get("sec-websocket-protocol", "").split(",") if p.strip()]
    if "mqtt" in offered:
        proto = "Sec-WebSocket-Protocol: mqtt\r\n"
    writer.write(
        (
            "HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {accept}\r\n{proto}\r\n"
        ).encode()
    )
    await writer.drain()
    return True


class WsReader:
    """Duck-typed StreamReader over WS binary frames."""

    def __init__(self, reader: asyncio.StreamReader, writer: "WsWriter") -> None:
        self._reader = reader
        self._writer = writer
        self._buf = bytearray()
        self._closed = False
        self._fragments = bytearray()

    async def read(self, n: int = -1) -> bytes:
        while not self._buf and not self._closed:
            payload = await self._next_message()
            if payload is None:
                self._closed = True
                break
            self._buf += payload
        if not self._buf:
            return b""
        if n < 0 or n >= len(self._buf):
            out = bytes(self._buf)
            self._buf.clear()
        else:
            out = bytes(self._buf[:n])
            del self._buf[:n]
        return out

    async def _next_message(self) -> Optional[bytes]:
        """One complete (possibly fragmented) binary message; None on close."""
        while True:
            frame = await self._read_frame()
            if frame is None:
                return None
            fin, op, payload = frame
            if op == OP_PING:
                await self._writer.send_frame(OP_PONG, payload)
                continue
            if op == OP_PONG:
                continue
            if op == OP_CLOSE:
                try:
                    await self._writer.send_frame(OP_CLOSE, payload[:2])
                except (ConnectionError, OSError):
                    pass
                return None
            if op in (OP_BIN, OP_TEXT):
                if fin:
                    return payload
                self._fragments = bytearray(payload)
            elif op == OP_CONT:
                self._fragments += payload
                if fin:
                    out = bytes(self._fragments)
                    self._fragments = bytearray()
                    return out

    async def _read_frame(self) -> Optional[Tuple[bool, int, bytes]]:
        try:
            head = await self._reader.readexactly(2)
            fin = bool(head[0] & 0x80)
            op = head[0] & 0x0F
            masked = bool(head[1] & 0x80)
            length = head[1] & 0x7F
            if length == 126:
                (length,) = struct.unpack(">H", await self._reader.readexactly(2))
            elif length == 127:
                (length,) = struct.unpack(">Q", await self._reader.readexactly(8))
            if length > 16 * 1024 * 1024:
                return None
            mask = await self._reader.readexactly(4) if masked else None
            payload = await self._reader.readexactly(length) if length else b""
            if mask:
                payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
            return fin, op, payload
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            return None


class WsWriter:
    """Duck-typed StreamWriter sending WS binary frames (server: unmasked)."""

    # bytes only reach the wire on drain() (a whole WS frame per drain):
    # callers must NOT elide drains the way they may for raw StreamWriters
    buffers_until_drain = True

    def __init__(self, writer: asyncio.StreamWriter) -> None:
        self._writer = writer
        self._pending = bytearray()

    def write(self, data: bytes) -> None:
        self._pending += data

    async def drain(self) -> None:
        if self._pending:
            data, self._pending = bytes(self._pending), bytearray()
            await self.send_frame(OP_BIN, data)

    async def send_frame(self, op: int, payload: bytes) -> None:
        head = bytearray([0x80 | op])
        n = len(payload)
        if n < 126:
            head.append(n)
        elif n < 65536:
            head.append(126)
            head += struct.pack(">H", n)
        else:
            head.append(127)
            head += struct.pack(">Q", n)
        self._writer.write(bytes(head) + payload)
        await self._writer.drain()

    def get_extra_info(self, name, default=None):
        return self._writer.get_extra_info(name, default)

    def close(self) -> None:
        self._writer.close()

    @property
    def transport(self):
        return self._writer.transport


def mask_client_frame(op: int, payload: bytes, mask: bytes = b"\x12\x34\x56\x78") -> bytes:
    """Build a masked client→server frame (for test clients/bridges)."""
    head = bytearray([0x80 | op])
    n = len(payload)
    if n < 126:
        head.append(0x80 | n)
    elif n < 65536:
        head.append(0x80 | 126)
        head += struct.pack(">H", n)
    else:
        head.append(0x80 | 127)
        head += struct.pack(">Q", n)
    masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    return bytes(head) + mask + masked
