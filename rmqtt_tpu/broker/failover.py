"""Device-plane failover: TPU routing faults degrade to the host trie.

The device router (`router/xla.py`) is one failure domain: an XLA dispatch
error, a hung kernel completion, or an OOM on a table upload used to reach
`RoutingService` as rejected publish futures — the broker had no
degraded-but-correct routing plane. This module closes that gap by wiring
two existing primitives together:

- PR4's :class:`~rmqtt_tpu.broker.overload.CircuitBreaker` wraps the device
  router: classified device failures (``dispatch_error`` / ``complete_error``
  / ``timeout`` / ``upload_error``) count toward the breaker; once it opens,
  `RoutingService` routes every batch through the **host-side trie mirror**
  the hybrid already maintains (`XlaRouter._side` — updated synchronously on
  every subscribe/unsubscribe, so the fallback table is *current*, not a
  snapshot; see README "Failure domains & failover" for the staleness
  contract).
- PR5's full-pack upload path rewarm: a half-open probe first calls
  ``router.device_rewarm()`` (layout-epoch bump → the delta gate closes, the
  next refresh re-packs and re-uploads the WHOLE table, so delta state can't
  go stale across the outage), then runs ``k_successes`` consecutive canary
  matches through the device matcher checked against the trie oracle. All
  green → breaker closes, routing switches back; any failure → re-open with
  the breaker's exponential backoff.

A per-batch deadline (``timeout_s``) acts as the completion-queue watchdog:
a hung device (the ``device.complete = hang`` failpoint, or a real wedged
kernel) times the batch out, serves it from the host, and trips the breaker
— ``_complete_loop`` never wedges. The abandoned executor thread is
swallowed, not awaited.

Failover state surfaces everywhere overload state already does: RoutingService
``stats()`` (``routing_failover_state`` 0=device 1=host 2=probing),
Prometheus, the dashboard, ``$SYS/brokers/<n>/routing/failover``, the
slow-op ring, and ``routing.failover`` trace spans on host-routed publishes.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, Optional

from rmqtt_tpu.broker.overload import CircuitBreaker

log = logging.getLogger("rmqtt_tpu.failover")

#: failure taxonomy — every counter/metric reason comes from this set
REASONS = ("dispatch_error", "complete_error", "upload_error", "timeout",
           "canary_mismatch")


def _swallow_abandoned(fut) -> None:
    """Done-callback for executor futures a watchdog abandoned (the probe
    here, the per-batch deadline in broker/routing.py): retrieve the late
    result/exception so asyncio never logs 'exception was never retrieved'
    for a thread that finally unwedged."""
    if not fut.cancelled():
        fut.exception()


def device_verify(router, k: int = 1,
                  static_topic: str = "rmqtt/failover/canary"
                  ) -> Optional[bool]:
    """``k`` consecutive canary matches through the DEVICE matcher checked
    against the host trie oracle — the verify step shared by the failover
    plane's half-open probe and the autotuner's canary epochs
    (broker/autotune.py): both must prove "the device still answers
    CORRECTLY under the current settings" before trusting a transition.

    → True (all canaries agreed), False (mismatch or canary raised), or
    None when the router exposes no device canary entry point (trie-only
    routers; the caller decides whether that means pass or fail — the
    probe fails closed, the autotuner skips the check).

    Topics derive from live filters where possible (router.canary_topics):
    on a non-empty table a static unmatched topic would compare
    empty-vs-empty and vacuously pass a device that recovered into wrong
    answers."""
    canary = getattr(router, "device_canary", None)
    if not callable(canary):
        return None
    ct = getattr(router, "canary_topics", None)
    topics = (ct() if callable(ct) else []) or [static_topic]
    for _ in range(max(1, int(k))):
        if not canary(topics):
            return False
    return True


def classify(exc: BaseException, default: str) -> str:
    """Refine a call-site reason (dispatch/complete) by exception content:
    HBM refresh failures — a real device OOM on upload after table growth,
    or the ``device.upload`` failpoint — surface during dispatch but are a
    distinct failure domain (rewarm fixes them; a dead kernel it won't)."""
    s = str(exc)
    if ("device.upload" in s or "RESOURCE_EXHAUSTED" in s
            or "out of memory" in s.lower()):
        return "upload_error"
    return default


class DeviceFailover:
    """Failover brain shared by ``RoutingService`` and the admin surfaces.

    Hot-path contract: while the device plane is healthy the routing
    service pays ONE attribute test (``fo.active``) per dispatch plus a
    breaker reset per completed batch; all bookkeeping lives on the
    failure/probe paths."""

    DEVICE, HOST, PROBING = 0, 1, 2  # state_value() encoding

    def __init__(self, router, breaker: CircuitBreaker, *,
                 timeout_s: float = 30.0, k_successes: int = 3,
                 canary_topic: str = "rmqtt/failover/canary",
                 metrics=None, telemetry=None) -> None:
        self.router = router
        self.breaker = breaker
        self.timeout_s = float(timeout_s)
        self.k_successes = max(1, int(k_successes))
        self.canary_topic = canary_topic
        self.metrics = metrics
        self.telemetry = telemetry
        self.active = False  # True while routing via the host fallback
        self.failovers = 0  # device → host transitions
        self.switchbacks = 0  # host → device transitions
        self.host_batches = 0
        self.host_items = 0
        self.probes = 0
        self.probe_failures = 0
        self.failures: Dict[str, int] = {r: 0 for r in REASONS}
        self.state_since = time.time()
        self.last_failover_ts: Optional[float] = None
        self.last_switchback_ts: Optional[float] = None
        self._probe_task = None  # at most one probe in flight
        self._pacer_task = None  # clock-driven probe scheduler while active
        self._abandoned = 0  # probe threads wedged past the watchdog

    # ------------------------------------------------------------- queries
    @property
    def usable(self) -> bool:
        """Can the host fallback serve right now? (The Python-trie mirror
        is dropped past 200K filters — then there is nothing to route
        through and device failures stay failures.)"""
        avail = getattr(self.router, "host_available", None)
        return bool(avail()) if callable(avail) else False

    def state_value(self) -> int:
        if not self.active:
            return self.DEVICE
        return (self.PROBING if self.breaker.state == self.breaker.HALF_OPEN
                else self.HOST)

    @property
    def failure_total(self) -> int:
        return sum(self.failures.values())

    # ------------------------------------------------------------ failures
    def record_failure(self, reason: str) -> None:
        """One classified device-plane failure: reason-labeled counter +
        breaker bookkeeping; opening the breaker activates the host plane."""
        if reason not in self.failures:
            reason = "dispatch_error"
        self.failures[reason] += 1
        if self.metrics is not None:
            self.metrics.inc(f"routing.failover.failures.{reason}")
        self.breaker.fail()
        if not self.active and self.breaker.state != self.breaker.CLOSED:
            self._transition(True, reason)

    def note_device_ok(self) -> None:
        """A device batch completed fine: reset the consecutive-failure
        count (the breaker's threshold is *consecutive*, like PR4's peers)."""
        if not self.active:
            self.breaker.ok()

    # ---------------------------------------------------------- host plane
    def note_host_batch(self, n_items: int) -> None:
        self.host_batches += 1
        self.host_items += n_items
        if self.metrics is not None:
            self.metrics.inc("routing.failover.host_routed", n_items)

    # -------------------------------------------------------------- probes
    #: max probe threads left wedged past the watchdog before probing
    #: pauses until one unwedges — a persistently hung device must not
    #: leak one default-executor worker per cooldown forever (the pool
    #: caps at min(32, cpus+4); unbounded leaks starve every other
    #: run_in_executor user in the process)
    MAX_ABANDONED_PROBES = 4

    def maybe_probe(self, loop) -> None:
        """Called per dispatch while active: once the breaker cooldown has
        elapsed, launch ONE background probe (rewarm + K canaries). The
        live traffic keeps flowing through the host path meanwhile."""
        if self._probe_task is not None or self.breaker.state == self.breaker.CLOSED:
            return
        if self._abandoned >= self.MAX_ABANDONED_PROBES:
            return  # wedged-thread budget spent: wait for one to return
        if self.breaker.remaining() > 0.0 or not self.breaker.allow():
            return  # still cooling down (allow() flips OPEN → HALF_OPEN)
        self._probe_task = loop.create_task(self._probe(loop))

    async def _pace(self, loop) -> None:
        """Clock-driven probe scheduler: dispatch-triggered probes alone
        would strand the broker on the host plane when traffic is idle or
        fully served by the match cache (cache hits never dispatch) —
        recovery must not depend on cache misses. Sleeps track the
        breaker's cooldown so this is a handful of wakeups per outage."""
        try:
            while self.active:
                self.maybe_probe(loop)
                wait = self.breaker.remaining()
                await asyncio.sleep(min(max(wait, 0.05), 0.5))
        finally:
            self._pacer_task = None

    def stop(self) -> None:
        """Cancel background probe/pacer tasks (routing-service shutdown)."""
        for t in (self._pacer_task, self._probe_task):
            if t is not None:
                t.cancel()
        self._pacer_task = self._probe_task = None

    async def _probe(self, loop) -> None:
        self.probes += 1
        try:
            # same watchdog contract as routing._device_call: a probe that
            # hangs inside the device matcher must not strand the broker in
            # PROBING forever — abandon the executor thread, count the probe
            # as failed, and let the backed-off breaker schedule the next one
            fut = loop.run_in_executor(None, self._probe_sync)
            if self.timeout_s > 0:
                done, pending = await asyncio.wait({fut}, timeout=self.timeout_s)
                if pending:
                    self._abandoned += 1

                    def _unwedged(f) -> None:
                        self._abandoned -= 1
                        _swallow_abandoned(f)

                    fut.add_done_callback(_unwedged)
                    raise TimeoutError(
                        f"probe exceeded the {self.timeout_s:.1f}s "
                        f"failover deadline")
                ok = fut.result()
            else:
                ok = await fut
        except asyncio.CancelledError:
            raise
        except Exception as e:
            log.warning("failover probe raised: %s", e)
            ok = False
        finally:
            self._probe_task = None
        if ok:
            self.breaker.ok()
            self._transition(False, "probe_ok")
        else:
            self.probe_failures += 1
            if self.metrics is not None:
                self.metrics.inc("routing.failover.probe_failures")
            self.breaker.fail()  # HALF_OPEN fail → re-open, backed off

    def _probe_sync(self) -> bool:
        """The probe body (executor thread): force a full HBM re-upload,
        then ``k_successes`` consecutive canary matches, device vs the trie
        oracle. Device failpoints stay armed inside — a still-injected
        fault keeps the breaker open."""
        rewarm = getattr(self.router, "device_rewarm", None)
        if callable(rewarm):
            rewarm()
        ok = device_verify(self.router, self.k_successes, self.canary_topic)
        if ok is None:
            return False  # no canary entry point: fail closed, stay on host
        if not ok:
            self.failures["canary_mismatch"] += 1
            if self.metrics is not None:
                self.metrics.inc("routing.failover.failures.canary_mismatch")
            return False
        return True

    # ---------------------------------------------------------- transitions
    def _transition(self, to_host: bool, reason: str) -> None:
        self.active = to_host
        self.state_since = time.time()
        if to_host:
            self.failovers += 1
            self.last_failover_ts = self.state_since
            if self.metrics is not None:
                self.metrics.inc("routing.failover.failovers")
            log.warning("device routing plane FAILED OVER to host trie "
                        "(reason=%s breaker=%s)", reason, self.breaker.snapshot())
            # postmortem artifact: freeze the flight recorder at the moment
            # the device plane was declared dead (broker/devprof.py) — the
            # last K dispatch records + compile registry + HBM model are
            # exactly what the cfg4/cfg5 deaths never left behind
            try:
                from rmqtt_tpu.broker.devprof import DEVPROF

                DEVPROF.auto_dump("failover_trip")
            except Exception:  # pragma: no cover - dump must never block failover
                pass
            # start the clock-driven probe pacer (see _pace); transitions
            # to host always happen on the event loop (dispatch/complete
            # coroutines), so a running loop is available
            if self._pacer_task is None:
                try:
                    loop = asyncio.get_running_loop()
                except RuntimeError:
                    loop = None  # non-asyncio harness: dispatch-driven only
                if loop is not None:
                    self._pacer_task = loop.create_task(self._pace(loop))
        else:
            self.switchbacks += 1
            self.last_switchback_ts = self.state_since
            if self.metrics is not None:
                self.metrics.inc("routing.failover.switchbacks")
            log.warning("device routing plane RECOVERED (full re-upload + "
                        "%d canary matches); switching back", self.k_successes)
        # slow-ring annotation (same timeline operators read for stalls,
        # mirroring overload._transition)
        tele = self.telemetry
        if tele is not None and tele.enabled:
            tele.slow_ops.append({
                "op": "routing.failover", "ms": 0.0,
                "ts": round(self.state_since, 3),
                "detail": {"to": "host" if to_host else "device",
                           "reason": reason, "failovers": self.failovers,
                           "switchbacks": self.switchbacks},
            })

    # ------------------------------------------------------- observability
    def snapshot(self) -> dict:
        return {
            "state": ("host" if self.state_value() == self.HOST
                      else "probing" if self.state_value() == self.PROBING
                      else "device"),
            "state_value": self.state_value(),
            "state_since": round(self.state_since, 3),
            "usable": self.usable,
            "failovers": self.failovers,
            "switchbacks": self.switchbacks,
            "host_batches": self.host_batches,
            "host_routed": self.host_items,
            "probes": self.probes,
            "probe_failures": self.probe_failures,
            "failures": dict(self.failures),
            "timeout_s": self.timeout_s,
            "k_successes": self.k_successes,
            "breaker": self.breaker.snapshot(),
        }
