"""Device-plane flight recorder: recompile tracking, HBM accounting and
dispatch time-series for the TPU router.

The host plane has histograms (broker/telemetry.py), tracing
(broker/tracing.py) and SLO budgets (broker/slo.py); the device plane —
the component the whole paper is about — reported a handful of flat
counters. The last real-chip window left cfg4/cfg5 dead with no
on-device diagnosis and cfg1's small-batch loss attributed to "dispatch
overhead" only via offline A/B. This module is the instrument that
makes those diagnosable in production:

``shape-key registry`` (compile/retrace tracking)
    Every ``jax.jit`` entry seam in the matcher stack (match / fused /
    compact / split / delta-scatter / pallas — ``ops/partitioned.py``,
    ``parallel/sharded.py``) reports one ``note_jit(kernel, key, ns)``
    per dispatch. ``jax.jit`` caches executables on exactly the
    (static-args, arg-shapes/dtypes) signature, so a never-seen key IS a
    trace+compile by construction and a seen key is a cache hit — no
    jax-internal hooks needed, and the wall time of a first-seen call
    brackets the trace+compile cost. A burst of ``storm_n`` traces
    inside ``storm_window`` seconds is a **retrace storm** (the failure
    mode the sticky pad floor and pow2 padding exist to prevent): it
    bumps a counter, lands on PR2's slow-op ring, and auto-dumps the
    flight recorder — the padding invariants become *checkable in
    production* instead of assumed.

``dispatch rollups`` (time series, not cumulative counters)
    Fixed-interval ring-buffer buckets of dispatch count, batch items,
    padded rows (pad-waste fraction = (padded − real) / padded), active
    dispatch-path wall time (log2 histogram → p50/p99 per interval),
    delta-vs-full upload bytes and fused-vs-fallback share.

``flight recorder``
    A bounded ring of the last K dispatch records (shape kind, compile
    hit/trace, batch/padded, per-stage ns from PR9's ``stage_timing``,
    fused flag, trace id when one is in scope). ``dump()`` freezes ring
    + snapshot into one JSON artifact; ``auto_dump()`` fires on retrace
    storms, device-plane failover trips (broker/failover.py), fused-
    verify disagreement (ops/partitioned.py, parallel/sharded.py) and
    bench/chip-hunter failure exits — exactly the postmortem cfg4/cfg5
    never got.

Surfaces follow the house pattern: ``/api/v1/device`` (+ cluster
``/device/sum`` via a ``what=device`` DATA query), ``rmqtt_device_*``
Prometheus families, ``$SYS/brokers/<n>/device/#``, dashboard cards,
``stats()`` gauges, ``[observability]`` knobs (``device_profile``,
``device_ring``, ``recompile_storm_n``, ``recompile_storm_window``).
``enabled=False`` (the module default) keeps every instrumented seam at
ONE attribute check — no keys built, no timestamps taken, no ring
appends — while the surfaces stay shape-stable.

The profiler is process-global (``DEVPROF``), like the failpoint
registry: the jit executable caches it models are process-global too,
so per-matcher registries would double-count shared compilations.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from rmqtt_tpu.broker.telemetry import Histogram, prom_sanitize
from rmqtt_tpu.broker.tracing import CURRENT_TRACE

_LOG = logging.getLogger("rmqtt_tpu.devprof")

DUMP_SCHEMA = "rmqtt_tpu.devprof_dump/1"

#: per-kernel shape keys kept with their trace wall time (the report's
#: "top shape keys" table); past the cap older keys stay counted but lose
#: their per-key row — the registry set itself is never evicted (it is
#: what makes hit-vs-trace classification exact)
_KEY_ROWS_MAX = 128


class _Rollup:
    """One fixed-interval dispatch bucket (the time-series element)."""

    __slots__ = ("t", "dispatches", "items", "padded", "hist", "whist",
                 "bhist", "delta_bytes", "full_bytes", "fused", "fallback",
                 "traces")

    def __init__(self, t: int) -> None:
        self.t = t
        self.dispatches = 0
        self.items = 0
        self.padded = 0
        self.hist = Histogram()  # active dispatch-path ns (submit+complete)
        # warm-only subset: dispatches that carried NO fresh jit trace.
        # The autotuner's canary compares steady-state p99 against its
        # baseline — a ladder step legitimately compiles its new shape
        # once, and judging that one-off against the guard would veto
        # every exploration (the trace budget bounds compile COUNT
        # separately)
        self.whist = Histogram()
        # per-dispatch batch-size distribution (log2 buckets, mergeable by
        # addition like every Histogram): the autotuner's primary
        # regime-detection signal — pad-waste alone can't distinguish
        # "steady batch-1 traffic" from "mixed small batches", and the two
        # regimes want different pad floors (broker/autotune.py)
        self.bhist = Histogram()
        self.delta_bytes = 0
        self.full_bytes = 0
        self.fused = 0
        self.fallback = 0
        self.traces = 0

    def row(self) -> dict:
        return {
            "t": self.t,
            "dispatches": self.dispatches,
            "items": self.items,
            "padded": self.padded,
            "pad_waste": round(1.0 - self.items / self.padded, 4)
            if self.padded else 0.0,
            "p50_ms": round(self.hist.quantile(0.50) / 1e6, 3),
            "p99_ms": round(self.hist.quantile(0.99) / 1e6, 3),
            "warm_p99_ms": round(self.whist.quantile(0.99) / 1e6, 3),
            # quantiles are the bucket's EXCLUSIVE upper bound (exact to
            # one log2 bucket); batch_hist keys are those bounds too, so
            # consumers (autotune replay) merge rows by key addition
            "batch_p50": int(self.bhist.quantile(0.50)),
            "batch_p99": int(self.bhist.quantile(0.99)),
            "batch_hist": {
                str(Histogram.bucket_upper(i)): c
                for i, c in enumerate(self.bhist.counts) if c
            },
            "delta_bytes": self.delta_bytes,
            "full_bytes": self.full_bytes,
            "fused": self.fused,
            "fallback": self.fallback,
            "traces": self.traces,
        }


class DeviceProfiler:
    """Process-global device-plane profiler + flight recorder."""

    def __init__(
        self,
        enabled: bool = False,
        ring: int = 256,
        storm_n: int = 8,
        storm_window: float = 10.0,
        interval_s: float = 5.0,
        rollup_max: int = 120,
        dump_dir: Optional[str] = None,
    ) -> None:
        self.enabled = enabled
        self.storm_n = max(2, storm_n)
        self.storm_window = max(0.1, storm_window)
        self.interval_s = max(0.1, interval_s)
        self.rollup_max = max(2, rollup_max)
        self.dump_dir = dump_dir
        #: callable returning the router/matcher HBM occupancy breakdown
        #: (wired by ServerContext / the bench); None = model unavailable
        self.hbm_provider: Optional[Callable[[], dict]] = None
        #: telemetry registry whose slow-op ring storm/pad-floor events
        #: annotate (wired by ServerContext); None outside a broker
        self.telemetry = None
        self._lock = threading.Lock()
        self._reset_state(ring)

    def _reset_state(self, ring: int) -> None:
        self.ring_cap = max(1, ring)
        self.flight_ring: deque = deque(maxlen=self.ring_cap)
        # compile/retrace registry
        self._seen: set = set()  # (kernel, key) signatures already traced
        self.traces = 0
        self.cache_hits = 0
        self.trace_ns_total = 0
        self._kernel_traces: Dict[str, int] = {}
        self._kernel_trace_ns: Dict[str, int] = {}
        self._key_rows: Dict[str, List[dict]] = {}
        self._trace_ts: deque = deque()  # monotonic stamps for storm window
        self.storms = 0
        self.last_storm: Optional[dict] = None
        self._last_storm_mono = -1e18
        # dispatch accounting
        self.dispatches = 0
        self.items_total = 0
        self.padded_total = 0
        self.fused_total = 0
        self.fallback_total = 0
        self._rollups: deque = deque(maxlen=self.rollup_max)
        # upload accounting
        self.upload_counts = {"delta": 0, "full": 0}
        self.upload_bytes = {"delta": 0, "full": 0}
        # pad floor (reported by the matcher at prewarm/floor change)
        self.pad_floor = 1
        # dump bookkeeping
        self.dumps_log: deque = deque(maxlen=16)
        self.last_dump: Optional[dict] = None
        self._last_dump_mono: Dict[str, float] = {}

    # ------------------------------------------------------------ lifecycle
    def configure(self, **kw: Any) -> None:
        """Apply [observability] device knobs (ServerContext / bench).
        Counters survive a reconfigure; only a ``ring`` change rebuilds the
        flight ring (keeping the newest records that still fit)."""
        with self._lock:
            for name in ("enabled", "dump_dir", "telemetry", "hbm_provider"):
                if name in kw:
                    setattr(self, name, kw[name])
            if "storm_n" in kw:
                self.storm_n = max(2, int(kw["storm_n"]))
            if "storm_window" in kw:
                self.storm_window = max(0.1, float(kw["storm_window"]))
            if "interval_s" in kw:
                self.interval_s = max(0.1, float(kw["interval_s"]))
            if "ring" in kw and int(kw["ring"]) != self.ring_cap:
                self.ring_cap = max(1, int(kw["ring"]))
                self.flight_ring = deque(self.flight_ring,
                                         maxlen=self.ring_cap)
            if ("rollup_max" in kw
                    and max(2, int(kw["rollup_max"])) != self.rollup_max):
                self.rollup_max = max(2, int(kw["rollup_max"]))
                self._rollups = deque(self._rollups,
                                      maxlen=self.rollup_max)

    def reset(self) -> None:
        """Drop every counter/ring (tests; the registry is process-global,
        so accumulated state would otherwise leak across test cases)."""
        with self._lock:
            self._reset_state(self.ring_cap)

    # ------------------------------------------------------- shape keys
    @staticmethod
    def key_of(args: tuple, kwargs: dict) -> Tuple:
        """Shape key of one jit call: (shape, dtype) per array argument +
        the static kwargs, i.e. exactly the signature ``jax.jit`` caches
        executables on — so registry membership predicts hit-vs-trace."""

        def k(v: Any) -> Any:
            shape = getattr(v, "shape", None)
            if shape is not None:
                return (tuple(shape), str(getattr(v, "dtype", "")))
            if isinstance(v, (tuple, list)):
                return tuple(k(x) for x in v)
            if isinstance(v, (int, float, str, bool)) or v is None:
                return v
            return repr(v)

        return tuple(k(a) for a in args) + tuple(
            (n, k(v)) for n, v in sorted(kwargs.items()))

    def note_jit(self, kernel: str, key: Tuple, dur_ns: int) -> bool:
        """Record one jit-seam call. → True iff this (kernel, key) was a
        never-seen signature (a trace+compile). Called only when enabled
        (call sites guard on ``.enabled``)."""
        sig = (kernel, key)
        storm: Optional[dict] = None
        with self._lock:
            if sig in self._seen:
                self.cache_hits += 1
                return False
            self._seen.add(sig)
            self.traces += 1
            self.trace_ns_total += dur_ns
            self._kernel_traces[kernel] = self._kernel_traces.get(kernel, 0) + 1
            self._kernel_trace_ns[kernel] = (
                self._kernel_trace_ns.get(kernel, 0) + dur_ns)
            rows = self._key_rows.setdefault(kernel, [])
            if len(rows) < _KEY_ROWS_MAX:
                rows.append({"key": repr(key), "trace_ms": round(dur_ns / 1e6, 3),
                             "ts": round(time.time(), 3)})
            self._rollup().traces += 1
            # storm window: a burst of distinct signatures means the shape
            # discipline (pad floor, pow2 NC, sticky budgets) broke down
            now = time.monotonic()
            self._trace_ts.append(now)
            horizon = now - self.storm_window
            while self._trace_ts and self._trace_ts[0] < horizon:
                self._trace_ts.popleft()
            if (len(self._trace_ts) >= self.storm_n
                    and now - self._last_storm_mono >= self.storm_window):
                self.storms += 1
                self._last_storm_mono = now
                storm = self.last_storm = {
                    "ts": round(time.time(), 3),
                    "traces_in_window": len(self._trace_ts),
                    "window_s": self.storm_window,
                    "kernel": kernel,
                    "key": repr(key),
                }
        if storm is not None:
            _LOG.warning(
                "device RETRACE STORM: %d jit traces in %.1fs (last: %s %s) "
                "— shape discipline broke down (pad floor / pow2 padding)",
                storm["traces_in_window"], storm["window_s"], kernel,
                storm["key"])
            self._annotate_ring("device.retrace_storm", storm)
            self.auto_dump("retrace_storm")
        return True

    # ------------------------------------------------------- dispatch ring
    def _rollup(self) -> _Rollup:
        """Current interval bucket (caller holds the lock). The bucket key
        must keep the interval's resolution — int() truncation collapsed
        every sub-second interval onto 1s buckets, which silently starved
        any consumer windowing finer than a second (the autotuner's bench
        cadence)."""
        t = round(time.time() // self.interval_s * self.interval_s, 3)
        if not self._rollups or self._rollups[-1].t != t:
            self._rollups.append(_Rollup(t))
        return self._rollups[-1]

    def note_dispatch(self, rec: dict, dispatch_ns: int) -> None:
        """One completed logical dispatch: flight-ring record + rollup.
        ``dispatch_ns`` is the ACTIVE dispatch-path wall time (submit work
        + complete work, excluding the pipeline park in between)."""
        trace = CURRENT_TRACE.get()
        if trace is not None:
            rec["trace"] = trace.tid
        rec["total_ms"] = round(dispatch_ns / 1e6, 3)
        with self._lock:
            self.dispatches += 1
            self.items_total += rec.get("batch", 0)
            self.padded_total += rec.get("padded", 0)
            if rec.get("fused"):
                self.fused_total += 1
            else:
                self.fallback_total += 1
            r = self._rollup()
            r.dispatches += 1
            r.items += rec.get("batch", 0)
            r.padded += rec.get("padded", 0)
            r.hist.record(dispatch_ns)
            if not rec.get("traces"):
                r.whist.record(dispatch_ns)
            r.bhist.record(rec.get("batch", 0))
            if rec.get("fused"):
                r.fused += 1
            else:
                r.fallback += 1
            # under the lock: configure(ring=...) swaps the deque object,
            # and an append racing the swap would land on the orphan
            self.flight_ring.append(rec)

    def note_abandoned(self, rec: dict) -> None:
        """A submit whose handle was never completed: the record reaches
        the flight ring (submit-half data only, marked) but counts toward
        NO dispatch/rollup totals and carries no trace id — stamping the
        flushing publish's context onto a stale record would send an
        operator to the wrong publish."""
        rec["abandoned"] = True
        with self._lock:
            self.flight_ring.append(rec)

    def note_upload(self, kind: str, nbytes: int) -> None:
        """One device upload ('delta' scatter or 'full' repack+put)."""
        with self._lock:
            self.upload_counts[kind] = self.upload_counts.get(kind, 0) + 1
            self.upload_bytes[kind] = self.upload_bytes.get(kind, 0) + nbytes
            r = self._rollup()
            if kind == "delta":
                r.delta_bytes += nbytes
            else:
                r.full_bytes += nbytes

    def note_pad_floor(self, floor: int, old: int) -> None:
        """The matcher latched a new sticky pad floor (prewarm / change):
        log it with the current cumulative waste fraction and annotate the
        slow ring, so the cfg1 small-batch regime shows WHY it pays what
        it pays. Tracks the reported value directly — the autotuner's
        ladder LOWERS the floor too (broker/autotune.py), so a monotonic
        max here would misreport the live setting."""
        with self._lock:
            self.pad_floor = max(1, floor)
            waste = (round(1.0 - self.items_total / self.padded_total, 4)
                     if self.padded_total else 0.0)
        _LOG.info(
            "sticky pad floor %d -> %d (small batches pad up to this "
            "compiled shape; cumulative pad-waste fraction %.4f)",
            old, floor, waste)
        self._annotate_ring("device.pad_floor", {
            "floor": floor, "old": old, "pad_waste": waste})

    def rollup_summary(self, since: Optional[float] = None,
                       n: Optional[int] = None) -> dict:
        """Rollup CONSUMER API (the autotuner's signal source): merge the
        interval buckets at/after ``since`` (or the newest ``n``; the
        newest 6 by default) into one window summary — dispatch count,
        pad-waste fraction, dispatch p50/p99 and the batch-size quantiles,
        upload bytes, fused/fallback share, traces. Cheaper than
        ``snapshot()`` (no kernel tables, no HBM provider call — the
        provider may touch ``jax.live_arrays``) so a controller can poll
        it every few seconds."""
        with self._lock:
            rolls = list(self._rollups)
        if since is not None:
            rolls = [r for r in rolls if r.t + self.interval_s > since]
        elif n is not None:
            rolls = rolls[-max(0, n):]
        else:
            rolls = rolls[-6:]
        hist = Histogram()
        whist = Histogram()
        bhist = Histogram()
        out = {"intervals": len(rolls), "dispatches": 0, "items": 0,
               "padded": 0, "traces": 0, "fused": 0, "fallback": 0,
               "delta_bytes": 0, "full_bytes": 0}
        for r in rolls:
            out["dispatches"] += r.dispatches
            out["items"] += r.items
            out["padded"] += r.padded
            out["traces"] += r.traces
            out["fused"] += r.fused
            out["fallback"] += r.fallback
            out["delta_bytes"] += r.delta_bytes
            out["full_bytes"] += r.full_bytes
            hist.merge(r.hist)
            whist.merge(r.whist)
            bhist.merge(r.bhist)
        out["pad_waste"] = (round(1.0 - out["items"] / out["padded"], 4)
                            if out["padded"] else 0.0)
        out["p50_ms"] = round(hist.quantile(0.50) / 1e6, 3)
        out["p99_ms"] = round(hist.quantile(0.99) / 1e6, 3)
        out["warm_dispatches"] = whist.count
        out["warm_p99_ms"] = round(whist.quantile(0.99) / 1e6, 3)
        out["batch_p50"] = int(bhist.quantile(0.50))
        out["batch_p99"] = int(bhist.quantile(0.99))
        # the merged window's sparse batch histogram (upper-bound key →
        # count, same encoding as _Rollup.row) so consumers that merge
        # summaries (history samples, the offline fitter) keep the
        # mergeable-by-addition property
        out["batch_hist"] = {
            str(Histogram.bucket_upper(i)): c
            for i, c in enumerate(bhist.counts) if c
        }
        return out

    def _annotate_ring(self, op: str, detail: dict) -> None:
        """Slow-op ring annotation (the timeline operators read for stalls
        — same pattern as overload/failover/slo transitions)."""
        tele = self.telemetry
        if tele is not None and getattr(tele, "enabled", False):
            tele.slow_ops.append({
                "op": op, "ms": 0.0, "ts": round(time.time(), 3),
                "detail": detail,
            })

    # ------------------------------------------------------------ HBM model
    @staticmethod
    def live_device_arrays() -> Optional[dict]:
        """Reconciliation source: ``jax.live_arrays()`` totals plus the
        backend's own memory stats where the platform exposes them.
        None when jax is unavailable/too old (the model stands alone)."""
        try:
            import jax

            arrs = jax.live_arrays()
            out = {
                "live_arrays": len(arrs),
                "live_arrays_bytes": int(sum(
                    getattr(a, "nbytes", 0) or 0 for a in arrs)),
            }
            try:
                ms = jax.devices()[0].memory_stats()
                if ms and "bytes_in_use" in ms:
                    out["device_bytes_in_use"] = int(ms["bytes_in_use"])
            except Exception:
                pass
            return out
        except Exception:
            return None

    def hbm_snapshot(self) -> dict:
        """Occupancy model (matcher-reported breakdown) reconciled against
        the live-array census. ``modeled ≤ live`` always holds — jax holds
        more than the table (topic uploads in flight, jit constants) — and
        a modeled total far ABOVE live means the model went stale."""
        out: dict = {"modeled_bytes": 0}
        provider = self.hbm_provider
        if provider is not None:
            try:
                bd = provider() or {}
                out.update(bd)
                out["modeled_bytes"] = int(bd.get("total_bytes", 0))
            except Exception as e:  # a dead weak provider must not 500 /device
                out["provider_error"] = str(e)
        live = self.live_device_arrays()
        if live:
            out.update(live)
        return out

    # ------------------------------------------------------------ surfaces
    def snapshot(self) -> dict:
        """The `/api/v1/device` body: shape-stable whether enabled or not
        (zeros everywhere before any dispatch / with the profiler off)."""
        with self._lock:
            kernels = {
                k: {
                    "traces": self._kernel_traces[k],
                    "trace_ms": round(self._kernel_trace_ns.get(k, 0) / 1e6, 3),
                    "keys": sorted(self._key_rows.get(k, []),
                                   key=lambda r: -r["trace_ms"])[:8],
                }
                for k in sorted(self._kernel_traces)
            }
            rollups = [r.row() for r in self._rollups]
            recent = Histogram()
            for r in list(self._rollups)[-6:]:
                recent.merge(r.hist)
            snap = {
                "enabled": self.enabled,
                "compile": {
                    "traces": self.traces,
                    "cache_hits": self.cache_hits,
                    "trace_ms_total": round(self.trace_ns_total / 1e6, 3),
                    "storms": self.storms,
                    "last_storm": self.last_storm,
                    "storm_n": self.storm_n,
                    "storm_window_s": self.storm_window,
                    "kernels": kernels,
                },
                "dispatch": {
                    "dispatches": self.dispatches,
                    "items": self.items_total,
                    "padded_items": self.padded_total,
                    "pad_waste": round(
                        1.0 - self.items_total / self.padded_total, 4)
                    if self.padded_total else 0.0,
                    "pad_floor": self.pad_floor,
                    "fused": self.fused_total,
                    "fallback": self.fallback_total,
                    "p50_ms": round(recent.quantile(0.50) / 1e6, 3),
                    "p99_ms": round(recent.quantile(0.99) / 1e6, 3),
                    "interval_s": self.interval_s,
                    "rollups": rollups,
                },
                "uploads": {
                    "delta": self.upload_counts.get("delta", 0),
                    "full": self.upload_counts.get("full", 0),
                    "delta_bytes": self.upload_bytes.get("delta", 0),
                    "full_bytes": self.upload_bytes.get("full", 0),
                },
                "flight_len": len(self.flight_ring),
                "flight_cap": self.ring_cap,
                "dumps": list(self.dumps_log),
            }
        snap["hbm"] = self.hbm_snapshot()
        return snap

    def flight(self) -> List[dict]:
        with self._lock:  # concurrent ring appends (executor threads)
            return list(self.flight_ring)

    @staticmethod
    def merge_snapshots(base: dict, others: List[dict]) -> dict:
        """Cluster merge (`/api/v1/device/sum`): counters sum, pad waste is
        recomputed from the summed item/padded totals, HBM bytes sum to a
        fleet total. Per-kernel key detail stays per-node (fetch each
        node's `/api/v1/device` for it)."""
        others = list(others)
        out = {
            "nodes": 1 + len(others),
            "enabled": bool(base.get("enabled", False)),
            "compile": {"traces": 0, "cache_hits": 0, "trace_ms_total": 0.0,
                        "storms": 0},
            "dispatch": {"dispatches": 0, "items": 0, "padded_items": 0,
                         "fused": 0, "fallback": 0},
            "uploads": {"delta": 0, "full": 0, "delta_bytes": 0,
                        "full_bytes": 0},
            "hbm": {"modeled_bytes": 0},
        }
        for snap in [base, *others]:
            c = snap.get("compile") or {}
            for k in out["compile"]:
                out["compile"][k] = round(out["compile"][k] + c.get(k, 0), 3)
            d = snap.get("dispatch") or {}
            for k in out["dispatch"]:
                out["dispatch"][k] += d.get(k, 0)
            u = snap.get("uploads") or {}
            for k in out["uploads"]:
                out["uploads"][k] += u.get(k, 0)
            out["hbm"]["modeled_bytes"] += (snap.get("hbm") or {}).get(
                "modeled_bytes", 0)
        padded = out["dispatch"]["padded_items"]
        out["dispatch"]["pad_waste"] = (
            round(1.0 - out["dispatch"]["items"] / padded, 4) if padded
            else 0.0)
        return out

    def prometheus_lines(self, labels: str) -> List[str]:
        """`rmqtt_device_*` exposition families (grammar-pinned by the
        scrape test like every other exporter)."""
        with self._lock:
            kt = dict(self._kernel_traces)
            rows = [
                ("rmqtt_device_jit_traces_total", "counter", self.traces),
                ("rmqtt_device_jit_cache_hits_total", "counter",
                 self.cache_hits),
                ("rmqtt_device_jit_trace_seconds_total", "counter",
                 format(self.trace_ns_total * 1e-9, "g")),
                ("rmqtt_device_retrace_storms_total", "counter", self.storms),
                ("rmqtt_device_dispatches_total", "counter", self.dispatches),
                ("rmqtt_device_fused_dispatches_total", "counter",
                 self.fused_total),
                ("rmqtt_device_upload_delta_bytes_total", "counter",
                 self.upload_bytes.get("delta", 0)),
                ("rmqtt_device_upload_full_bytes_total", "counter",
                 self.upload_bytes.get("full", 0)),
                ("rmqtt_device_pad_waste_ratio", "gauge",
                 round(1.0 - self.items_total / self.padded_total, 4)
                 if self.padded_total else 0.0),
                ("rmqtt_device_pad_floor", "gauge", self.pad_floor),
            ]
        out: List[str] = []
        for name, typ, val in rows:
            out.append(f"# TYPE {name} {typ}")
            out.append(f"{name}{{{labels}}} {val}")
        hbm = self.hbm_snapshot()
        out.append("# TYPE rmqtt_device_hbm_modeled_bytes gauge")
        out.append(f"rmqtt_device_hbm_modeled_bytes{{{labels}}} "
                   f"{hbm.get('modeled_bytes', 0)}")
        if kt:
            out.append("# TYPE rmqtt_device_kernel_traces_total counter")
            for kernel, n in sorted(kt.items()):
                out.append(
                    f'rmqtt_device_kernel_traces_total{{{labels},'
                    f'kernel="{prom_sanitize(kernel)}"}} {n}')
        return out

    # ------------------------------------------------------------- dumping
    def dump(self, reason: str) -> dict:
        """Freeze the flight recorder + snapshot into one artifact dict."""
        return {
            "schema": DUMP_SCHEMA,
            "reason": reason,
            "ts": round(time.time(), 3),
            "snapshot": self.snapshot(),
            "flight": self.flight(),
        }

    def dump_to(self, path: str, reason: str) -> Optional[str]:
        """Write a dump artifact; → the path, or None on failure (a dump
        must never take the caller down with it)."""
        try:
            d = self.dump(reason)
            dirname = os.path.dirname(path)
            if dirname:
                os.makedirs(dirname, exist_ok=True)
            with open(path, "w") as f:
                json.dump(d, f, indent=1)
            self.last_dump = d
            self.dumps_log.append({"reason": reason, "ts": d["ts"],
                                   "path": path})
            _LOG.warning("device flight recorder dumped (%s) -> %s",
                         reason, path)
            return path
        except Exception as e:  # pragma: no cover - disk-full etc.
            _LOG.warning("flight-recorder dump failed (%s): %s", reason, e)
            return None

    def auto_dump(self, reason: str) -> None:
        """Event-triggered dump (failover trip / fused-verify disagreement /
        retrace storm). Rate-limited per reason so a flapping trigger can't
        spam the disk, and OFFLOADED to a daemon thread: the triggers fire
        from the asyncio event loop (failover transition) and the match hot
        path (storm in note_jit) — serializing the ring + a disk write
        there would stall the broker at exactly its worst moment. With no
        ``dump_dir`` the artifact stays in memory (``last_dump``) and on
        the dumps log."""
        if not self.enabled:
            return
        now = time.monotonic()
        with self._lock:
            if now - self._last_dump_mono.get(reason, -1e18) < 30.0:
                return
            self._last_dump_mono[reason] = now
        try:
            threading.Thread(target=self._auto_dump_now, args=(reason,),
                             name="rmqtt-devprof-dump", daemon=True).start()
        except Exception as e:  # pragma: no cover - thread exhaustion
            _LOG.warning("flight-recorder auto-dump thread failed (%s): %s",
                         reason, e)

    def _auto_dump_now(self, reason: str) -> None:
        if self.dump_dir:
            path = os.path.join(
                self.dump_dir,
                f"devprof_{prom_sanitize(reason)}_{int(time.time())}.json")
            self.dump_to(path, reason)
            return
        self.last_dump = self.dump(reason)
        self.dumps_log.append({"reason": reason,
                               "ts": self.last_dump["ts"], "path": None})
        _LOG.warning("device flight recorder dumped in memory (%s); set "
                     "RMQTT_DEVPROF_DIR for an on-disk artifact", reason)


#: process-global instance — matchers guard on ``DEVPROF.enabled`` (one
#: attribute check per jit seam when off); the broker configures it from
#: the [observability] section, the bench enables it directly
DEVPROF = DeviceProfiler(
    enabled=os.environ.get("RMQTT_DEVICE_PROFILE", "") == "1",
    dump_dir=os.environ.get("RMQTT_DEVPROF_DIR") or None,
)
