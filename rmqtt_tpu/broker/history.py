"""Unified telemetry history: the broker's black-box flight recorder.

Every plane is instrumented — latency histograms (PR 2), devprof (PR 10),
hostprof (PR 13), overload/SLO state machines, the autotune journal — but
each keeps its own short in-memory rollup ring: nothing is queryable
*across* planes, and nothing survives a restart. Regressions surface as
trends across phases, not point snapshots (the IoT-broker benchmarking
literature is unanimous on this), so "what changed at time T" needs a
timeline, not eight disconnected `/api/v1/*` bodies.

This service closes that gap with one fixed-interval collector that
snapshots every plane into a single schema'd sample row
(``rmqtt_tpu.history_sample/1``):

- every ``stats()`` gauge (the cross-plane shape-stable surface);
- tracked ``metrics`` counters delta-encoded into per-second ``.rate``
  series (a cumulative counter is useless on a timeline; its rate is the
  signal);
- devprof/hostprof rollup summaries since the previous sample
  (dispatch p50/p99, pad waste + the mergeable batch histogram; loop lag,
  GC pauses, blocking incidents);
- per-objective SLO burn rates, and the collector's own cost
  (``history.collect_ms`` — which the ``history.collect`` failpoint can
  inflate, giving chaos drills a provokable latency step).

Samples land in a bounded in-memory ring *and*, when ``history_dir`` is
set, in CRC-framed on-disk segment files (``seg-NNNNNNNNNN.hist``) with
rotation + retention — the exact framing discipline of the PR 12
durability journal (``frame_record``/``decode_record``), so a kill-9
mid-append loses at most the torn tail and a cold start reads every
intact frame back into the ring.

On top of the timeline:

- **Range queries** — ``GET /api/v1/history?series=&from=&to=&step=``
  with step-bucket downsampling, and ``/api/v1/history/sum`` merging
  node timelines over the existing ``what=`` DATA-query path (counters
  sum, ``*_ms``/``*_p50``/``*_p99``/``.rate`` average, sparse bucket
  histograms key-add, ``*_state`` takes the worst).
- **Anomaly annotation** — per-tracked-series EWMA mean + EWMA absolute
  deviation (a robust MAD-style scale); a breach lands a row on the
  shared slow-op ring (the cross-plane correlation timeline
  ops_doctor joins), fires the ``SERVER_ANOMALY`` hook
  (``SERVER_SLO``-style), bumps ``rmqtt_history_anomalies_total{series}``
  and records which devprof/hostprof auto-dumps landed in the same
  window — the "p99 stepped 2.1x, 3 s after a retrace storm" join
  becomes mechanical.

House pattern: ``[observability] history_*`` knobs, default ON with a
pinned low-overhead budget (``bench.py --config 17`` bounds the
collector at <=2% on the publish path); ``history = false`` costs one
attribute check and every surface stays shape-stable.
"""

from __future__ import annotations

import asyncio
import logging
import os
import re
import struct
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from rmqtt_tpu.broker.durability import decode_record, frame_record
from rmqtt_tpu.broker.hooks import HookType
from rmqtt_tpu.utils.failpoints import FAILPOINTS

log = logging.getLogger("rmqtt_tpu.history")

SCHEMA = "rmqtt_tpu.history_sample/1"

_FP_COLLECT = FAILPOINTS.register("history.collect")

#: segment file naming — monotonic sequence number, lexicographic sort ==
#: chronological sort (the recovery scan depends on it)
_SEG_RE = re.compile(r"^seg-(\d{10})\.hist$")

#: metrics counters whose per-second rate rides the sample (dotted names
#: from broker/metrics.py Metrics; the timeline wants rates, not totals)
RATE_COUNTERS = ("publish.received", "messages.delivered",
                 "messages.dropped")

#: series watched by the anomaly annotator. Every entry must be a key the
#: collector actually emits; zero-change series never breach (the EWMA
#: residual is exactly 0 and the deviation floor is strictly positive)
TRACKED_SERIES = (
    "publish_e2e_p99_ms",
    "routing_match_p99_ms",
    "host_loop_lag_p99_ms",
    "device.p99_ms",
    "history.collect_ms",
    "rss_mb",
    "publish.received.rate",
    "hotkeys_top1_share",
)

#: devprof/hostprof auto-dumps within this many seconds of a breach are
#: attached to the anomaly row by reference (path + reason)
DUMP_CORRELATE_WINDOW_S = 30.0


def _merge_value(key: str, values: List[Any]):
    """One downsample/cluster-merge cell: how N values of series ``key``
    combine. Shared by step-bucketing and /sum so a downsampled local
    query and a cluster merge agree on semantics."""
    dicts = [v for v in values if isinstance(v, dict)]
    if dicts:  # sparse bucket histogram (e.g. device.batch_hist): key-add
        out: Dict[str, int] = {}
        for d in dicts:
            for k, c in d.items():
                try:
                    out[k] = out.get(k, 0) + int(c)
                except (TypeError, ValueError):
                    continue
        return out
    nums = [v for v in values if isinstance(v, (int, float))]
    if not nums:
        return values[0] if values else None
    if key.endswith("_state") or key.endswith("_state_value"):
        return max(nums)  # worst state wins
    return round(sum(nums) / len(nums), 3)


def _sum_value(key: str, values: List[Any]):
    """Cluster-merge cell (/sum): like :func:`_merge_value` but counters
    SUM across nodes; quantiles/averages/rates stay averaged, states
    stay worst-of."""
    dicts = [v for v in values if isinstance(v, dict)]
    if dicts:
        return _merge_value(key, values)
    nums = [v for v in values if isinstance(v, (int, float))]
    if not nums:
        return values[0] if values else None
    if key.endswith("_state") or key.endswith("_state_value"):
        return max(nums)
    if (key.endswith(("_ms", "_p50", "_p99", "_ema", ".rate", "_waste",
                      "_burn", "_share"))
            or key == "t"):
        return round(sum(nums) / len(nums), 3)
    total = sum(nums)
    return round(total, 3) if isinstance(total, float) else total


class _Baseline:
    """Per-series EWMA mean + EWMA absolute deviation (a streaming
    MAD-style scale estimate — robust to single spikes, adapts after a
    sustained level shift so one regression is one episode, not an
    alarm that never clears)."""

    __slots__ = ("mean", "dev", "n")

    def __init__(self) -> None:
        self.mean = 0.0
        self.dev = 0.0
        self.n = 0

    def observe(self, x: float, alpha: float = 0.3
                ) -> Tuple[bool, float, float]:
        """Feed one sample → (breach_possible_residual, mean, dev) BEFORE
        the baseline absorbs ``x`` (detection precedes adaptation)."""
        if self.n == 0:
            self.mean = x
        resid = abs(x - self.mean)
        mean, dev = self.mean, self.dev
        self.dev = (1 - alpha) * self.dev + alpha * resid
        self.mean = (1 - alpha) * self.mean + alpha * x
        self.n += 1
        return resid, mean, dev


class HistoryService:
    """Broker-wide telemetry timeline: collector + ring + segments +
    range queries + anomaly annotation. Constructed unconditionally by
    ``ServerContext`` (shape-stable surfaces); everything is a no-op
    behind one ``enabled`` check when ``[observability] history=false``."""

    def __init__(self, ctx, cfg) -> None:
        self.ctx = ctx
        self.enabled = bool(cfg.history_enable)
        self.interval_s = max(0.5, float(cfg.history_interval_s))
        self.dir = str(cfg.history_dir or "")
        self.segment_rows = max(16, int(cfg.history_segment_rows))
        self.retention_segments = max(1, int(cfg.history_retention_segments))
        self.anomaly_enable = bool(cfg.history_anomaly_enable)
        self.anomaly_k = max(1.0, float(cfg.history_anomaly_k))
        self.anomaly_warmup = max(2, int(cfg.history_anomaly_warmup))
        self.ring: deque = deque(maxlen=max(8, int(cfg.history_ring_max)))
        self.anomalies: deque = deque(maxlen=256)
        # counters (the stats()/Prometheus surface)
        self.samples_total = 0
        self.anomalies_total: Dict[str, int] = {s: 0 for s in TRACKED_SERIES}
        self.segments_written = 0
        self.recovered_rows = 0
        self.torn_tails = 0
        self.retention_deleted = 0
        # collector state
        self._task: Optional[asyncio.Task] = None
        self._last_counters: Dict[str, int] = {}
        self._last_t: Optional[float] = None
        self._baselines: Dict[str, _Baseline] = {}
        # segment writer state
        self._fh = None
        self._seg_seq = 0
        self._seg_rows = 0
        if self.enabled and self.dir:
            self._recover()
            self._open_segment()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        """Arm the collector task on the RUNNING loop (sync, like every
        plane armed from ``ServerContext.start``). Disabled = no-op."""
        if not self.enabled:
            return
        if self._task is not None and not self._task.done():
            return
        self._task = asyncio.get_running_loop().create_task(
            self._run(), name="history-collector")

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._close_segment()

    async def _run(self) -> None:
        # sample at tick START (then sleep): the timeline's first row
        # lands at broker start, and a short-lived arm window (tests,
        # the cfg17 paired bench) still contains a real collection
        while True:
            try:
                self.collect_once()
            except Exception:
                log.exception("history collection failed")
            await asyncio.sleep(self.interval_s)

    # ------------------------------------------------------------ collector
    def collect_once(self) -> Optional[dict]:
        """Take one sample NOW: snapshot every plane into a flat row,
        append it to the ring (+ segment), run the anomaly pass. Public
        and synchronous so tests and drills drive ticks directly."""
        if not self.enabled:
            return None
        t0 = time.perf_counter()
        if _FP_COLLECT.action is not None:  # chaos seam: a provokable
            _FP_COLLECT.fire_sync()         # collector latency step
        now = time.time()
        row: Dict[str, Any] = {"t": round(now, 3)}
        row.update(self.ctx.stats().to_json())
        # counter deltas → per-second rates
        dt = (now - self._last_t) if self._last_t else None
        for name in RATE_COUNTERS:
            cur = self.ctx.metrics.get(name)
            prev = self._last_counters.get(name)
            rate = 0.0
            if dt and dt > 0 and prev is not None:
                rate = max(0.0, (cur - prev) / dt)
            row[name + ".rate"] = round(rate, 3)
            self._last_counters[name] = cur
        # device plane: the window summary since the previous sample
        try:
            from rmqtt_tpu.broker.devprof import DEVPROF

            dv = DEVPROF.rollup_summary(since=self._last_t)
            for k in ("dispatches", "items", "padded", "pad_waste",
                      "p50_ms", "p99_ms", "traces", "batch_hist"):
                if k in dv:
                    row["device." + k] = dv[k]
        except Exception:
            pass
        # host plane: loop lag / GC / blocking over the same window
        try:
            from rmqtt_tpu.broker.hostprof import HOSTPROF

            hv = HOSTPROF.rollup_summary(since=self._last_t)
            for k in ("ticks", "laggy", "lag_p50_ms", "lag_p99_ms",
                      "gc_pauses", "gc_pause_ms", "blocked"):
                if k in hv:
                    row["host." + k] = hv[k]
        except Exception:
            pass
        # hot-key attribution (broker/hotkeys.py): top-1/top-8 share +
        # distinct-key estimate per key space — a sudden skew shift
        # (hotkeys_top1_share is a tracked series) is the earliest
        # noisy-neighbor signal, often ahead of any latency breach
        try:
            hk = getattr(self.ctx, "hotkeys", None)
            if hk is not None and hk.enabled:
                hv = hk.history_summary()
                row["hotkeys_top1_share"] = hv.pop("top1_share", 0.0)
                for k, v in hv.items():
                    row["hotkeys." + k] = v
        except Exception:
            pass
        # SLO burn rates per objective (slo_state already rides stats())
        try:
            for obj in self.ctx.slo.snapshot().get("objectives") or ():
                name = obj.get("name")
                if not name:
                    continue
                row[f"slo.{name}.fast_burn"] = float(
                    (obj.get("fast") or {}).get("burn_rate", 0.0))
                row[f"slo.{name}.slow_burn"] = float(
                    (obj.get("slow") or {}).get("burn_rate", 0.0))
        except Exception:
            pass
        row["history.collect_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 3)
        self._last_t = now
        self.ring.append(row)
        self.samples_total += 1
        self._persist(["s", row])
        if self.anomaly_enable:
            self._annotate(row)
        return row

    # ------------------------------------------------------------ anomalies
    def _annotate(self, row: dict) -> None:
        for series in TRACKED_SERIES:
            x = row.get(series)
            if not isinstance(x, (int, float)):
                continue
            bl = self._baselines.get(series)
            if bl is None:
                bl = self._baselines[series] = _Baseline()
            n_before = bl.n
            resid, mean, dev = bl.observe(float(x))
            if n_before < self.anomaly_warmup:
                continue
            # strictly positive scale floor: a flat series (dev -> 0) can
            # never breach, and tiny baselines don't alarm on noise
            devf = max(dev, 0.05 * abs(mean), 1e-3)
            if resid <= self.anomaly_k * devf:
                continue
            anomaly = {
                "ts": row["t"],
                "series": series,
                "value": round(float(x), 3),
                "baseline": round(mean, 3),
                "dev": round(dev, 3),
                "factor": round(resid / devf, 2),
                "dumps": self._dump_refs(row["t"]),
            }
            self.anomalies.append(anomaly)
            self.anomalies_total[series] = (
                self.anomalies_total.get(series, 0) + 1)
            self._persist(["a", anomaly])
            self._fire(series, float(x), anomaly)

    @staticmethod
    def _dump_refs(ts: float,
                   window_s: float = DUMP_CORRELATE_WINDOW_S) -> List[dict]:
        """devprof/hostprof auto-dumps within the window, by reference —
        the breach row names the postmortem artifacts that explain it."""
        refs: List[dict] = []
        try:
            from rmqtt_tpu.broker.devprof import DEVPROF
            from rmqtt_tpu.broker.hostprof import HOSTPROF

            for plane, prof in (("device", DEVPROF), ("host", HOSTPROF)):
                for d in list(getattr(prof, "dumps_log", ()) or ()):
                    if abs(float(d.get("ts", 0)) - ts) <= window_s:
                        refs.append({"plane": plane,
                                     "reason": d.get("reason"),
                                     "path": d.get("path"),
                                     "ts": d.get("ts")})
        except Exception:
            pass
        return refs

    def _fire(self, series: str, value: float, anomaly: dict) -> None:
        """Slow-op ring row + SERVER_ANOMALY hook — the exact transition
        idiom of slo.py/overload.py, so anomalies join the shared
        correlation timeline every other plane annotates."""
        tele = getattr(self.ctx, "telemetry", None)
        if tele is not None and getattr(tele, "enabled", False):
            tele.slow_ops.append({
                "op": "history.anomaly", "ms": 0.0,
                "ts": round(time.time(), 3),
                "detail": {"series": series, "value": anomaly["value"],
                           "baseline": anomaly["baseline"],
                           "factor": anomaly["factor"],
                           "dumps": len(anomaly["dumps"])},
            })
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # collect_once() driven synchronously in tests
        loop.create_task(self.ctx.hooks.fire(
            HookType.SERVER_ANOMALY, series, value, anomaly))

    # ------------------------------------------------------------ segments
    def _seg_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"seg-{seq:010d}.hist")

    def _recover(self) -> None:
        """Cold-start read-back: newest ``retention_segments`` files,
        every CRC-intact frame; the first torn/corrupt frame in a file
        drops that file's tail (the crash model — nothing framed after a
        tear is trusted). Recovered samples refill the ring so a
        restarted broker serves its pre-restart timeline."""
        try:
            os.makedirs(self.dir, exist_ok=True)
            names = sorted(n for n in os.listdir(self.dir)
                           if _SEG_RE.match(n))
        except OSError:
            return
        for name in names:
            self._seg_seq = max(self._seg_seq,
                                int(_SEG_RE.match(name).group(1)))
        for name in names[-self.retention_segments:]:
            rows, anoms, torn = read_segment(os.path.join(self.dir, name))
            for r in rows:
                self.ring.append(r)
                self.recovered_rows += 1
            for a in anoms:
                self.anomalies.append(a)
                if a.get("series") in self.anomalies_total:
                    self.anomalies_total[a["series"]] += 1
            self.torn_tails += torn
        if self.recovered_rows:
            last = self.ring[-1]
            self._last_t = float(last.get("t") or 0) or None
            log.info("history recovered %d sample(s), %d torn tail(s) "
                     "from %s", self.recovered_rows, self.torn_tails,
                     self.dir)

    def _open_segment(self) -> None:
        self._seg_seq += 1
        try:
            self._fh = open(self._seg_path(self._seg_seq), "ab")
        except OSError:
            log.exception("history segment open failed; persistence off")
            self._fh = None
            return
        self._seg_rows = 0
        self.segments_written += 1
        self._enforce_retention()

    def _close_segment(self) -> None:
        fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass

    def _persist(self, event: list) -> None:
        if self._fh is None:
            return
        try:
            self._fh.write(frame_record(event))
            self._fh.flush()
        except (OSError, ValueError):
            log.exception("history append failed; persistence off")
            self._close_segment()
            return
        if event[0] == "s":
            self._seg_rows += 1
            if self._seg_rows >= self.segment_rows:
                self._close_segment()
                self._open_segment()

    def _enforce_retention(self) -> None:
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if _SEG_RE.match(n))
            for name in names[:-self.retention_segments]:
                os.unlink(os.path.join(self.dir, name))
                self.retention_deleted += 1
        except OSError:
            pass

    # -------------------------------------------------------------- queries
    def query(self, series=None, frm=None, to=None, step=None) -> dict:
        """The `/api/v1/history` body: ring samples filtered to
        [from, to], optionally projected to ``series`` (comma-separated;
        ``t`` always rides) and step-bucket downsampled (numeric avg,
        ``*_state`` worst, sparse histograms key-add). Shape-stable when
        disabled: same keys, empty timelines."""
        samples = [dict(r) for r in self.ring]
        anomalies = list(self.anomalies)
        try:
            lo = float(frm) if frm not in (None, "") else None
            hi = float(to) if to not in (None, "") else None
            step_s = float(step) if step not in (None, "") else None
        except (TypeError, ValueError):
            lo = hi = step_s = None
        if lo is not None:
            samples = [r for r in samples if r["t"] >= lo]
            anomalies = [a for a in anomalies if a["ts"] >= lo]
        if hi is not None:
            samples = [r for r in samples if r["t"] <= hi]
            anomalies = [a for a in anomalies if a["ts"] <= hi]
        names: Optional[List[str]] = None
        if series:
            names = [s.strip() for s in str(series).split(",") if s.strip()]
            samples = [
                {"t": r["t"], **{k: r[k] for k in names if k in r}}
                for r in samples
            ]
        if step_s and step_s > 0:
            buckets: Dict[int, List[dict]] = {}
            for r in samples:
                buckets.setdefault(int(r["t"] // step_s), []).append(r)
            down = []
            for b in sorted(buckets):
                rows = buckets[b]
                keys = {k for r in rows for k in r if k != "t"}
                out = {"t": round(b * step_s, 3), "n": len(rows)}
                for k in sorted(keys):
                    out[k] = _merge_value(
                        k, [r[k] for r in rows if k in r])
                down.append(out)
            samples = down
        return {
            "schema": SCHEMA,
            "enabled": self.enabled,
            "interval_s": self.interval_s,
            "node": getattr(self.ctx.cfg, "node_id", 0),
            "count": len(samples),
            "samples": samples,
            "anomalies": anomalies,
            "series": names,
            "step": step_s,
            "persistence": {
                "dir": self.dir or None,
                "segments_written": self.segments_written,
                "recovered_rows": self.recovered_rows,
                "torn_tails": self.torn_tails,
            },
        }

    @staticmethod
    def merge_snapshots(base: dict, others: List[dict]) -> dict:
        """Cluster merge (`/api/v1/history/sum`): node timelines align on
        step buckets (the query ``step`` or the collection interval);
        within a bucket counters SUM, ``*_ms``/quantile/``.rate`` series
        average, sparse bucket histograms key-add and ``*_state`` takes
        the worst. Anomalies concatenate (they are per-node facts)."""
        snaps = [base, *list(others)]
        step = (base.get("step") or base.get("interval_s") or 5.0)
        buckets: Dict[int, List[dict]] = {}
        for snap in snaps:
            for r in snap.get("samples") or ():
                if isinstance(r, dict) and isinstance(
                        r.get("t"), (int, float)):
                    buckets.setdefault(int(r["t"] // step), []).append(r)
        samples = []
        for b in sorted(buckets):
            rows = buckets[b]
            keys = {k for r in rows for k in r if k not in ("t", "n")}
            out: Dict[str, Any] = {"t": round(b * step, 3), "n": len(rows)}
            for k in sorted(keys):
                out[k] = _sum_value(k, [r[k] for r in rows if k in r])
            samples.append(out)
        anomalies = sorted(
            (dict(a, node=snap.get("node", i))
             for i, snap in enumerate(snaps)
             for a in snap.get("anomalies") or ()),
            key=lambda a: a.get("ts", 0))
        return {
            "schema": SCHEMA,
            "nodes": len(snaps),
            "enabled": any(s.get("enabled") for s in snaps),
            "step": step,
            "count": len(samples),
            "samples": samples,
            "anomalies": anomalies,
        }

    # ------------------------------------------------------------- surfaces
    def snapshot(self) -> dict:
        """Small gauge block for ``ServerContext.stats()``."""
        return {
            "samples": self.samples_total,
            "anomalies": sum(self.anomalies_total.values()),
            "segments": self.segments_written,
            "recovered_rows": self.recovered_rows,
        }

    def prometheus_lines(self, labels: str) -> List[str]:
        """Exposition counters. One ``{series=...}`` row per tracked
        series, zeros included — the scrape shape never depends on which
        series happened to breach."""
        out = [
            "# TYPE rmqtt_history_samples_recorded_total counter",
            f"rmqtt_history_samples_recorded_total{{{labels}}} "
            f"{self.samples_total}",
            "# TYPE rmqtt_history_anomalies_total counter",
        ]
        for series in TRACKED_SERIES:
            out.append(
                f'rmqtt_history_anomalies_total{{{labels},'
                f'series="{series}"}} {self.anomalies_total.get(series, 0)}')
        return out


# ---------------------------------------------------------------- offline
def read_segment(path: str) -> Tuple[List[dict], List[dict], int]:
    """One segment file → (samples, anomalies, torn_frames). Streaming
    frame scan: 8-byte header, exactly ``len`` payload bytes, CRC check
    via the shared ``decode_record``; the first bad frame ends the file
    (everything after a tear is untrusted). Shared by recovery and the
    offline renderers (history_report / autotune_replay / bench_trend)."""
    rows: List[dict] = []
    anomalies: List[dict] = []
    torn = 0
    try:
        with open(path, "rb") as f:
            while True:
                head = f.read(8)
                if not head:
                    break
                if len(head) < 8:
                    torn += 1
                    break
                _crc, ln = struct.unpack("<II", head)
                if ln > 1 << 24:  # corrupt length: nothing sane is 16MB
                    torn += 1
                    break
                payload = f.read(ln)
                ev = decode_record(head + payload)
                if ev is None:
                    torn += 1
                    break
                if ev[0] == "s" and len(ev) > 1 and isinstance(ev[1], dict):
                    rows.append(ev[1])
                elif ev[0] == "a" and len(ev) > 1 and isinstance(ev[1], dict):
                    anomalies.append(ev[1])
    except OSError:
        return rows, anomalies, torn + 1
    return rows, anomalies, torn


def load_dir(dirpath: str) -> Tuple[List[dict], List[dict], int]:
    """Every segment in a history dir, chronological → merged
    (samples, anomalies, torn_frames)."""
    rows: List[dict] = []
    anomalies: List[dict] = []
    torn = 0
    try:
        names = sorted(n for n in os.listdir(dirpath) if _SEG_RE.match(n))
    except OSError:
        return rows, anomalies, 0
    for name in names:
        r, a, t = read_segment(os.path.join(dirpath, name))
        rows.extend(r)
        anomalies.extend(a)
        torn += t
    return rows, anomalies, torn
