"""Hook/event bus: the plugin seam of the broker.

Mirrors the reference hook system (`/root/reference/rmqtt/src/hook.rs`):
the hook ``Type`` catalog (:352-405), priority-ordered handler chains with
short-circuiting (:73-110 — highest priority first; a handler returning
``proceed=False`` stops the chain), and the ``(Parameter, HookResult)``
calling convention (:458-583) flattened into
``async handler(htype, *args, prev) -> HookResult | None``.
"""

from __future__ import annotations

import asyncio
import enum
import itertools
from dataclasses import dataclass
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple


class HookType(enum.Enum):
    # lifecycle (hook.rs:352-405; string names match the reference's From<&str>)
    BEFORE_STARTUP = "before_startup"
    SESSION_CREATED = "session_created"
    SESSION_TERMINATED = "session_terminated"
    SESSION_SUBSCRIBED = "session_subscribed"
    SESSION_UNSUBSCRIBED = "session_unsubscribed"
    CLIENT_AUTHENTICATE = "client_authenticate"
    CLIENT_CONNECT = "client_connect"
    CLIENT_CONNACK = "client_connack"
    CLIENT_CONNECTED = "client_connected"
    CLIENT_DISCONNECTED = "client_disconnected"
    CLIENT_SUBSCRIBE = "client_subscribe"
    CLIENT_UNSUBSCRIBE = "client_unsubscribe"
    CLIENT_SUBSCRIBE_CHECK_ACL = "client_subscribe_check_acl"
    CLIENT_KEEPALIVE = "client_keepalive"
    MESSAGE_PUBLISH_CHECK_ACL = "message_publish_check_acl"
    MESSAGE_PUBLISH = "message_publish"
    MESSAGE_DELIVERED = "message_delivered"
    MESSAGE_ACKED = "message_acked"
    MESSAGE_DROPPED = "message_dropped"
    MESSAGE_EXPIRY_CHECK = "message_expiry_check"
    MESSAGE_NONSUBSCRIBED = "message_nonsubscribed"
    OFFLINE_MESSAGE = "offline_message"
    OFFLINE_INFLIGHT_MESSAGES = "offline_inflight_messages"
    GRPC_MESSAGE_RECEIVED = "grpc_message_received"
    # overload-controller state change (broker/overload.py): fired with
    # (old_state_name, new_state_name, snapshot) on every transition
    SERVER_OVERLOAD = "server_overload"
    # SLO-engine objective state change (broker/slo.py): fired with
    # (objective_name, old_state_name, new_state_name, objective_row) on
    # every burn/exhaustion transition
    SERVER_SLO = "server_slo"
    # telemetry-history anomaly (broker/history.py): fired with
    # (series_name, sample_value, anomaly_row) on every baseline breach
    SERVER_ANOMALY = "server_anomaly"
    # hot-key attribution alert (broker/hotkeys.py): fired with
    # (space_name, key, alert_row) when a key space's top-1 share
    # crosses hotkeys_alert_share (transition-edged: once per episode)
    SERVER_HOTKEY = "server_hotkey"


@dataclass
class HookResult:
    """Outcome of a handler chain (reference HookResult, hook.rs:458-583).

    ``proceed=False`` short-circuits remaining handlers. ``value`` carries the
    type-specific payload (auth result, modified packet, ACL verdict, ...).
    """

    proceed: bool = True
    value: Any = None


# handler(htype, args: tuple, prev) → HookResult | None (None = pass-through).
# `args` arrives as ONE tuple so hook types can carry any payload arity
# without breaking handlers (the reference's typed Parameter enum flattened).
Handler = Callable[[Any, tuple, Any], Awaitable[Optional[HookResult]]]

_seq = itertools.count()


class HookRegistry:
    """Priority-ordered handler chains per hook type (DefaultHookManager,
    hook.rs:621-624). Higher priority runs first; ties break by registration
    order."""

    def __init__(self) -> None:
        self._handlers: Dict[HookType, List[Tuple[int, int, Handler]]] = {}

    def register(self, htype: HookType, handler: Handler, priority: int = 0) -> Callable[[], None]:
        entry = (-priority, next(_seq), handler)
        chain = self._handlers.setdefault(htype, [])
        chain.append(entry)
        chain.sort(key=lambda e: (e[0], e[1]))

        def unregister() -> None:
            try:
                chain.remove(entry)
            except ValueError:
                pass

        return unregister

    def handlers(self, htype: HookType) -> List[Handler]:
        return [h for _, _, h in self._handlers.get(htype, [])]

    async def fire(self, htype: HookType, *args: Any, initial: Any = None) -> Any:
        """Run the chain; returns the final value (hook.rs:73-110 semantics)."""
        value = initial
        for handler in self.handlers(htype):
            res = await handler(htype, args, value)
            if res is None:
                continue
            value = res.value if res.value is not None else value
            if not res.proceed:
                break
        return value
