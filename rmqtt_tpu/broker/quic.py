"""QUIC listener seam (reference `rmqtt-net/src/quic.rs:1-60`,
`rmqtt-net/src/builder.rs:486-583` ``bind_quic``).

The reference serves MQTT over one bidirectional QUIC stream per
connection (quinn). This image ships no QUIC stack — stdlib ``ssl``
cannot drive a QUIC handshake and pip installs are off — so the
decision, recorded here and in COMPONENTS.md, is a **stubbed seam**:

- the broker accepts ``quic_port`` config and will serve MQTT over any
  registered :class:`QuicBackend` exactly like its TCP path (the session
  layer is transport-agnostic: it consumes an asyncio reader/writer
  pair, which is also what one QUIC bidi stream presents);
- without a backend, configuring ``quic_port`` fails fast at startup
  with :class:`QuicUnavailableError` naming this module — nothing
  silently listens on UDP without QUIC semantics.

To slot a real stack in later (aioquic, an MsQuic C binding, ...):
implement ``QuicBackend.serve`` to run the QUIC handshake, accept the
first client-opened bidi stream, and invoke ``handler(reader, writer)``
per connection; then call :func:`register_backend` at import time.
``tests/test_transports.py::test_quic_seam`` pins the contract with an
in-memory backend.
"""

from __future__ import annotations

from typing import Awaitable, Callable, Optional, Protocol

# handler((reader, writer)) — the same shape MqttBroker._on_connection takes
StreamHandler = Callable[..., Awaitable[None]]


class QuicUnavailableError(RuntimeError):
    """quic_port configured but no QUIC backend is registered."""

    def __init__(self) -> None:
        super().__init__(
            "quic_port is configured but no QUIC stack is available in this "
            "environment (see rmqtt_tpu/broker/quic.py for the backend "
            "contract; the reference uses quinn, rmqtt-net/src/quic.rs)"
        )


class QuicBackend(Protocol):
    """The pluggable QUIC stack."""

    async def serve(self, host: str, port: int, handler: StreamHandler,
                    tls_cert: str, tls_key: str) -> "QuicServerHandle":
        """Bind UDP ``host:port``, run QUIC+TLS, and call ``handler`` with
        an asyncio (reader, writer) pair per accepted connection's first
        bidirectional stream."""
        ...


class QuicServerHandle(Protocol):
    async def close(self) -> None: ...

    @property
    def bound_port(self) -> int: ...


_backend: Optional[QuicBackend] = None


def register_backend(backend: QuicBackend) -> None:
    global _backend
    _backend = backend


def get_backend() -> QuicBackend:
    if _backend is None:
        raise QuicUnavailableError()
    return _backend


def backend_available() -> bool:
    return _backend is not None
