"""Crash-safe durability plane: journaled broker state + cold-start recovery.

Every fault plane so far (failover, fencing, anti-entropy, fabric owner
respawn) keeps the broker correct while the *process survives*; a SIGKILL
still lost the retained store, durable sessions, subscriptions and unacked
QoS1/2 windows. This module closes that gap, mirroring the reference's
layer-3 session/retain persistence (PAPER.md) with the crash-consistency
discipline of a write-ahead log:

**Journal.** Retained set/clear, session create/destroy, subscribe/
unsubscribe and QoS1/2 pending open/ack transitions append CRC-framed
records (``crc32 || len || payload``, payload = cluster wire encoding) to a
monotonically-keyed journal namespace on the existing ``SqliteStore`` /
``RedisStore`` surface. Appends only buffer in memory; a flusher commits
the buffer as ONE store transaction per group-commit window
(``flush_interval_ms`` / ``flush_max``), so the hot path never pays a
per-op fsync — concurrent publishers share each commit.

**Acknowledgement barrier.** A QoS1/2 PUBACK/PUBREC (and SUBACK/UNSUBACK)
waits on :meth:`DurabilityService.barrier` — resolved once every record
journaled so far is committed. That is the zero-acked-loss contract the
kill-9 torture harness (scripts/crash_torture.py) verifies: anything the
broker acknowledged is on disk first.

**Compaction.** When the journal outgrows ``compact_min`` rows past the
last snapshot, the flusher folds snapshot+journal into per-row snapshot
namespaces (retained topic → message, client id → session state), stamps
``snapshot_seq`` and deletes the folded journal prefix. Every journal
event is an idempotent upsert, so the crash window between snapshot write
and meta stamp replays harmlessly.

**Recovery.** ``MqttBroker.start`` runs :meth:`recover` before any
listener accepts (mirroring the fabric warm-up gate): snapshot+journal
fold back into ``RetainStore``, the session registry, the router and
per-session pending windows; unacked QoS1/2 re-deliver with DUP=1 when the
client returns. A torn journal tail (the ``storage.torn_write`` failpoint,
or a real partial write) fails its CRC and is dropped —
scan-to-last-valid, never a crash. Counters
(``durability_recovered_{retained,sessions,subs,inflight}``,
``durability_recovery_ms``) surface on ``/api/v1/durability``, Prometheus,
``$SYS`` and the dashboard.

``[durability] enable = false`` (the default) constructs nothing:
``ctx.durability is None`` and every hot-path guard is a single attribute
test — pinned byte-for-byte zero behavior change.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from rmqtt_tpu.cluster import wire
from rmqtt_tpu.utils.failpoints import FAILPOINTS, FailpointError

log = logging.getLogger("rmqtt_tpu.durability")

_FP_FSYNC = FAILPOINTS.register("storage.fsync")
_FP_TORN = FAILPOINTS.register("storage.torn_write")

#: store namespaces (shared sqlite file / redis prefix with nothing else —
#: the durability plane owns its own store instance)
NS_JOURNAL = "dj"
NS_SNAP_RETAIN = "dret"
NS_SNAP_SESS = "dsess"
NS_SNAP_DELAYED = "ddly"
NS_SNAP_MSG = "dmsg"
NS_META = "dmeta"

#: journal keys: zero-padded so lexicographic == numeric order everywhere
#: and ``delete_int_upto`` (raft-log compaction helper) applies directly
_KEY = "%020d"


# --------------------------------------------------------------- records
def frame_record(event: list) -> bytes:
    """CRC-framed journal record: a torn write (truncated value) fails the
    length or CRC check on recovery instead of resurrecting garbage."""
    payload = wire.dumps(event)
    return struct.pack("<II", zlib.crc32(payload) & 0xFFFFFFFF,
                       len(payload)) + payload


def decode_record(blob) -> Optional[list]:
    """Framed bytes → event list, or None for a torn/corrupt record."""
    if not isinstance(blob, (bytes, bytearray)) or len(blob) < 8:
        return None
    crc, ln = struct.unpack_from("<II", blob)
    payload = bytes(blob[8:])
    if len(payload) != ln or (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        return None
    try:
        ev = wire.loads(payload)
    except Exception:
        return None
    return ev if isinstance(ev, list) and ev else None


def fold_event(state: Dict[str, Any], ev: list) -> None:
    """Apply one journal event to the folded state. Every event is an
    idempotent upsert/delete so compaction's crash window (snapshot rows
    written, meta seq not yet stamped) replays harmlessly."""
    kind = ev[0]
    if kind == "ret":
        _topic, mw = ev[1], ev[2]
        if mw is None:
            state["retained"].pop(_topic, None)
        else:
            state["retained"][_topic] = mw
    elif kind == "sess+":
        # a create resets the slate: any prior subs/pending belonged to the
        # terminated predecessor (its sess- may share this journal window)
        state["sessions"][ev[1]] = {"info": ev[2], "subs": {}, "pending": {}}
    elif kind == "sess-":
        state["sessions"].pop(ev[1], None)
    elif kind == "off":
        # session went offline at wall time ev[2]: the expiry countdown
        # anchor, so a restart resumes the REMAINING window, not a full one
        sess = state["sessions"].get(ev[1])
        if sess is not None:
            sess["info"]["disconnected_at"] = ev[2]
    elif kind == "on":
        sess = state["sessions"].get(ev[1])
        if sess is not None:
            sess["info"].pop("disconnected_at", None)
            if len(ev) > 2 and ev[2]:
                # a resume re-fences the session (shared.py next_fence):
                # recovery must restore the HIGHEST fence it held, or a
                # healed partition would prefer a peer's staler copy
                sess["info"]["fence"] = ev[2]
    elif kind == "sub":
        sess = state["sessions"].get(ev[1])
        if sess is not None:
            sess["subs"][ev[2]] = ev[3]
    elif kind == "unsub":
        sess = state["sessions"].get(ev[1])
        if sess is not None:
            sess["subs"].pop(ev[2], None)
    elif kind == "msg":
        # one fan-out's payload, journaled ONCE and referenced by each
        # per-subscriber enq record (1,000 subscribers must not commit
        # 1,000 copies of the body inside the publisher's ack barrier)
        state.setdefault("msgs", {})[str(ev[1])] = ev[2]
    elif kind == "enq":
        sess = state["sessions"].get(ev[1])
        if sess is not None:
            sess["pending"][str(ev[2])] = ev[3]
    elif kind == "ack":
        sess = state["sessions"].get(ev[1])
        if sess is not None:
            sess["pending"].pop(str(ev[2]), None)
    elif kind == "q2+":
        # publisher-side QoS2 dedup window: a persistent publisher's DUP
        # resend after a broker crash must hit the dedup, not re-fan-out
        sess = state["sessions"].get(ev[1])
        if sess is not None:
            sess.setdefault("q2", {})[str(ev[2])] = True
    elif kind == "q2-":
        sess = state["sessions"].get(ev[1])
        if sess is not None:
            sess.setdefault("q2", {}).pop(str(ev[2]), None)
    elif kind == "dly+":
        state.setdefault("delayed", {})[str(ev[1])] = [ev[2], ev[3]]
    elif kind == "dly-":
        state.setdefault("delayed", {}).pop(str(ev[1]), None)
    # unknown kinds are skipped: an older broker reading a newer journal
    # degrades to ignoring what it cannot fold instead of refusing to boot


class DurabilityService:
    """The journaled-state plane (module docstring). One per broker; built
    by ``ServerContext`` only when ``[durability] enable = true``."""

    def __init__(self, ctx, cfg) -> None:
        self.ctx = ctx
        self.flush_interval = max(0.0005, cfg.durability_flush_interval_ms / 1000.0)
        self.flush_max = max(1, cfg.durability_flush_max)
        self.compact_min = max(16, cfg.durability_compact_min)
        self.backend = "redis" if cfg.durability_storage else "sqlite"
        if cfg.durability_storage:
            from rmqtt_tpu.storage import make_store

            self.store = make_store({"storage": cfg.durability_storage,
                                     "prefix": "rmqtt-dur"})
        else:
            from rmqtt_tpu.storage.sqlite import SqliteStore

            # the journal is the durability contract: per-commit fsync
            # (group-committed, so the hot path amortizes it) unless the
            # operator explicitly trades it away with sync = "normal"
            self.store = SqliteStore(cfg.durability_path,
                                     synchronous=cfg.durability_sync)
        # ride the context-wide expire sweep like the plugin stores (the
        # durability rows carry no TTL today, but the registration keeps
        # the "every configured store is swept" contract uniform)
        ctx.add_store(self.store)
        # ----- journal state (event loop owns _buf/_seq; flusher commits)
        self._buf: List[Tuple[int, bytes]] = []
        self._seq = 0
        self._committed = 0
        self._snapshot_seq = 0
        self._waiters: List[Tuple[int, asyncio.Future]] = []
        self._flush_ev = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        # journaling is PARKED until recover() establishes the seq space:
        # plugin start (session storage's restore path calls
        # registry.subscribe, which journals) runs before recover(), and
        # appends issued from seq 0 would collide with — and upsert-
        # overwrite — the previous run's live journal rows once recover()
        # re-anchors _seq to last_valid
        self._recovering = True
        self._compacting = False
        self._compact_fut: Optional[asyncio.Future] = None
        # per-publish body dedup: id(msg) → (strong msg ref, body seq)
        self._body_cache: Dict[int, Tuple[Any, int]] = {}
        #: a torn write means the process is (modeled as) crashing: no
        #: further commits, no further ack barriers resolve — anything not
        #: yet acknowledged stays unacknowledged, preserving zero acked loss
        self.wedged = False
        self._crash_for_test = False  # tests: skip the shutdown flush
        # ----- counters / surfaces
        self.appends = 0
        self.commits = 0
        self.commit_errors = 0
        self.compactions = 0
        self.recovered = {"retained": 0, "sessions": 0, "subs": 0,
                          "inflight": 0, "delayed": 0, "skipped_expired": 0}
        self.recovery_ms = 0.0

    # ----------------------------------------------------------- journal
    def _append(self, event: list) -> int:
        seq = self._seq + 1
        self._seq = seq
        self._buf.append((seq, frame_record(event)))
        self.appends += 1
        if len(self._buf) >= self.flush_max:
            self._flush_ev.set()
        return seq

    # Live hooks — called from the broker hot paths behind a single
    # ``ctx.durability is not None`` guard. All no-ops during recovery
    # (the recovered state is already in the store).
    def on_retain(self, topic: str, msg) -> None:
        if self._recovering:
            return
        from rmqtt_tpu.cluster.messages import msg_to_wire

        self._append(["ret", topic, None if msg is None else msg_to_wire(msg)])

    def on_session_created(self, session) -> None:
        if self._recovering or session.limits.session_expiry <= 0:
            return
        self._append(["sess+", session.client_id, {
            "proto": session.connect_info.protocol,
            "ka": session.connect_info.keepalive,
            "expiry": session.limits.session_expiry,
            "inflight": session.limits.max_inflight,
            "mqueue": session.limits.max_mqueue,
            "created_at": session.created_at,
            "fence": list(session.fence),
        }])

    def on_session_terminated(self, client_id: str) -> None:
        if not self._recovering:
            self._append(["sess-", client_id])

    def on_session_offline(self, client_id: str) -> None:
        """Socket gone: anchor the expiry countdown so a restart resumes
        the REMAINING window (MQTT session-expiry semantics — without the
        anchor a crash-looping broker would refresh every session's full
        expiry on each boot and never expire anything)."""
        if not self._recovering:
            self._append(["off", client_id, time.time()])

    def on_session_online(self, client_id: str, fence=None) -> None:
        """The client resumed before expiry: clear the countdown anchor
        and record the resume's re-fence (each resume stamps a fresh
        fence epoch that must survive a later crash)."""
        if not self._recovering:
            self._append(["on", client_id,
                          list(fence) if fence else None])

    def on_subscribe(self, client_id: str, full_filter: str, opts) -> None:
        if self._recovering:
            return
        from rmqtt_tpu.cluster.messages import opts_to_wire

        self._append(["sub", client_id, full_filter, opts_to_wire(opts)])

    def on_unsubscribe(self, client_id: str, full_filter: str) -> None:
        if not self._recovering:
            self._append(["unsub", client_id, full_filter])

    def _body_ref(self, msg) -> int:
        """Journal this publish's payload ONCE (the fan-out passes the
        same Message object to every subscriber's enqueue); per-subscriber
        enq records carry the returned seq instead of the body. The cache
        holds strong refs, so an id() can't be reused while cached."""
        key = id(msg)
        hit = self._body_cache.get(key)
        if hit is not None and hit[0] is msg:
            return hit[1]
        from rmqtt_tpu.cluster.messages import msg_to_wire

        seq = self._seq + 1
        self._append(["msg", seq, msg_to_wire(msg)])
        self._body_cache[key] = (msg, seq)
        while len(self._body_cache) > 64:
            self._body_cache.pop(next(iter(self._body_cache)))
        return seq

    def on_enqueue(self, client_id: str, item) -> int:
        """A QoS1/2 delivery entered a durable session's queue: journal it
        as pending and return its durable id (the journal seq). The id
        rides the DeliverItem/OutEntry until the subscriber acks."""
        if self._recovering:
            return 0
        ref = self._body_ref(item.msg)
        seq = self._seq + 1  # the id IS the seq this record gets
        return self._append(["enq", client_id, seq,
                             [item.qos, item.retain, item.topic_filter,
                              list(item.sub_ids), ref]])

    def on_ack(self, client_id: str, did: int) -> None:
        """Pending entry resolved: subscriber PUBACK/PUBCOMP, or a terminal
        drop (retries exhausted, expired, queue overflow)."""
        if did and not self._recovering:
            self._append(["ack", client_id, did])

    def on_qos2_open(self, client_id: str, packet_id: int) -> None:
        """Persistent publisher's incoming QoS2 accepted: journal the dedup
        window entry so a post-crash DUP resend can't fan out twice."""
        if not self._recovering:
            self._append(["q2+", client_id, packet_id])

    def on_qos2_release(self, client_id: str, packet_id: int) -> None:
        if not self._recovering:
            self._append(["q2-", client_id, packet_id])

    def on_delayed(self, delay_secs: float, msg) -> int:
        """A ``$delayed`` publish was scheduled: journal it with its wall
        fire time so a restart re-arms the REMAINING delay — its PUBACK
        rides the same barrier as every other journaled record. Returns
        the durable id the DelayedSender's fire resolves."""
        if self._recovering:
            return 0
        from rmqtt_tpu.cluster.messages import msg_to_wire

        seq = self._seq + 1
        return self._append(["dly+", seq, time.time() + delay_secs,
                             msg_to_wire(msg)])

    def on_delayed_done(self, did: int) -> None:
        """The delayed entry fired (and its fan-out's own enq records are
        already journaled ahead of this) or was refused at the cap."""
        if did and not self._recovering:
            self._append(["dly-", did])

    @property
    def dirty(self) -> bool:
        return self._seq > self._committed

    async def barrier(self) -> None:
        """Resolve once everything journaled so far is committed. The ack
        gate: group-committed, so concurrent publishers share one fsync —
        a lone publisher pays at most one flush window of latency."""
        target = self._seq
        if target <= self._committed:
            return
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append((target, fut))
        self._flush_ev.set()  # hasten: an ack is waiting on this window
        await fut

    # ------------------------------------------------------------ flusher
    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._flush_loop(), name="durability-flush")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._buf and not self.wedged and not self._crash_for_test:
            # clean shutdown: best-effort final commit — SNAPSHOT
            # discipline like the flusher (a record appended while the
            # commit is in flight, e.g. an expiry-task terminate, must not
            # be marked committed and dropped unwritten)
            batch = list(self._buf)
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, self._commit_sync, batch)
                self._committed = batch[-1][0]
                del self._buf[: len(batch)]
                self.commits += 1
            except Exception:
                log.warning("durability: final flush failed", exc_info=True)
        self._resolve_waiters()  # committed barriers resolve, not cancel
        for _t, fut in self._waiters:
            if not fut.done():
                fut.cancel()
        self._waiters.clear()
        if self._compact_fut is not None:
            # let an in-flight background compaction finish before the
            # store closes under it
            try:
                await self._compact_fut
            except Exception:
                pass
            self._compact_fut = None
        self.ctx.remove_store(self.store)
        try:
            self.store.close()
        except Exception:
            pass

    async def _flush_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            try:
                await asyncio.wait_for(self._flush_ev.wait(),
                                       self.flush_interval)
            except asyncio.TimeoutError:
                pass
            self._flush_ev.clear()
            if self.wedged:
                return  # crashed-journal model: no further commits
            if not self._buf:
                continue
            batch = list(self._buf)
            try:
                torn = await loop.run_in_executor(
                    None, self._commit_sync, batch)
            except Exception:
                # storage.fsync fault or a real store failure: the batch
                # stays buffered (barriers keep parking), retried next tick
                self.commit_errors += 1
                if self.commit_errors in (1, 10, 100, 1000):
                    log.warning("durability commit failed (x%d)",
                                self.commit_errors, exc_info=True)
                continue
            self.commits += 1
            del self._buf[: len(batch)]
            if torn:
                # the torn record was "written" but its writer is modeled
                # as crashing mid-append: wedge — anything past the torn
                # point must never be acknowledged
                self.wedged = True
                log.error("durability: torn journal write injected — "
                          "journal wedged (recovery drops the torn tail)")
                return
            self._committed = batch[-1][0]
            self._resolve_waiters()
            if (self._committed - self._snapshot_seq >= self.compact_min
                    and not self._compacting):
                # compaction runs CONCURRENTLY on an executor thread (the
                # store's own lock serializes row access, and the fold
                # only reads seqs ≤ upto, which no live commit touches):
                # an inline await here would stall every group commit —
                # and thus every parked ack barrier — for the whole fold
                self._compacting = True
                self._compact_fut = loop.run_in_executor(
                    None, self._compact_bg, self._committed)

    def _compact_bg(self, upto: int) -> None:
        try:
            self._compact_sync(upto)
        except Exception:
            log.warning("durability compaction failed", exc_info=True)
        finally:
            self._compacting = False

    def _resolve_waiters(self) -> None:
        if not self._waiters:
            return
        keep = []
        for target, fut in self._waiters:
            if target <= self._committed:
                if not fut.done():
                    fut.set_result(None)
            else:
                keep.append((target, fut))
        self._waiters = keep

    def _commit_sync(self, batch: List[Tuple[int, bytes]]) -> bool:
        """One group commit (executor thread). Returns True when the
        torn-write failpoint truncated the final record mid-append."""
        if _FP_FSYNC.action is not None:
            _FP_FSYNC.fire_sync()
        torn = False
        if _FP_TORN.action is not None:
            try:
                _FP_TORN.fire_sync()
            except FailpointError:
                torn = True
        rows = [(_KEY % seq, blob) for seq, blob in batch]
        if torn:
            key, blob = rows[-1]
            rows[-1] = (key, blob[: max(4, len(blob) // 2)])
        self.store.put_many(NS_JOURNAL, rows)
        return torn

    # --------------------------------------------------------- compaction
    def _compact_sync(self, upto: int) -> None:
        """Fold snapshot+journal(≤ upto) into fresh snapshot rows, stamp
        the meta seq, drop the folded journal prefix (executor thread;
        serialized with commits by the flusher loop). Write order makes
        every crash window safe: snapshot rows first (replay is
        idempotent), meta stamp second, journal delete last."""
        state, _last, _torn = self._load_state_sync(upto)
        for ns, fresh in ((NS_SNAP_RETAIN, state["retained"]),
                          (NS_SNAP_SESS, state["sessions"]),
                          (NS_SNAP_DELAYED, state["delayed"]),
                          (NS_SNAP_MSG, state["msgs"])):
            stale = [k for k, _v in self.store.scan(ns) if k not in fresh]
            if fresh:
                self.store.put_many(ns, list(fresh.items()))
            if stale:
                self.store.delete_many(ns, stale)
        self.store.put(NS_META, "snapshot_seq", upto)
        self.store.delete_int_upto(NS_JOURNAL, upto)
        self._snapshot_seq = upto
        self.compactions += 1

    def _load_state_sync(self, upto: Optional[int]):
        """snapshot + journal fold (executor thread) → (state, last_valid
        seq, torn_seq_or_None). Journal rows past a CRC-invalid record are
        a torn tail: dropped (scan-to-last-valid by design)."""
        snap_seq = int(self.store.get(NS_META, "snapshot_seq") or 0)
        state: Dict[str, Any] = {"retained": {}, "sessions": {},
                                 "delayed": {}, "msgs": {}}
        for topic, mw in self.store.scan(NS_SNAP_RETAIN):
            state["retained"][topic] = mw
        for cid, sess in self.store.scan(NS_SNAP_SESS):
            state["sessions"][cid] = sess
        for did_s, row in self.store.scan(NS_SNAP_DELAYED):
            state["delayed"][did_s] = row
        for ref_s, mw in self.store.scan(NS_SNAP_MSG):
            state["msgs"][ref_s] = mw
        rows = [(int(k), blob) for k, blob in self.store.scan(NS_JOURNAL)]
        rows.sort()
        last_valid, torn_at = snap_seq, None
        events = []
        for seq, blob in rows:
            if seq <= snap_seq:
                continue  # pre-snapshot leftovers (compaction crash window)
            if upto is not None and seq > upto:
                break
            ev = decode_record(blob)
            if ev is None:
                torn_at = seq
                break
            events.append(ev)
            last_valid = seq
        self._snapshot_seq = snap_seq
        for ev in events:
            fold_event(state, ev)
        # prune message bodies no live pending references (every enq they
        # backed has acked): keeps the body table bounded by the open
        # pending set, not by publish history
        referenced = {
            str(row[4])
            for sess in state["sessions"].values()
            for row in (sess.get("pending") or {}).values()
            if isinstance(row[4], int)
        }
        state["msgs"] = {k: v for k, v in state["msgs"].items()
                         if k in referenced}
        return state, last_valid, torn_at

    # ----------------------------------------------------------- recovery
    async def recover(self) -> None:
        """Boot phase (server.py, before listeners accept): replay
        snapshot+journal into the live broker. Runs after plugin start so
        retainer-loaded retained rows (possibly stale) are superseded —
        the session-storage plugin refuses to coexist — and with
        journaling suppressed: the recovered state is already durable."""
        t0 = time.monotonic()
        loop = asyncio.get_running_loop()
        state, last_valid, torn_at = await loop.run_in_executor(
            None, self._recover_load_sync)
        self._seq = self._committed = last_valid
        if torn_at is not None:
            log.warning("durability: dropped torn journal tail at seq %d",
                        torn_at)
        post: List[list] = []  # reap events journaled AFTER recovery
        self._recovering = True
        try:
            await self._restore_retained(state["retained"], post)
            await self._restore_sessions(state["sessions"], post,
                                         state.get("msgs") or {})
            self._restore_delayed(state.get("delayed") or {}, post)
        finally:
            self._recovering = False
        # the DelayedSender resolves journaled entries when they fire
        self.ctx.delayed.on_fired = self.on_delayed_done
        for ev in post:
            self._append(ev)
        self.recovery_ms = round((time.monotonic() - t0) * 1000.0, 3)
        r = self.recovered
        log.info(
            "durability recovery: %d retained, %d sessions, %d subs, "
            "%d inflight (%d expired skipped) in %.1fms (journal seq %d)",
            r["retained"], r["sessions"], r["subs"], r["inflight"],
            r["skipped_expired"], self.recovery_ms, last_valid)

    def _recover_load_sync(self):
        state, last_valid, torn_at = self._load_state_sync(None)
        if torn_at is not None:
            # the torn record and anything after it never happened; its
            # rows must not collide with the seqs we are about to re-issue
            victims = [k for k, _b in self.store.scan(NS_JOURNAL)
                       if int(k) >= torn_at]
            if victims:
                self.store.delete_many(NS_JOURNAL, victims)
        return state, last_valid, torn_at

    async def _restore_retained(self, retained: Dict[str, Any],
                                post: List[list]) -> None:
        from rmqtt_tpu.cluster.messages import msg_from_wire

        for topic, mw in retained.items():
            try:
                msg = msg_from_wire(mw)
            except Exception:
                continue
            if msg.is_expired():
                # skipped on restore AND reaped from the durable state, so
                # it cannot resurrect on the next restart either
                self.recovered["skipped_expired"] += 1
                post.append(["ret", topic, None])
                continue
            if self.ctx.retain.set_local(topic, msg):
                self.recovered["retained"] += 1

    async def _restore_sessions(self, sessions: Dict[str, Any],
                                post: List[list],
                                msgs: Dict[str, Any]) -> None:
        from rmqtt_tpu.broker.fitter import Limits
        from rmqtt_tpu.broker.session import DeliverItem, Session
        from rmqtt_tpu.broker.types import ConnectInfo
        from rmqtt_tpu.cluster.messages import msg_from_wire, opts_from_wire
        from rmqtt_tpu.core.topic import strip_prefixes
        from rmqtt_tpu.router.base import Id

        # NOTE: parallels session.py's restore_session() deliberately —
        # this copy must additionally thread the durable `did` through
        # every pending item and read the journal-shaped state; keep the
        # remaining-expiry and fence semantics of the two in lockstep.
        ctx = self.ctx
        loop = asyncio.get_running_loop()
        for cid, sess in sessions.items():
            if ctx.registry.get(cid) is not None:
                continue  # already present (defensive; no plugin coexists)
            info = sess.get("info") or {}
            expiry = float(info.get("expiry", 0.0))
            disc = info.get("disconnected_at")
            if disc is not None:
                # offline when the broker died: resume the REMAINING
                # expiry window (restore_session semantics) — a crash
                # must not refresh the countdown
                expiry = expiry - max(0.0, time.time() - float(disc))
            else:
                # connected when the broker died: the countdown starts at
                # recovery — anchor it durably, or repeated crashes would
                # re-grant the full window every boot
                post.append(["off", cid, time.time()])
            if expiry <= 0:
                self.recovered["skipped_expired"] += 1 if disc else 0
                post.append(["sess-", cid])
                continue
            sid = Id(ctx.cfg.node_id, cid)
            ci = ConnectInfo(id=sid, protocol=int(info.get("proto", 4)),
                             keepalive=int(info.get("ka", 60)),
                             clean_start=False)
            limits = Limits(
                keepalive=int(info.get("ka", 60)), server_keepalive=False,
                max_inflight=int(info.get("inflight", 16)),
                max_mqueue=int(info.get("mqueue", 1000)),
                session_expiry=expiry,
                max_message_expiry=ctx.cfg.fitter.max_message_expiry,
                max_topic_aliases_in=0, max_topic_aliases_out=0,
                max_packet_size=ctx.cfg.max_packet_size,
            )
            session = Session(ctx, sid, ci, limits, clean_start=False)
            fence = info.get("fence")
            if fence:
                # the restored fence must advance the local clock too, or
                # the next takeover could stamp a LOWER fence than the
                # state it resumes (restore_session's contract)
                session.fence = tuple(fence)
                observe = getattr(ctx.registry, "observe_fence", None)
                if observe is not None:
                    observe(int(fence[0]))
            ctx.registry._sessions[cid] = session
            for tf, ow in (sess.get("subs") or {}).items():
                try:
                    stripped = strip_prefixes(tf)
                except ValueError:
                    stripped = tf
                # LOCAL router add, not registry.subscribe: in raft mode
                # the registry proposes through consensus, and boot
                # recovery must never stall (or abort the boot) on an
                # unavailable quorum — the anti-entropy SYNC_ROUTES
                # exchange reconciles peers once the cluster heals
                opts = opts_from_wire(ow)
                ctx.router.add(stripped, session.id, opts)
                session.subscriptions[tf] = opts
                self.recovered["subs"] += 1
            pending = sess.get("pending") or {}
            for did_s in sorted(pending, key=int):
                qos, retain, tf, sub_ids, mw = pending[did_s]
                if isinstance(mw, int):  # deduped body reference
                    mw = msgs.get(str(mw))
                if mw is None:
                    post.append(["ack", cid, int(did_s)])
                    continue
                try:
                    msg = msg_from_wire(mw)
                except Exception:
                    continue
                if msg.is_expired():
                    self.recovered["skipped_expired"] += 1
                    post.append(["ack", cid, int(did_s)])
                    continue
                # unacked QoS1/2 re-delivers with DUP=1 when the client
                # resumes: the crash may have lost the first send's fate
                overflow = session.deliver_queue.push(DeliverItem(
                    msg=msg, qos=int(qos), retain=bool(retain),
                    topic_filter=tf, sub_ids=tuple(sub_ids), dup=True,
                    did=int(did_s)))
                self.recovered["inflight"] += 1
                if overflow is not None and overflow.did:
                    # pendings can exceed max_mqueue (queued + inflight
                    # were journaled separately): DROP_EARLY evicted the
                    # OLDEST restored item — that drop is terminal and
                    # must resolve its record, or it would resurrect and
                    # re-overflow on every restart
                    post.append(["ack", cid, overflow.did])
                    self.recovered["inflight"] -= 1
            # publisher-side QoS2 dedup window: a DUP resend of an
            # already-accepted publish must dedup, not re-fan-out
            for pid_s in (sess.get("q2") or {}):
                session.in_qos2.add(int(pid_s))
            session._expiry_task = loop.create_task(session._expire(expiry))
            self.recovered["sessions"] += 1

    def _restore_delayed(self, delayed: Dict[str, Any],
                         post: List[list]) -> None:
        """Re-arm journaled ``$delayed`` publishes with their REMAINING
        delay (due entries fire immediately); expired messages are reaped.
        A crash between a fire's fan-out and its dly- record replays the
        fire — the delayed path is at-least-once across kill -9."""
        from rmqtt_tpu.cluster.messages import msg_from_wire

        for did_s in sorted(delayed, key=int):
            fire_at, mw = delayed[did_s]
            try:
                msg = msg_from_wire(mw)
            except Exception:
                continue
            if msg.is_expired():
                self.recovered["skipped_expired"] += 1
                post.append(["dly-", int(did_s)])
                continue
            if not self.ctx.delayed.push(
                    max(0.0, float(fire_at) - time.time()), msg,
                    did=int(did_s)):
                post.append(["dly-", int(did_s)])  # cap refusal = terminal
                continue
            self.recovered["delayed"] += 1

    # ----------------------------------------------------------- surfaces
    def snapshot(self) -> dict:
        """/api/v1/durability body (+ the retained digest the torture
        harness compares against its client-side oracle)."""
        return {
            "enabled": True,
            "backend": self.backend,
            "wedged": self.wedged,
            "journal": {
                "seq": self._seq,
                "committed": self._committed,
                "buffered": len(self._buf),
                "snapshot_seq": self._snapshot_seq,
                "len": max(0, self._committed - self._snapshot_seq),
            },
            "appends": self.appends,
            "commits": self.commits,
            "commit_errors": self.commit_errors,
            "compactions": self.compactions,
            "recovered": dict(self.recovered),
            "recovery_ms": self.recovery_ms,
            "flush_interval_ms": round(self.flush_interval * 1000.0, 3),
            "flush_max": self.flush_max,
            "compact_min": self.compact_min,
            "retain_digest": self.ctx.retain.digest(),
        }
