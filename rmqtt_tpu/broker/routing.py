"""Micro-batched routing service: the publish-ingress → kernel seam.

The reference resolves `Router::matches()` inline per publish
(`/root/reference/rmqtt/src/shared.rs:771-778`). The TPU path instead runs a
bounded ingress queue + batcher (SURVEY.md §2.4 item 2's back-pressure system
re-purposed): concurrent publishes park a future on the queue; the drain task
collects up to ``max_batch`` (or until ``linger_ms`` passes) and resolves
them with ONE ``Router.matches_batch`` call. With ``DefaultRouter`` the batch
degrades to a loop — the seam is identical, only the router swaps, exactly
like the reference's extension manager (`rmqtt/src/extend.rs:64-113`).

Batching is latency-adaptive: a dispatch takes whatever is queued RIGHT NOW
(no linger), so a lone publish at low load pays zero added latency, while
under load the previous dispatch's service time naturally accumulates the
next batch (the classic adaptive-batching scheme — batch size tracks load
with no tuning knob). An optional ``linger_ms > 0`` restores a bounded wait
for workloads that prefer fuller device batches over first-packet latency.

In FRONT of the queue sits an epoch-versioned match-result cache
(`rmqtt_tpu/router/cache.py`): repeat-topic publishes — the dominant regime
under zipf-skewed IoT traffic — resolve synchronously from the cached
expanded relations and never enter the batcher, so device/native batches
shrink to misses only. Misses are deduplicated per dispatch (one match per
DISTINCT topic, matched with ``from_id=None``) and the per-publish result is
derived from the shared entry (No-Local re-filtered, shared-group liveness
re-flagged, round-robin choice still per publish).
"""

from __future__ import annotations

import asyncio
import time
from typing import List, Optional, Tuple

from rmqtt_tpu.broker.failover import _swallow_abandoned
from rmqtt_tpu.broker.telemetry import NULL_TELEMETRY, Telemetry
from rmqtt_tpu.broker.tracing import CURRENT_TRACE
from rmqtt_tpu.router.base import Id, Router, SubRelationsMap
from rmqtt_tpu.router.cache import MatchCache


class RoutingService:
    #: consecutive per-item failures in _isolate before the rest of the
    #: batch rejects without further retries (systemic-outage bailout)
    _ISOLATE_FAIL_STREAK = 3

    def __init__(
        self,
        router: Router,
        max_batch: int = 1024,
        linger_ms: float = 0.0,
        max_queue: int = 100_000,
        pipeline_depth: int = 3,
        prewarm: bool = True,
        cache_enable: bool = True,
        cache_capacity: int = 8192,
        cache_shared_bypass: bool = False,
        telemetry: Optional[Telemetry] = None,
    ) -> None:
        self.router = router
        # latency telemetry (broker/telemetry.py): stage histograms for
        # queue wait / match / hit-vs-miss + the slow-op ring. The disabled
        # singleton keeps every hot-path guard a single attribute test;
        # per-publish stages go through fast recorder closures (no-ops
        # when disabled — the t0 guards mean they're never even called)
        self.tele = telemetry if telemetry is not None else NULL_TELEMETRY
        self._rec_hit = self.tele.recorder("publish.cache_hit")
        self._rec_miss = self.tele.recorder("publish.cache_miss")
        self._rec_qwait = self.tele.recorder("routing.queue_wait")
        self.max_batch = max_batch
        self.linger = linger_ms / 1000.0
        self._q: asyncio.Queue = asyncio.Queue(maxsize=max_queue)
        self._task: Optional[asyncio.Task] = None
        # pipelined dispatch (routers exposing submit/complete halves):
        # up to pipeline_depth batches in flight — batch N+1's host encode
        # and dispatch overlap batch N's device compute, so burst latency
        # approaches the slowest stage instead of the sum of stages. The
        # semaphore is the in-flight bound (acquired before submit, released
        # after completion); pipeline_depth=1 degrades to serial dispatch.
        self.pipeline_depth = max(1, pipeline_depth)
        self._pipe_sem: Optional[asyncio.Semaphore] = None  # built in start()
        self._completion_q: asyncio.Queue = asyncio.Queue()
        self._completer: Optional[asyncio.Task] = None
        # small-batch fast path: device routers pre-compile their tiny
        # dispatch shapes off the hot path at start() (and latch a sticky
        # pad floor), so cfg1-style traffic — one publish per dispatch —
        # hits an already-compiled executable instead of paying a fresh
        # XLA compile per distinct small shape
        self.prewarm = prewarm
        # device-plane failover (broker/failover.py), wired by ServerContext
        # for device routers with a host trie mirror; None keeps every
        # dispatch guard a single attribute test
        self.failover = None
        # intra-node routing fabric (broker/fabric.py), wired by
        # ServerContext when [fabric] is enabled; surfaced through stats()
        # so the fabric counters ride every admin plane (None = zeros)
        self.fabric = None
        # hot-key attribution plane (broker/hotkeys.py), wired by
        # ServerContext only when enabled: the dispatch seam attributes
        # automaton work to first-segment prefixes; None keeps the
        # disabled cost at a single attribute test per dispatch
        self.hotkeys = None
        # epoch-versioned match-result cache (pre-queue fast path). The
        # cache is only sound for routers that OPT IN via epochs_tracked
        # (their add/remove bump Router.epochs on every mutation); any
        # other router — duck-typed or a custom Router subclass that never
        # bumps — runs uncached rather than risk stale serves
        self.cache: Optional[MatchCache] = None
        if (cache_enable and cache_capacity > 0
                and getattr(router, "epochs_tracked", False)):
            self.cache = MatchCache(
                router.epochs,
                capacity=cache_capacity,
                shared_bypass=cache_shared_bypass,
                is_online=getattr(router, "_is_online", lambda cid: True),
            )
        # observability (TaskExecStats analogue, context.rs:506-555):
        # dispatch counts + an EMA of batch size, surfaced via ctx.stats()
        self.dispatches = 0
        self.dispatched_items = 0
        self.batch_size_ema = 0.0
        self.inflight = 0  # batches currently past collect, not yet resolved

    def stats(self) -> dict:
        """Gauges for the admin surface (per-exec stats parity). The _ema
        key is average-mode for cluster merging (counter.rs AVG), not a
        summable count — /stats/sum treats the suffix accordingly (as it
        does the _ms latency-percentile keys below)."""
        c = self.cache
        t = self.tele
        t.flush()  # ONE fold pass; the quantile reads below skip theirs

        def pq(name: str, q: float) -> float:
            return round(t.hist(name).quantile(q) / 1e6, 3)

        # device-table lifecycle counters (router/xla.py device_stats):
        # zeros for routers without a device mirror so the surface stays
        # shape-stable (Prometheus/dashboard/$SYS all iterate these keys)
        ds = getattr(self.router, "device_stats", None)
        d = ds() if callable(ds) else {}
        return {
            # latency percentile gauges (broker/telemetry.py histograms):
            # zeros when telemetry is disabled — shape-stable either way
            "routing_match_p50_ms": pq("routing.match", 0.50),
            "routing_match_p99_ms": pq("routing.match", 0.99),
            "routing_queue_wait_p50_ms": pq("routing.queue_wait", 0.50),
            "routing_queue_wait_p99_ms": pq("routing.queue_wait", 0.99),
            "publish_e2e_p50_ms": pq("publish.e2e", 0.50),
            "publish_e2e_p99_ms": pq("publish.e2e", 0.99),
            "routing_queued": self._q.qsize(),
            "routing_inflight_batches": self.inflight,
            "routing_dispatches": self.dispatches,
            "routing_dispatched_items": self.dispatched_items,
            "routing_batch_size_ema": round(self.batch_size_ema, 1),
            # match-result cache gauges (zeros when the cache is disabled so
            # the observability surface stays shape-stable for dashboards)
            "routing_cache_size": len(c) if c is not None else 0,
            "routing_cache_hits": c.hits if c is not None else 0,
            "routing_cache_misses": c.misses if c is not None else 0,
            "routing_cache_invalidations": c.invalidations if c is not None else 0,
            "routing_cache_evictions": c.evictions if c is not None else 0,
            "routing_cache_door_rejects": c.door_rejects if c is not None else 0,
            # device-table churn gauges (delta uploads / bg compaction)
            "routing_uploads": d.get("uploads", 0),
            "routing_delta_uploads": d.get("delta_uploads", 0),
            "routing_upload_bytes": d.get("upload_bytes", 0),
            "routing_compactions": d.get("compactions", 0),
            # cumulative time, so the suffix is _total (summed in
            # /stats/sum), NOT _ms (averaged like latency percentiles)
            "routing_compact_ms_total": d.get("compact_ms", 0.0),
            "routing_cand_cache_invalidations": d.get("cand_cache_invalidations", 0),
            "routing_fused_batches": d.get("fused_batches", 0),
            # per-stage device dispatch attribution (PR9 stage_timing via
            # XlaRouter.device_stats): cumulative ms → _total suffix (summed
            # in /stats/sum); zeros for trie/native routers and while
            # stage_timing is off, so the surface stays shape-stable
            "routing_stage_encode_ms_total": d.get("stage_encode_ms_total", 0.0),
            "routing_stage_dispatch_ms_total": d.get("stage_dispatch_ms_total", 0.0),
            "routing_stage_fetch_ms_total": d.get("stage_fetch_ms_total", 0.0),
            "routing_stage_decode_ms_total": d.get("stage_decode_ms_total", 0.0),
            # device-plane failover gauges (broker/failover.py): zeros when
            # failover is not wired so the surface stays shape-stable.
            # state: 0 = device (healthy), 1 = host fallback, 2 = probing
            "routing_failover_state": (
                self.failover.state_value() if self.failover is not None else 0),
            "routing_failovers": (
                self.failover.failovers if self.failover is not None else 0),
            "routing_switchbacks": (
                self.failover.switchbacks if self.failover is not None else 0),
            "routing_failover_host_routed": (
                self.failover.host_items if self.failover is not None else 0),
            "routing_device_failures": (
                self.failover.failure_total if self.failover is not None else 0),
            # intra-node fabric gauges (broker/fabric.py): zeros without a
            # fabric so the surface stays shape-stable. The two stage keys
            # attribute fabric submit RTT / remote fan-out write time next
            # to the device-stage *_ms_total gauges, keeping the
            # host-vs-device split honest when matches cross workers
            "fabric_enabled": 1 if self.fabric is not None else 0,
            "fabric_owner": (
                1 if self.fabric is not None and self.fabric.is_owner else 0),
            "fabric_batches": self.fabric.batches if self.fabric else 0,
            "fabric_items": self.fabric.items if self.fabric else 0,
            "fabric_bytes_out": self.fabric.bytes_out if self.fabric else 0,
            "fabric_deliver_in": self.fabric.deliver_in if self.fabric else 0,
            "fabric_deliver_out": self.fabric.deliver_out if self.fabric else 0,
            "fabric_kicks_o1": self.fabric.kicks_o1 if self.fabric else 0,
            "fabric_kick_rpcs": self.fabric.kick_rpcs if self.fabric else 0,
            "fabric_plan_hits": self.fabric.plan_hits if self.fabric else 0,
            "fabric_owner_reconnects": (
                self.fabric.owner_reconnects if self.fabric else 0),
            "fabric_submit_fallbacks": (
                self.fabric.submit_fallbacks if self.fabric else 0),
            "directory_epoch": (
                (self.fabric.dir_epoch if self.fabric.is_owner
                 else self.fabric.replica_epoch) if self.fabric else 0),
            "routing_stage_fabric_submit_ms_total": (
                round(self.fabric.submit_ms_total, 3) if self.fabric else 0.0),
            "routing_stage_fabric_fanout_ms_total": (
                round(self.fabric.fanout_ms_total, 3) if self.fabric else 0.0),
        }

    def set_batch_window(self, max_batch: Optional[int] = None,
                         linger_ms: Optional[float] = None) -> None:
        """Knob seam (broker/knobs.py / the autotuner): retune the batcher
        live. ``_collect`` reads both per dispatch, so the next batch
        collected after this call already runs under the new window — no
        queue drain or task restart involved."""
        if max_batch is not None:
            self.max_batch = max(1, int(max_batch))
        if linger_ms is not None:
            self.linger = max(0.0, float(linger_ms)) / 1000.0

    def queue_fraction(self) -> float:
        """Ingress-queue fullness in [0, 1] — the overload controller's
        routing-backlog pressure signal (broker/overload.py)."""
        return self._q.qsize() / (self._q.maxsize or 1)

    def start(self) -> None:
        loop = asyncio.get_running_loop()
        if self._task is None:
            self._task = loop.create_task(self._run())
        if self._completer is None and hasattr(self.router, "submit_batch_raw"):
            self._pipe_sem = asyncio.Semaphore(self.pipeline_depth)
            self._completer = loop.create_task(self._complete_loop())
        if self.prewarm and hasattr(self.router, "prewarm"):
            # background thread: compiling the small shapes can take
            # seconds on a real chip and must not stall broker start
            loop.run_in_executor(None, self.router.prewarm)

    async def stop(self) -> None:
        if self.failover is not None:
            self.failover.stop()  # cancel probe/pacer background tasks
        for name in ("_task", "_completer"):
            t = getattr(self, name)
            if t is not None:
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass
                setattr(self, name, None)
        # reject everything still parked in either queue — those waiters
        # would otherwise await forever (e.g. forwards() during shutdown).
        # Destructure defensively (the batch is always item[0]): a future
        # queue-shape change must not turn shutdown into a TypeError that
        # strands every parked waiter
        while not self._completion_q.empty():
            item = self._completion_q.get_nowait()
            self._reject(item[0], RuntimeError("routing service stopped"))
        while not self._q.empty():
            item = self._q.get_nowait()
            self._reject([item], RuntimeError("routing service stopped"))

    def _cache_lookup(self, topic: str):
        """Pre-queue fast path: the entry for ``topic`` if current."""
        if self.cache is None:
            return None
        return self.cache.get(topic)

    async def matches(self, from_id: Optional[Id], topic: str) -> SubRelationsMap:
        relmap, _hit = await self.matches_for_fanout(from_id, topic)
        return relmap

    async def matches_for_fanout(
        self, from_id: Optional[Id], topic: str
    ) -> Tuple[SubRelationsMap, bool]:
        """``(relations, cache_hit)`` — the fan-out entry point. A cache hit
        resolves synchronously (never enters the batcher); a miss parks on
        the ingress queue as before.

        NOTE: even for prefer_inline routers the MISS path keeps the queue
        round trip — its yield is load-bearing: a read loop processing a
        whole TCP chunk of publishes would otherwise starve the deliver
        loops and overflow bounded deliver queues (measured: QoS0 drops
        under flood). The hit path preserves that cooperative yield with an
        explicit sleep(0), still far cheaper than the queue round trip."""
        t0 = time.perf_counter_ns() if self.tele.enabled else 0
        # the active trace rides the queue item so the batcher task can
        # stamp queue-wait/match spans onto it (broker/tracing.py); spans
        # reuse t0 and the dispatch timestamps — no extra clock reads
        trace = CURRENT_TRACE.get() if t0 else None
        entry = self._cache_lookup(topic)
        if entry is not None:
            await asyncio.sleep(0)
            out = self.router.collapse(self.cache.derive(entry, from_id))
            if t0:
                dur = time.perf_counter_ns() - t0
                self._rec_hit(dur, topic, trace)
                if trace is not None:
                    trace.add("publish.cache_hit", t0, dur, topic)
            return out, True
        fut = asyncio.get_running_loop().create_future()
        # t0 doubles as the enqueue timestamp for the queue-wait histogram
        await self._q.put((from_id, topic, fut, False, t0, trace))
        res = await fut
        # only meaningful with the cache on: a cache-off broker recording
        # every publish as a "miss" would read as a malfunctioning cache
        # (same rule as the hit/miss counters in shared.forwards)
        if t0 and self.cache is not None:
            dur = time.perf_counter_ns() - t0
            self._rec_miss(dur, topic, trace)
            if trace is not None:
                trace.add("publish.cache_miss", t0, dur, topic)
        return res, False

    async def matches_raw(self, from_id: Optional[Id], topic: str):
        """Un-collapsed variant for cluster-global shared-group choice."""
        t0 = time.perf_counter_ns() if self.tele.enabled else 0
        trace = CURRENT_TRACE.get() if t0 else None
        entry = self._cache_lookup(topic)
        if entry is not None:
            await asyncio.sleep(0)  # keep the cooperative yield (see above)
            out = self.cache.derive(entry, from_id)
            if t0:
                dur = time.perf_counter_ns() - t0
                self._rec_hit(dur, topic, trace)
                if trace is not None:
                    trace.add("publish.cache_hit", t0, dur, topic)
            return out
        fut = asyncio.get_running_loop().create_future()
        await self._q.put((from_id, topic, fut, True, t0, trace))
        res = await fut
        if t0 and self.cache is not None:  # see matches_for_fanout
            dur = time.perf_counter_ns() - t0
            self._rec_miss(dur, topic, trace)
            if trace is not None:
                trace.add("publish.cache_miss", t0, dur, topic)
        return res

    async def _collect(self):
        batch = [await self._q.get()]
        while len(batch) < self.max_batch:
            try:
                batch.append(self._q.get_nowait())
            except asyncio.QueueEmpty:
                break
        if self.linger > 0 and len(batch) < self.max_batch:
            deadline = asyncio.get_running_loop().time() + self.linger
            while len(batch) < self.max_batch:
                timeout = deadline - asyncio.get_running_loop().time()
                if timeout <= 0:
                    break
                try:
                    batch.append(await asyncio.wait_for(self._q.get(), timeout))
                except asyncio.TimeoutError:
                    break
                except asyncio.CancelledError:
                    # stop() mid-linger: items already popped off the queue
                    # are invisible to stop()'s drain — reject them here or
                    # their waiters hang forever
                    self._reject(batch, RuntimeError("routing service stopped"))
                    raise
        return batch

    def _plan(self, batch):
        """→ (match items, per-item waiter groups or None).

        Without the cache, items mirror the batch 1:1. With it, misses are
        DEDUPLICATED per distinct topic and matched with ``from_id=None``
        (No-Local is re-applied per waiter at resolve time) so a burst of
        publishes to one hot topic costs one match; epoch snapshots are
        taken here — BEFORE the match runs — so a subscribe landing while
        the batch is in flight makes the entry born-stale, never wrong."""
        if self.cache is None:
            return [(fid, topic) for fid, topic, *_ in batch], None
        order: dict = {}
        items: list = []
        groups: list = []
        for i, (_fid, topic, _fut, _raw, _t, _tr) in enumerate(batch):
            j = order.get(topic)
            if j is None:
                order[topic] = len(items)
                items.append((None, topic))
                groups.append(([i], self.cache.snapshot(topic)))
            else:
                groups[j][0].append(i)
        return items, groups

    def _resolve(self, batch, results, groups=None) -> None:
        """Resolve waiters from per-item results. A result slot may be an
        EXCEPTION (the poisoned-batch isolation path, ``_isolate``): it
        rejects only that item's waiters — the co-batched publishes still
        resolve normally."""
        if groups is None:
            for (_, _, fut, raw, _t, _tr), res in zip(batch, results):
                if fut.done():
                    continue
                if isinstance(res, BaseException):
                    fut.set_exception(res)
                    continue
                try:
                    fut.set_result(res if raw else self.router.collapse(res))
                except Exception as e:
                    # a collapse failure (e.g. a shared-sub strategy callback
                    # bug) must reject ITS waiter, not kill the service task
                    fut.set_exception(e)
            return
        for (idxs, snap), res in zip(groups, results):
            if isinstance(res, BaseException):
                for i in idxs:
                    fut = batch[i][2]
                    if not fut.done():
                        fut.set_exception(res)
                continue
            topic = batch[idxs[0]][1]
            entry = self.cache.put(topic, res, snap)
            # ONE waiter may consume the fresh raw directly (its containers
            # are unaliased until collapse mutates them); the rest derive
            # copies from the entry. No-Local publishers always derive, and
            # a transient (unstored) entry ALIASES the raw, so the raw may
            # only be consumed directly when no other waiter derives from it
            raw_free = entry.stored or len(idxs) == 1
            for i in idxs:
                fid, _topic, fut, raw, _t, _tr = batch[i]
                if fut.done():
                    continue
                try:
                    if raw_free and (fid is None or not entry.has_no_local):
                        derived, raw_free = res, False
                    else:
                        derived = self.cache.derive(entry, fid)
                    fut.set_result(
                        derived if raw else self.router.collapse(derived))
                except Exception as e:
                    fut.set_exception(e)

    @staticmethod
    def _reject(batch, exc) -> None:
        for it in batch:
            fut = it[2]
            if not fut.done():
                fut.set_exception(exc)

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        # CPU routers (trie/native) match in microseconds: a thread-pool hop
        # per dispatch costs more than the match itself and caps serial
        # publish throughput. Device routers keep the executor (the kernel
        # blocks; numpy/jax release the GIL for the heavy parts).
        inline_ok = self.router.inline_ok
        pipelined = hasattr(self.router, "submit_batch_raw")
        while True:
            batch = await self._collect()
            try:
                await self._dispatch_one(loop, batch, inline_ok, pipelined)
            except asyncio.CancelledError:
                # shutdown while this batch was mid-dispatch: its waiters
                # must not hang (stop()'s drain only sees the queues)
                self._reject(batch, RuntimeError("routing service stopped"))
                raise

    async def _dispatch_one(self, loop, batch, inline_ok, pipelined) -> None:
        items, groups = self._plan(batch)
        self.dispatches += 1
        self.dispatched_items += len(items)
        hk = self.hotkeys
        if hk is not None:
            # per dispatched (deduplicated) match item: the automaton work
            # a namespace prefix is responsible for, not raw publish volume
            hk.on_dispatch_items(items)
        self.batch_size_ema = (
            len(items) if self.dispatches == 1
            else 0.9 * self.batch_size_ema + 0.1 * len(items)
        )
        tele = self.tele
        t_disp = 0
        if tele.enabled:
            t_disp = time.perf_counter_ns()
            rec_qwait = self._rec_qwait
            for it in batch:
                if it[4]:
                    wait = t_disp - it[4]
                    tr = it[5]
                    rec_qwait(wait, it[1], tr)
                    if tr is not None:  # same t0/t_disp reads as the stage
                        tr.add("routing.queue_wait", it[4], wait, it[1])
            tele.record("routing.batch_size", len(items))
        fo = self.failover
        if fo is not None and fo.active:
            # device plane is out: route through the host trie mirror and
            # (once the breaker cooldown elapses) kick a background probe
            fo.maybe_probe(loop)
            await self._host_dispatch(loop, batch, items, groups, t_disp)
            return
        if inline_ok(len(items)):
            # inline batches are HOST-served by contract (inline_ok is only
            # true for the trie routers / the hybrid's trie branch): a
            # failure here is host-side poison, not device evidence — it
            # must neither trip nor reset the device breaker
            try:
                results = self.router.matches_batch_raw(items)
            except Exception as e:
                await self._isolate(loop, batch, items, groups, e)
                return
            self._resolve(batch, results, groups)
            if t_disp:
                self._record_match(t_disp, len(items), batch)
            return
        if pipelined:
            # in-flight bound: block BEFORE submitting so at most
            # pipeline_depth batches are ever past submit
            await self._pipe_sem.acquire()
            self.inflight += 1
            try:
                done, payload = await self._device_call(
                    loop, self.router.submit_batch_raw, items,
                    "device dispatch")
            except TimeoutError as e:
                self.inflight -= 1
                self._pipe_sem.release()
                await self._device_failed(
                    loop, batch, items, groups, e, "timeout", t_disp)
                return
            except Exception as e:
                self.inflight -= 1
                self._pipe_sem.release()
                await self._device_failed(
                    loop, batch, items, groups, e, "dispatch_error", t_disp)
                return
            except asyncio.CancelledError:
                self.inflight -= 1
                self._pipe_sem.release()
                raise
            if done:
                # the router resolved synchronously (the hybrid's trie
                # branch, or a device matcher with no submit entry point):
                # don't spend a pipeline permit or a completion-queue round
                # trip on it
                self.inflight -= 1
                self._pipe_sem.release()
                self._resolve(batch, payload, groups)
                if t_disp:
                    self._record_match(t_disp, len(items), batch)
                if fo is not None and self._served_by_device():
                    fo.note_device_ok()
                return
            await self._completion_q.put((batch, groups, payload, t_disp, items))
            return
        self.inflight += 1
        try:
            results = await loop.run_in_executor(
                None, self.router.matches_batch_raw, items
            )
        except Exception as e:
            self.inflight -= 1
            await self._device_failed(
                loop, batch, items, groups, e, "dispatch_error", t_disp)
            return
        except asyncio.CancelledError:
            self.inflight -= 1
            raise
        self.inflight -= 1
        self._resolve(batch, results, groups)
        if t_disp:
            self._record_match(t_disp, len(items), batch)
        if fo is not None and self._served_by_device():
            fo.note_device_ok()

    def _served_by_device(self) -> bool:
        """Was the dispatch that just resolved served by the DEVICE matcher?
        Hybrid routers report per-batch (last_match_was_device — reads are
        safe: dispatches are serialized on the single batcher task); plain
        device routers have no trie branch, so default True."""
        probe = getattr(self.router, "last_match_was_device", None)
        return probe() if callable(probe) else True

    async def _device_call(self, loop, fn, arg, what: str):
        """One device-router call in the executor, under the failover
        plane's per-batch deadline. On timeout the executor thread is
        ABANDONED (its eventual result/exception is swallowed) so a hung
        kernel can never wedge the dispatch or completion loop — the
        watchdog contract of broker/failover.py."""
        fo = self.failover
        fut = loop.run_in_executor(None, fn, arg)
        if fo is None or not fo.usable or fo.timeout_s <= 0:
            return await fut
        done, pending = await asyncio.wait({fut}, timeout=fo.timeout_s)
        if pending:
            fut.add_done_callback(_swallow_abandoned)
            raise TimeoutError(
                f"{what} exceeded the {fo.timeout_s:.1f}s failover deadline")
        return fut.result()

    async def _host_dispatch(self, loop, batch, items, groups, t_disp) -> None:
        """Serve one batch through the host trie mirror (failover plane):
        same resolve semantics as the device path, plus ``routing.failover``
        trace spans and the host-routed counters."""
        fo = self.failover
        router = self.router
        t0 = time.perf_counter_ns() if (t_disp or self.tele.enabled) else 0
        try:
            if router.host_inline_ok():
                results = router.host_matches_batch_raw(items)
            else:
                results = await loop.run_in_executor(
                    None, router.host_matches_batch_raw, items)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # the host fallback itself failed (e.g. a genuinely poisoned
            # topic): isolate per item ON THE HOST PATH so one bad encode
            # doesn't reject the co-batched publishes
            await self._isolate(loop, batch, items, groups, e,
                                router.host_matches_batch_raw)
            return
        fo.note_host_batch(len(items))
        self._resolve(batch, results, groups)
        if t0:
            dur = time.perf_counter_ns() - t0
            detail = {"backend": "host-fallback", "batch": len(items)}
            for it in batch:
                tr = it[5]
                if tr is not None:
                    tr.add("routing.failover", t0, dur, detail)
            self.tele.record("routing.match", dur, detail)

    async def _device_failed(self, loop, batch, items, groups, exc,
                             reason: str, t_disp) -> None:
        """A batch failed on the primary path. With a usable failover plane
        the failure is CLASSIFIED (breaker bookkeeping; the breaker opening
        activates host routing) and this batch is served from the host trie
        — zero lost publishes. Without one, fall back to poisoned-batch
        isolation: split-and-retry so only the faulty item's futures reject."""
        fo = self.failover
        if fo is not None and fo.usable:
            from rmqtt_tpu.broker.failover import classify

            fo.record_failure(classify(exc, reason))
            await self._host_dispatch(loop, batch, items, groups, t_disp)
            return
        await self._isolate(loop, batch, items, groups, exc)

    async def _isolate(self, loop, batch, items, groups, exc,
                       match_fn=None) -> None:
        """Poisoned-batch isolation (one bad topic encode must not reject
        its co-batched publishes): split the failed batch in half, retry
        each half once, and for a half that still fails match its items
        one by one — failures become per-item exceptions that ``_resolve``
        routes to only their own waiters.

        The per-item pass bails out after ``_ISOLATE_FAIL_STREAK``
        consecutive failures: poison is item-shaped (a bad topic fails
        alone among healthy neighbours), so an unbroken failure run means
        the PATH is down (dead device with no usable failover plane) — and
        then 2+N guaranteed-to-fail serial retries per batch would back up
        the dispatch loop exactly when the broker is already degraded.
        Remaining items reject with the original error immediately."""
        if match_fn is None:
            match_fn = getattr(self.router, "matches_batch_raw", None)
        n = len(items)
        if n == 1 or match_fn is None:
            # a single item IS the poison; a pipelined-only router (no
            # synchronous batch entry point) can't be retried — both
            # degrade to rejecting with the original error
            self._resolve(batch, [exc] * n, groups)
            return
        results: list = [exc] * n
        streak = 0

        async def retry(lo: int, hi: int) -> None:
            nonlocal streak
            try:
                sub = await loop.run_in_executor(None, match_fn, items[lo:hi])
            except asyncio.CancelledError:
                raise
            except Exception:
                for j in range(lo, hi):  # per-item: isolate the poison
                    if streak >= self._ISOLATE_FAIL_STREAK:
                        return  # systemic, not poison: stop amplifying
                    try:
                        one = await loop.run_in_executor(
                            None, match_fn, items[j:j + 1])
                        results[j] = one[0]
                        streak = 0
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:
                        results[j] = e
                        streak += 1
            else:
                results[lo:hi] = sub

        mid = n // 2
        await retry(0, mid)
        await retry(mid, n)
        self._resolve(batch, results, groups)

    def _record_match(self, t0: int, n: int, batch=None) -> None:
        """Per-dispatch backend match latency (submit → results expanded).
        The same timestamp pair also stamps a ``routing.match`` span onto
        every traced item of the batch — the per-publish view of the
        kernel dispatch (backend name = native/xla/trie in the detail).
        A slow dispatch's ring entry carries the batch's first trace id
        (the batcher task has no trace contextvar of its own)."""
        dur = time.perf_counter_ns() - t0
        detail = {"backend": type(self.router).__name__, "batch": n}
        first_trace = None
        if batch is not None:
            for it in batch:
                tr = it[5]
                if tr is not None:
                    if first_trace is None:
                        first_trace = tr
                    tr.add("routing.match", t0, dur, detail)
        self.tele.record("routing.match", dur, detail, first_trace)

    async def _complete_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            batch, groups, handle, t_disp, items = await self._completion_q.get()
            fo = self.failover
            try:
                try:
                    # watchdog (broker/failover.py): a hung device completes
                    # nothing — the deadline serves the batch from the host
                    # trie and abandons the wedged executor thread instead
                    # of wedging this loop with it
                    results = await self._device_call(
                        loop, self.router.complete_batch_raw, handle,
                        "device completion")
                except asyncio.CancelledError:
                    # shutdown mid-completion: don't strand these waiters
                    self._reject(batch, RuntimeError("routing service stopped"))
                    raise
                except TimeoutError as e:
                    await self._device_failed(
                        loop, batch, items, groups, e, "timeout", t_disp)
                except Exception as e:
                    await self._device_failed(
                        loop, batch, items, groups, e, "complete_error", t_disp)
                else:
                    self._resolve(batch, results, groups)
                    if t_disp:
                        self._record_match(t_disp, len(items), batch)
                    if fo is not None:
                        fo.note_device_ok()
            finally:
                self.inflight -= 1
                self._pipe_sem.release()
