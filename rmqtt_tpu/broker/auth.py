"""MQTT 5 enhanced authentication (AUTH packet exchange, spec §4.12).

The reference implements the AUTH codec in
`rmqtt-codec/src/v5/packet/auth.rs` and drives the exchange from its v5
session front-end; here the server side is a pluggable seam on the
``ServerContext`` (``ctx.enhanced_auth``):

- CONNECT carrying an Authentication Method property starts an exchange:
  the server may answer with AUTH (0x18 Continue authentication) challenges
  until the authenticator returns success (CONNACK, echoing the method) or
  failure (refusal CONNACK).
- A connected client may re-authenticate with AUTH (0x19 Re-authenticate);
  the same challenge loop runs over the live connection and ends with a
  server AUTH (0x00 Success) or a DISCONNECT carrying the failure code.

``CramSha256Authenticator`` is the bundled implementation (method
``CRAM-SHA256``): the server challenges with a random nonce, the client
answers ``HMAC-SHA256(secret, nonce)``.
"""

from __future__ import annotations

import hmac
import hashlib
import os
from typing import Dict, Optional, Tuple

# AUTH / CONNACK reason codes (mqtt5 spec; types.py holds the common ones)
RC_AUTH_SUCCESS = 0x00
RC_CONTINUE_AUTHENTICATION = 0x18
RC_RE_AUTHENTICATE = 0x19
RC_NOT_AUTHORIZED = 0x87
RC_BAD_AUTHENTICATION_METHOD = 0x8C


class EnhancedAuthenticator:
    """Server-side enhanced-auth driver. Implementations are stateful per
    in-flight exchange (keyed by client id) and must be safe to call from
    concurrent handshakes."""

    async def start(self, ci, method: str, data: Optional[bytes]) -> Tuple[int, Optional[bytes]]:
        """Begin an exchange (CONNECT or AUTH 0x19). Returns
        (reason_code, server_data): 0x18 to challenge, 0x00 to accept,
        anything else to refuse with that code."""
        raise NotImplementedError

    async def continue_(self, ci, method: str, data: Optional[bytes]) -> Tuple[int, Optional[bytes]]:
        """Process the client's AUTH 0x18 answer; same return contract."""
        raise NotImplementedError


class CramSha256Authenticator(EnhancedAuthenticator):
    """Challenge-response over a shared secret (method ``CRAM-SHA256``)."""

    METHOD = "CRAM-SHA256"

    def __init__(self, secrets: Dict[str, bytes]) -> None:
        # username (falling back to client id) → shared secret
        self.secrets = {
            k: v.encode() if isinstance(v, str) else bytes(v) for k, v in secrets.items()
        }
        self._pending: Dict[str, bytes] = {}

    def _secret_for(self, ci) -> Optional[bytes]:
        if ci.username and ci.username in self.secrets:
            return self.secrets[ci.username]
        return self.secrets.get(ci.id.client_id)

    # abandoned exchanges (challenge sent, socket dropped) never reach
    # continue_(), so the pending table is FIFO-capped — attacker-controlled
    # client ids must not grow broker memory unboundedly
    MAX_PENDING = 4096

    async def start(self, ci, method, data):
        if method != self.METHOD:
            return RC_BAD_AUTHENTICATION_METHOD, None
        nonce = os.urandom(16)
        while len(self._pending) >= self.MAX_PENDING:
            self._pending.pop(next(iter(self._pending)))
        self._pending[ci.id.client_id] = nonce
        return RC_CONTINUE_AUTHENTICATION, nonce

    async def continue_(self, ci, method, data):
        nonce = self._pending.pop(ci.id.client_id, None)
        secret = self._secret_for(ci)
        if nonce is None or secret is None or not data:
            return RC_NOT_AUTHORIZED, None
        expect = hmac.new(secret, nonce, hashlib.sha256).digest()
        if not hmac.compare_digest(expect, bytes(data)):
            return RC_NOT_AUTHORIZED, None
        return RC_AUTH_SUCCESS, None


def cram_response(secret: bytes, nonce: bytes) -> bytes:
    """Client-side answer for CRAM-SHA256 (used by tests/bridges)."""
    return hmac.new(secret, nonce, hashlib.sha256).digest()
