"""Retained-message store.

Mirrors the reference's `RetainStorage` trait + in-memory default
(`/root/reference/rmqtt/src/retain.rs:100-213`): set (empty payload clears,
MQTT-3.3.1-10/11), wildcard lookup on SUBSCRIBE, per-message expiry, count
and max limits. Backed by the CPU ``RetainTree``; when the store grows past
``tpu_threshold`` the wildcard lookup switches to the partitioned TPU
inverse-match kernel (`rmqtt_tpu.ops.retained_part`) over a mirrored
chunk-tiled row table — the same pruned automaton the router uses, per the
north star.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

from rmqtt_tpu.core.topic import filter_valid, topic_valid
from rmqtt_tpu.core.trie import RetainTree
from rmqtt_tpu.broker.types import Message, now


class RetainStore:
    def __init__(
        self,
        enable: bool = True,
        max_retained: int = 1_000_000,
        max_payload: int = 1024 * 1024,
        tpu: bool = False,
        tpu_threshold: int = 50_000,
    ) -> None:
        self.enable = enable
        self.max_retained = max_retained
        self.max_payload = max_payload
        self._tree: RetainTree[Message] = RetainTree()
        self._tpu = tpu
        self._tpu_threshold = tpu_threshold
        self._table = None  # lazily-built ops.retained_part.RetainedTable mirror
        self._scanner = None
        self._rowid_by_topic: Dict[str, int] = {}
        self._msg_by_rowid: Dict[int, Tuple[str, Message]] = {}
        # cluster hook: called as on_set(topic, msg_or_None) after a local
        # mutation (broadcast-mode retain_set_broadcast analogue)
        self.on_set = None
        # store revision: bumped on every content mutation so digest()
        # recomputes only when the store actually changed (the membership
        # API polls the digest — an O(n log n) pass per poll would stall
        # the event loop at scale)
        self._rev = 0
        self._digest_cache: Optional[Tuple[int, Dict[str, object]]] = None

    def count(self) -> int:
        return self._tree.count()

    def set(self, topic: str, msg: Message) -> bool:
        """Store/replace/clear; returns False if refused (limits/disabled)."""
        ok = self.set_local(topic, msg)
        if ok and self.on_set is not None:
            self.on_set(topic, msg if msg.payload else None)
        return ok

    def set_local(self, topic: str, msg: Message) -> bool:
        """Like `set` but without the cluster broadcast (inbound sync path)."""
        if not self.enable:
            return False
        if not topic_valid(topic):
            # a wildcard/invalid publish topic (reachable via the HTTP API,
            # which skips the wire codec's validation) must be refused, not
            # half-inserted: the TPU mirror rejects wildcard rows and the
            # tree would diverge from it permanently
            return False
        if not msg.payload:  # empty payload clears (MQTT-3.3.1-10)
            self.remove_local(topic)
            return True
        if len(msg.payload) > self.max_payload:
            return False
        if self._tree.get(topic) is None and self._tree.count() >= self.max_retained:
            return False
        self._tree.insert(topic, msg)
        self._rev += 1
        if self._tpu:
            self._set_row(topic, msg)
        return True

    def remove_local(self, topic: str) -> None:
        self._tree.remove(topic)
        self._rev += 1
        self._drop_row(topic)

    def all_items(self) -> List[Tuple[str, Message]]:
        """Every retained (topic, message), including ``$``-topics."""
        return [("/".join(levels), m) for levels, m in self._tree.items()]

    def get(self, topic: str) -> Optional[Message]:
        msg = self._tree.get(topic)
        if msg is not None and msg.is_expired():
            self.remove_local(topic)
            return None
        return msg

    def matches(self, topic_filter: str) -> List[Tuple[str, Message]]:
        """All retained (topic, message) matching a new subscription's filter."""
        if not self.enable or not filter_valid(topic_filter):
            return []
        if self._tpu and self.count() >= self._tpu_threshold:
            out = self._matches_tpu(topic_filter)
        else:
            out = [("/".join(levels), msg) for levels, msg in self._tree.matches(topic_filter)]
        fresh = []
        for topic, msg in out:
            if msg.is_expired():
                self.remove_local(topic)
            else:
                fresh.append((topic, msg))
        return fresh

    def digest(self) -> Dict[str, object]:
        """Content digest over every live retained (topic, create_time,
        payload): byte-equal across nodes iff the stores converged —
        ``create_time`` rides the retain-sync wire, so replicas agree after
        a successful sync. The anti-entropy exchange
        (cluster/membership.py) compares this before moving any payloads.
        Cached against the store revision, so membership-API polls only
        recompute after an actual mutation (expired entries still drop out:
        their removal on first touch bumps the revision)."""
        if (self._digest_cache is not None
                and self._digest_cache[0] == self._rev):
            return dict(self._digest_cache[1])
        h = hashlib.sha1()
        n = 0
        expired = []
        for topic, m in sorted(self.all_items()):
            if m.is_expired():
                expired.append(topic)
                continue
            h.update(topic.encode())
            h.update(b"\x00")
            h.update(repr(m.create_time).encode())
            h.update(hashlib.sha1(m.payload).digest())
            n += 1
        for t in expired:
            # reap now (bumps the revision) so the cached digest stays
            # consistent with what a recompute would produce
            self.remove_local(t)
        out = {"count": n, "digest": h.hexdigest()}
        self._digest_cache = (self._rev, dict(out))
        return out

    def summary(self) -> Dict[str, list]:
        """Per-topic repair summary ``{topic: [create_time, payload_hash]}``
        — what the anti-entropy delta plan compares instead of shipping
        payloads (cluster/membership.py retain_delta)."""
        out: Dict[str, list] = {}
        for topic, m in self.all_items():
            if m.is_expired():
                continue
            out[topic] = [m.create_time,
                          hashlib.sha1(m.payload).hexdigest()[:12]]
        return out

    def expire_sweep(self) -> int:
        """Periodic expiry cleanup (retainer plugin's cleanup loop)."""
        expired = ["/".join(levels) for levels, msg in self._tree.items() if msg.is_expired()]
        for t in expired:
            self.remove_local(t)
        return len(expired)

    # ---- TPU mirror -------------------------------------------------------
    def _ensure_tpu(self):
        if self._scanner is None:
            from rmqtt_tpu.ops.retained_part import (
                PartitionedRetainedScanner,
                RetainedTable,
            )
            from rmqtt_tpu.utils.tpuprobe import ensure_safe_platform

            # the first scan is the first backend touch on this path: a
            # wedged accelerator grant would block the event loop forever
            ensure_safe_platform()
            self._table = RetainedTable()
            self._scanner = PartitionedRetainedScanner(self._table)
            # backfill current tree contents (incl. $-topics)
            for levels, msg in self._tree.items():
                self._set_row("/".join(levels), msg)

    def _set_row(self, topic: str, msg: Message) -> None:
        if self._scanner is None:
            return  # rows are built lazily on first TPU lookup
        rid = self._rowid_by_topic.get(topic)
        if rid is None:
            try:
                rid = self._table.add(topic)
            except ValueError:
                # pre-existing invalid tree entry (e.g. loaded from an old
                # persisted store): leave it to the tree path rather than
                # poisoning every future scan
                return
            self._rowid_by_topic[topic] = rid
        self._msg_by_rowid[rid] = (topic, msg)

    def _drop_row(self, topic: str) -> None:
        rid = self._rowid_by_topic.pop(topic, None)
        if rid is not None:
            self._msg_by_rowid.pop(rid, None)
            if self._table is not None:
                self._table.remove(rid)

    def _matches_tpu(self, topic_filter: str) -> List[Tuple[str, Message]]:
        self._ensure_tpu()
        (row,) = self._scanner.scan([topic_filter])
        return [self._msg_by_rowid[rid] for rid in row.tolist() if rid in self._msg_by_rowid]
