"""Server context: the shared runtime bundle.

Mirrors the reference ``ServerContext`` (`/root/reference/rmqtt/src/context.rs:290-341`):
one object carrying the swappable subsystems (router, session registry,
retain store, delayed sender, hook registry, ACL, fitter, metrics) that every
connection handler receives — the extension-manager seam
(`rmqtt/src/extend.rs:64-113`) where cluster/TPU implementations swap in.
"""

from __future__ import annotations

import asyncio
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from rmqtt_tpu.broker.acl import AclEngine
from rmqtt_tpu.broker.delayed import DelayedSender
from rmqtt_tpu.broker.fitter import Fitter, FitterConfig
from rmqtt_tpu.broker.hooks import HookRegistry
from rmqtt_tpu.broker.metrics import Metrics, Stats
from rmqtt_tpu.broker.retain import RetainStore
from rmqtt_tpu.broker.routing import RoutingService
from rmqtt_tpu.router.base import Router

#: period of the shared store expire-sweep task (ServerContext.start):
#: TTL'd rows are reaped for EVERY registered store — previously only the
#: message-storage plugin's flush loop swept, and only its own store
STORE_SWEEP_INTERVAL_S = 60.0


@dataclass
class BrokerConfig:
    host: str = "127.0.0.1"
    port: int = 1883
    # additional listeners (None = disabled, 0 = ephemeral); the reference
    # rmqtt-net supports TCP/TLS/WS/WSS (+QUIC, needs an external stack)
    ws_port: Optional[int] = None
    tls_port: Optional[int] = None
    wss_port: Optional[int] = None
    # MQTT over QUIC (rmqtt-net/src/quic.rs): served iff a QuicBackend is
    # registered (broker/quic.py); fails fast at startup otherwise
    quic_port: Optional[int] = None
    tls_cert: str = ""
    tls_key: str = ""
    # require + verify client certificates against this CA bundle; the cert's
    # CN/O/subject/serial land in ConnectInfo.cert_info (cert_extractor.rs)
    tls_client_ca: str = ""
    # PROXY protocol v1/v2 on the non-TLS listeners (builder.rs:152,466-474):
    # the advertised source replaces the socket peer address
    proxy_protocol: bool = False
    # SO_REUSEPORT on the client listeners: multiple worker processes bind
    # the same port and the kernel load-balances accepts — the multi-core
    # analogue of the reference's multi-thread tokio accept loops
    # (server.rs:229); workers peer over the cluster layer for cross-worker
    # delivery (see broker/__main__.py --workers)
    reuse_port: bool = False
    # additional NAMED listeners (reference rmqtt-conf/src/listener.rs:
    # [listener.tcp.<name>] / ws / tls / wss sub-tables, each its own
    # address and TLS material): dicts with keys
    # {name, kind: tcp|ws|tls|wss, host?, port, tls_cert?, tls_key?,
    #  tls_client_ca?} — the flat fields above stay the primary listener
    extra_listeners: List[Dict[str, Any]] = field(default_factory=list)
    node_id: int = 1
    router: str = "trie"  # "trie" (DefaultRouter) | "xla" (TPU)
    allow_anonymous: bool = True
    allow_zero_keepalive: bool = True
    max_connections: int = 1_000_000
    max_handshake_delay: float = 10.0
    max_packet_size: int = 1024 * 1024
    max_subscriptions: int = 0  # 0 = unlimited
    max_topic_levels: int = 0
    max_qos: int = 2
    retain_enable: bool = True
    retain_max: int = 1_000_000
    # switch retained wildcard lookups to the partitioned TPU inverse-match
    # kernel (ops/retained_part) once the store exceeds the threshold
    retain_tpu: bool = False
    retain_tpu_threshold: int = 50_000
    delayed_publish_max: int = 100_000
    shared_subscription: bool = True
    limit_subscription: bool = False  # enable $limit/$exclusive prefixes
    batch_max: int = 1024
    batch_linger_ms: float = 0.0  # 0 = latency-adaptive (no linger)
    # max routing batches past submit at once (1 = serial dispatch)
    routing_pipeline_depth: int = 3
    # pre-compile the device matcher's small-batch dispatch shapes at
    # start (background thread) so the first lone publishes don't pay an
    # XLA compile; no-op for routers without a device matcher
    routing_prewarm: bool = True
    # device-table churn resilience (ops/partitioned.py): incremental HBM
    # delta uploads (scatter only dirty chunks; off = full re-upload per
    # mutation) and background compaction (off = synchronous compact())
    routing_delta_uploads: bool = True
    routing_compact_async: bool = True
    # compaction trigger: dirty_ops > max(min_ops, table_size // ratio)
    routing_compact_min_ops: int = 1024
    routing_compact_ratio: int = 5
    # epoch-versioned publish→relations match cache (router/cache.py):
    # repeat-topic publishes skip the matcher entirely; entries invalidate
    # by per-first-segment epochs (exact filters) / a global wildcard epoch
    route_cache: bool = True
    route_cache_capacity: int = 8192
    # don't cache topics that match $share groups (the round-robin choice
    # is per-publish either way; bypass trades hit rate for zero reuse of
    # shared candidate sets)
    route_cache_shared_bypass: bool = False
    cluster: bool = False  # use a cluster-aware session registry
    cluster_mode: str = "broadcast"  # "broadcast" | "raft"
    # intra-node routing fabric (broker/fabric.py, [fabric] config section):
    # one router owner per node serving every SO_REUSEPORT worker over a
    # UDS mesh — batched publish submission, zero-copy QoS0 fan-out, and a
    # node-local subscription directory for O(1) CONNECT kicks. Disabled by
    # default: `--workers N` without [fabric] peers as a localhost
    # broadcast cluster exactly as before (zero-behavior-change pin).
    fabric_enable: bool = False
    fabric_dir: str = ""  # UDS socket directory (required when enabled)
    fabric_worker_id: int = 0  # 0 = use node_id
    fabric_owner_id: int = 1  # worker holding the device table + directory
    fabric_workers: int = 0  # expected worker count (informational)
    fabric_batch_max: int = 256  # publishes coalesced per submit frame
    fabric_call_timeout_s: float = 5.0
    # owner-outage bound: submits park this long awaiting reconnect +
    # re-register, then degrade to worker-local match (reason-counted)
    fabric_submit_deadline_s: float = 20.0
    # owner warm-up gate: a (re)spawned owner holds submitted fan-outs
    # until every expected worker has re-registered its table slice, or
    # this many seconds pass (so one dead worker can't stall the node)
    fabric_warm_grace_s: float = 10.0
    # overload protection (reference busy detection, node.rs:212-239 +
    # handshake executor limits, executor.rs:66-137). NOTE reference
    # semantics: new connections are REFUSED once a listener's active
    # handshakes exceed 35% of max_handshaking (executor.rs:100 busy rule)
    max_handshaking: int = 2000
    max_handshake_rate: float = 0.0  # 0 = unlimited, else handshakes/sec
    busy_loadavg: float = 0.0  # 0 = ignore; else refuse above load1/ncpu
    # latency telemetry (broker/telemetry.py, [observability] config
    # section): log2 stage histograms + slow-op ring. Disabled = the hot
    # paths never take a timestamp (single-branch guards)
    telemetry_enable: bool = True
    telemetry_slow_ms: float = 100.0  # ring-log threshold per op
    telemetry_slow_log_max: int = 256  # bounded slow-op ring size
    # distributed per-publish tracing (broker/tracing.py, same
    # [observability] section): head-sampling probability plus
    # always-record-on-slow (shares telemetry_slow_ms); bounded in-memory
    # span store. Tracing follows telemetry_enable — disabled means no
    # trace ids, no span allocations, no timestamps.
    trace_sample: float = 0.01  # probability a publish is head-sampled
    trace_max_traces: int = 512  # committed traces kept (FIFO eviction)
    trace_max_spans: int = 64  # spans kept per trace
    # device-plane profiler + flight recorder (broker/devprof.py, same
    # [observability] section): jit shape-key registry (compile hit vs
    # trace, retrace-storm detection), HBM occupancy model, dispatch
    # rollup time series and a bounded flight ring that auto-dumps on
    # failover trips / fused-verify disagreement / retrace storms.
    # device_profile=false keeps every instrumented jit seam at one
    # attribute check (no keys, no timestamps, no ring appends).
    device_profile: bool = True
    device_ring: int = 256  # flight-recorder record cap
    device_storm_n: int = 8  # traces within the window that flag a storm
    device_storm_window: float = 10.0  # seconds
    # host-plane profiler (broker/hostprof.py, same [observability]
    # section): event-loop lag sampler (scheduled-vs-actual wakeup delta
    # into a log2 histogram, lag-storm detection), GC pause forensics via
    # gc.callbacks, a blocking-call watchdog that captures the loop
    # thread's frame stack into a bounded incident ring, and fixed-
    # interval process rollups (fds / threads / executor / RSS).
    # host_profile=false starts no task, installs no gc callback and keeps
    # every seam at one attribute check.
    host_profile: bool = True
    host_block_ms: float = 150.0  # loop-tick gap that counts as blocked
    host_lag_storm_n: int = 8  # laggy ticks within the window = a storm
    host_lag_storm_window: float = 10.0  # seconds
    # devprof/hostprof rollup-ring retention (same [observability]
    # section): interval buckets kept per profiler — at the default 5 s
    # interval, 120 rollups = a 10-minute in-memory window
    device_rollup_max: int = 120
    host_rollup_max: int = 120
    # telemetry-history plane (broker/history.py, same [observability]
    # section): fixed-interval collector snapshotting every plane into
    # one sample row, bounded in-memory ring + (history_dir set)
    # CRC-framed on-disk segments with retention, range queries with
    # downsampling/cluster merge, and an EWMA+MAD anomaly annotator.
    # history=false starts no task and keeps every surface shape-stable.
    history_enable: bool = True
    history_interval_s: float = 5.0  # seconds between samples
    history_ring_max: int = 720  # in-memory samples (1 h at 5 s)
    history_dir: str = ""  # segment directory ("" = memory only)
    history_segment_rows: int = 2048  # samples per segment before rotate
    history_retention_segments: int = 16  # on-disk segments kept
    history_anomaly_enable: bool = True
    history_anomaly_k: float = 6.0  # breach at k x EWMA deviation
    history_anomaly_warmup: int = 8  # samples before a series can breach
    # hot-key attribution plane (broker/hotkeys.py, same [observability]
    # section): Space-Saving top-k + Count-Min sketches over publish
    # topics (count AND bytes), publishing clients, delivering
    # subscribers and first-segment filter prefixes, epoch-rotated
    # decay-window pairs, cluster-mergeable /hotkeys/sum, and a
    # transition-edged top-1-share alert (slow ring + SERVER_HOTKEY).
    # hotkeys=false starts no task and costs one attribute check/seam.
    hotkeys_enable: bool = True
    hotkeys_k: int = 64  # tracked keys per space (Space-Saving k)
    hotkeys_cms_width: int = 1024  # Count-Min columns (error ~ N/width)
    hotkeys_cms_depth: int = 4  # Count-Min rows (confidence)
    hotkeys_window_s: float = 30.0  # decay-window epoch length
    hotkeys_alert_share: float = 0.4  # top-1 share that pages
    # overload-control subsystem (broker/overload.py, [overload] config
    # section): watermark-driven NORMAL/ELEVATED/CRITICAL states, token-
    # bucket admission, degradation tiers, circuit-broken egress. Disabled
    # by default — enable=false is pinned to zero behavior change.
    overload_enable: bool = False
    overload_sample_interval: float = 1.0  # seconds between signal samples
    overload_clear_ratio: float = 0.85  # hysteresis: clear below ratio*mark
    overload_hold: int = 2  # consecutive clear samples before de-escalating
    # watermarks (fractions of capacity unless noted; 0 disables a signal)
    overload_queue_elevated: float = 0.5  # routing ingress-queue fraction
    overload_queue_critical: float = 0.9
    overload_mqueue_elevated: float = 0.6  # aggregate deliver-queue occupancy
    overload_mqueue_critical: float = 0.9
    overload_inflight_elevated: float = 0.85  # QoS1/2 window saturation
    overload_inflight_critical: float = 0.97
    overload_rss_elevated_mb: float = 0.0  # process RSS watermarks (MB)
    overload_rss_critical_mb: float = 0.0
    overload_connect_rate_elevated: float = 0.0  # handshakes/sec
    overload_connect_rate_critical: float = 0.0
    # admission token buckets (0 = unlimited; burst 0 = equal to the rate)
    overload_connect_rate_limit: float = 0.0  # per listener port
    overload_connect_burst: float = 0.0
    overload_publish_rate_limit: float = 0.0  # per client id
    overload_publish_burst: float = 0.0
    # degradation knobs
    overload_shed_slow_fraction: float = 0.5  # "slow consumer" queue fill
    overload_batch_shrink: int = 4  # max_batch divisor at ELEVATED+
    # circuit-breaker defaults (cluster transport + bridge producers)
    overload_breaker_threshold: int = 5
    overload_breaker_cooldown: float = 3.0
    overload_breaker_max_cooldown: float = 30.0
    # live SLO engine (broker/slo.py, [slo] config section): declarative
    # latency/availability objectives over the telemetry histograms and
    # reason-labeled drop counters, evaluated continuously into error
    # budgets + multi-window burn rates (fast/slow). Observe-only (never
    # touches the data plane); enable=false starts no task and samples
    # nothing while /api/v1/slo stays shape-stable.
    slo_enable: bool = True
    slo_sample_interval: float = 5.0  # seconds between samples
    slo_fast_window_s: float = 300.0  # fast burn window (cliff detector)
    slo_slow_window_s: float = 3600.0  # slow burn window (budget keeper)
    slo_burn_alert: float = 2.0  # fast burn rate that flags BURNING
    # declarative objectives ([[slo.objectives]] rows); empty = built-in
    # defaults (publish-e2e / connect latency + delivery availability)
    slo_objectives: List[Dict[str, Any]] = field(default_factory=list)
    # device-plane failover (broker/failover.py, [routing] failover_* keys):
    # classified device-router failures trip a breaker; while open, publishes
    # route through the host trie mirror, half-open probes rewarm (full HBM
    # re-upload) + canary-match before switching back. Only engages on
    # routers exposing a host fallback (XlaRouter's hybrid side table).
    failover_enable: bool = True
    failover_timeout_s: float = 30.0  # per-batch device deadline (watchdog)
    failover_threshold: int = 3  # consecutive failures before opening
    failover_cooldown: float = 1.0  # first probe delay (exp backoff after)
    failover_max_cooldown: float = 30.0
    failover_k_successes: int = 3  # consecutive canary passes to switch back
    # device-plane autotuner (broker/autotune.py, [routing] autotune* keys):
    # closed-loop controller from devprof rollups + routing telemetry to
    # the live knob registry (broker/knobs.py) — hysteresis-guarded
    # hill-climbing, one knob at a time, each change a canary epoch with
    # instant rollback + cooldown. Default OFF: enable=false starts no
    # task, writes no knob, and every surface stays shape-stable.
    autotune_enable: bool = False
    autotune_interval_s: float = 5.0  # controller tick period
    autotune_canary_k: int = 8  # dispatches that must vouch for a change
    autotune_cooldown_s: float = 30.0  # knob quarantine after a rollback
    autotune_p99_guard: float = 2.0  # canary p99 ceiling vs baseline
    # (2.0 = one log2 histogram bucket: adjacent-bucket moves are
    # quantization noise, two buckets is a real regression)
    autotune_confirm_ticks: int = 2  # consecutive ticks before a move
    autotune_journal_max: int = 256  # bounded decision-journal ring
    # crash-safe durability plane (broker/durability.py, [durability] conf
    # section): group-committed write-ahead journal of retained / session /
    # subscription / QoS1-2 pending state over a SqliteStore (or redis via
    # durability_storage), replayed into the live broker at boot before
    # listeners accept. Default OFF — enable=false constructs nothing and
    # is pinned to byte-for-byte zero behavior change.
    durability_enable: bool = False
    durability_path: str = "./data/durability.db"
    durability_storage: str = ""  # redis://... selects the RESP backend
    # group-commit window: acks wait at most this long for the batched
    # fsync; flush_max forces an early commit under burst load
    durability_flush_interval_ms: float = 5.0
    durability_flush_max: int = 512
    # journal rows past the last snapshot before compaction folds them in
    durability_compact_min: int = 4096
    # sqlite PRAGMA synchronous for the journal db: "full" = fsync per
    # group commit (the durability contract), "normal" trades crash
    # windows for throughput (redis durability is appendfsync policy)
    durability_sync: str = "full"
    # syscall-batched data plane (broker/egress.py, [network] conf
    # section): per-connection egress coalescing — every frame queued for
    # a socket within one loop tick joins a single vectored send instead
    # of one write per frame — and the hashed keepalive timer wheel (one
    # ticking task per worker instead of one timer per connection).
    # RMQTT_EGRESS_COALESCE=0 / RMQTT_KEEPALIVE_WHEEL=0 are operator
    # kill-switches the TOML knobs cannot override (AND-composed, the
    # RMQTT_DELTA_UPLOADS discipline).
    egress_coalesce: bool = True
    egress_high_water: int = 64 * 1024  # flush+drain past this many bytes
    keepalive_wheel: bool = True
    keepalive_wheel_tick: float = 1.0  # wheel resolution (seconds/slot)
    # [failpoints] conf section (utils/failpoints.py): site name → action
    # spec ("off | error | delay(ms) | hang | prob(p, act) | times(n, act)");
    # RMQTT_FAILPOINTS env entries override these at context construction
    failpoints: Dict[str, str] = field(default_factory=dict)
    fitter: FitterConfig = field(default_factory=FitterConfig)


class ServerContext:
    def __init__(
        self,
        cfg: Optional[BrokerConfig] = None,
        router: Optional[Router] = None,
        acl: Optional[AclEngine] = None,
    ) -> None:
        from rmqtt_tpu.broker.shared import SessionRegistry
        from rmqtt_tpu.router.default import DefaultRouter
        from rmqtt_tpu.router.xla import XlaRouter

        self.cfg = cfg or BrokerConfig()
        self.hooks = HookRegistry()
        self.metrics = Metrics()
        from rmqtt_tpu.broker.telemetry import Telemetry

        self.telemetry = Telemetry(
            enabled=self.cfg.telemetry_enable,
            slow_ms=self.cfg.telemetry_slow_ms,
            slow_log_max=self.cfg.telemetry_slow_log_max,
        )
        # per-publish trace registry (broker/tracing.py): shares the
        # telemetry enable/slow knobs so "slow" means the same thing in
        # the ring log, the histograms and the span store
        from rmqtt_tpu.broker.tracing import Tracer

        self.tracer = Tracer(
            enabled=self.cfg.telemetry_enable,
            sample=self.cfg.trace_sample,
            max_traces=self.cfg.trace_max_traces,
            max_spans=self.cfg.trace_max_spans,
            slow_ms=self.cfg.telemetry_slow_ms,
            node_id=self.cfg.node_id,
        )
        # v5 enhanced-auth seam (broker/auth.py); None = AUTH methods refused
        self.enhanced_auth = None
        if router is None:
            online = lambda cid: (
                self.registry.get(cid) is not None and self.registry.get(cid).connected
            )
            if self.cfg.router == "xla":
                # never hang the broker on a wedged/unreachable accelerator:
                # honor an explicit cpu request (a sitecustomize preload can
                # override JAX_PLATFORMS) or probe + fall back (tpuprobe)
                from rmqtt_tpu.utils.tpuprobe import ensure_safe_platform

                ensure_safe_platform()
                router = XlaRouter(is_online=online)
            elif self.cfg.router == "native":
                from rmqtt_tpu.router.native import NativeRouter

                router = NativeRouter(is_online=online)
            else:
                router = DefaultRouter(is_online=online)
        self.router = router
        # the router records its kernel.dispatch stage through the shared
        # registry (router/base.py telemetry seam)
        router.telemetry = self.telemetry
        # device-table churn knobs ([routing] section): applied to whatever
        # table/matcher the router owns, duck-typed so trie/native routers
        # (no device mirror) are untouched
        rtable = getattr(router, "table", None)
        if rtable is not None and hasattr(rtable, "compact_async"):
            rtable.compact_async = self.cfg.routing_compact_async
            rtable.compact_min_ops = self.cfg.routing_compact_min_ops
            rtable.compact_ratio = max(1, self.cfg.routing_compact_ratio)
        rmatcher = getattr(router, "matcher", None)
        if rmatcher is not None and hasattr(rmatcher, "delta_enabled"):
            # AND, don't assign: the matcher's __init__ already honored the
            # RMQTT_DELTA_UPLOADS=0 kill-switch — the TOML knob must not
            # silently re-enable the path over an operator's env override
            rmatcher.delta_enabled = (
                self.cfg.routing_delta_uploads and rmatcher.delta_enabled
            )
        self.routing = RoutingService(
            router,
            max_batch=self.cfg.batch_max,
            linger_ms=self.cfg.batch_linger_ms,
            pipeline_depth=self.cfg.routing_pipeline_depth,
            prewarm=self.cfg.routing_prewarm,
            cache_enable=self.cfg.route_cache,
            cache_capacity=self.cfg.route_cache_capacity,
            cache_shared_bypass=self.cfg.route_cache_shared_bypass,
            telemetry=self.telemetry,
        )
        self.retain = RetainStore(
            enable=self.cfg.retain_enable,
            max_retained=self.cfg.retain_max,
            tpu=self.cfg.retain_tpu,
            tpu_threshold=self.cfg.retain_tpu_threshold,
        )
        # MessageManager seam (message.rs:61-147): the message-storage
        # plugin installs itself here; None = storage disabled (the
        # reference's DefaultMessageManager no-op, message.rs:148-164)
        self.message_mgr = None
        # TTL'd stores registered for the shared expire-sweep task (started
        # in start()): any subsystem holding a SqliteStore/RedisStore adds
        # itself here so expired rows are reaped whether or not the
        # message-storage plugin (whose flush loop used to own the sweep)
        # happens to be configured
        self._stores: List[Any] = []
        self._store_sweep_task = None
        # crash-safe durability plane (broker/durability.py): None when
        # disabled — every hot-path guard is one attribute test, the
        # pinned zero-behavior-change contract
        self.durability = None
        if self.cfg.durability_enable:
            if self.cfg.fabric_enable:
                # one journal file cannot serve several worker processes:
                # concurrent recovery would duplicate every persistent
                # session per worker and concurrent appends share one seq
                # space (upserts silently overwrite each other's records)
                raise ValueError(
                    "[durability] cannot combine with [fabric] workers: "
                    "each process would recover and journal into the same "
                    "store (run durability on a single-process broker)")
            from rmqtt_tpu.broker.durability import DurabilityService

            self.durability = DurabilityService(self, self.cfg)
            # retained set/clear journals through the same on_set chain the
            # retainer plugin and cluster broadcast ride (chained, so all
            # three coexist); durability registers FIRST so later links
            # (cluster push) see an already-journaled mutation
            _prev_on_set = self.retain.on_set
            _dur = self.durability

            def _durable_on_set(topic, msg, _prev=_prev_on_set, _d=_dur):
                _d.on_retain(topic, msg)
                if _prev is not None:
                    _prev(topic, msg)

            self.retain.on_set = _durable_on_set
        # intra-node routing fabric (broker/fabric.py): one router owner per
        # node, workers submit publishes over a UDS mesh. Mutually exclusive
        # with the cluster registries — the fabric IS this node's internal
        # cluster; federating fabric nodes is ROADMAP item 3 territory.
        self.fabric = None
        if self.cfg.fabric_enable:
            if self.cfg.cluster:
                raise ValueError(
                    "[fabric] and [cluster] cannot combine in one process: "
                    "the fabric replaces the intra-node cluster peering")
            if not self.cfg.fabric_dir:
                raise ValueError("[fabric] enable=true requires fabric.dir")
            from rmqtt_tpu.broker.fabric import (
                FabricService,
                FabricSessionRegistry,
            )

            self.fabric = FabricService(self, self.cfg)
            self.routing.fabric = self.fabric
            self.registry = FabricSessionRegistry(self)
        elif self.cfg.cluster and self.cfg.cluster_mode == "raft":
            from rmqtt_tpu.cluster.raft_mode import RaftSessionRegistry

            self.registry = RaftSessionRegistry(self)
        elif self.cfg.cluster:
            from rmqtt_tpu.cluster.broadcast import ClusterSessionRegistry

            self.registry = ClusterSessionRegistry(self)
        else:
            self.registry = SessionRegistry(self)
        self.delayed = DelayedSender(self.registry.forwards, max_pending=self.cfg.delayed_publish_max)
        self.acl = acl or AclEngine()
        self.fitter = Fitter(self.cfg.fitter)
        self.node_id = self.cfg.node_id
        from rmqtt_tpu.plugins import PluginManager
        from rmqtt_tpu.utils.counter import RateCounter

        self.plugins = PluginManager(self)
        self.handshake_rate = RateCounter(window=5.0)
        from rmqtt_tpu.broker.executor import HandshakeExecutor

        self.hs_executor = HandshakeExecutor(
            workers=self.cfg.max_handshaking, queue_max=self.cfg.max_connections
        )
        # overload controller (broker/overload.py): constructed even when
        # disabled so every data-plane guard is one attribute test and the
        # breaker registry / snapshot surface always exist
        from rmqtt_tpu.broker.overload import OverloadController

        self.overload = OverloadController(self, self.cfg)
        # SLO engine (broker/slo.py): constructed unconditionally (like the
        # overload controller) so /api/v1/slo, the gauges and $SYS are
        # shape-stable; objective specs validate here, so a bad [slo]
        # section fails at broker construction, not mid-flight
        from rmqtt_tpu.broker.slo import SloEngine

        self.slo = SloEngine(self, self.cfg)
        # syscall-batched data plane (broker/egress.py): resolved flags
        # SessionState reads per connection. The env kill-switches AND
        # with the TOML knobs — a config file must never silently
        # re-enable a path an operator killed via env (the
        # RMQTT_DELTA_UPLOADS discipline above)
        self.egress_coalesce = (
            self.cfg.egress_coalesce
            and os.environ.get("RMQTT_EGRESS_COALESCE", "") != "0")
        self.egress_high_water = int(self.cfg.egress_high_water)
        self.keepalive_wheel = None
        if (self.cfg.keepalive_wheel
                and os.environ.get("RMQTT_KEEPALIVE_WHEEL", "") != "0"):
            from rmqtt_tpu.broker.egress import KeepaliveWheel

            self.keepalive_wheel = KeepaliveWheel(
                self.metrics, self.hooks,
                tick=self.cfg.keepalive_wheel_tick)
        # failpoints ([failpoints] conf section, utils/failpoints.py):
        # applied here so broker configs reach the process registry; the
        # RMQTT_FAILPOINTS env string is re-applied on top (env outranks
        # file, matching the load() precedence for every other section)
        if self.cfg.failpoints:
            import os as _os

            from rmqtt_tpu.utils.failpoints import FAILPOINTS

            FAILPOINTS.configure(self.cfg.failpoints)
            _env = _os.environ.get("RMQTT_FAILPOINTS", "")
            if _env:
                FAILPOINTS.configure_env(_env)
        # device-plane failover (broker/failover.py): wired only for routers
        # with a host fallback (XlaRouter's trie mirror); the breaker lives
        # in the overload registry so it surfaces in /api/v1/overload and
        # the open-breaker gauges like every other wrapped egress
        if self.cfg.failover_enable and callable(
            getattr(router, "host_available", None)
        ):
            from rmqtt_tpu.broker.failover import DeviceFailover

            self.routing.failover = DeviceFailover(
                router,
                self.overload.breaker(
                    "routing.device",
                    threshold=self.cfg.failover_threshold,
                    cooldown=self.cfg.failover_cooldown,
                    max_cooldown=self.cfg.failover_max_cooldown,
                ),
                timeout_s=self.cfg.failover_timeout_s,
                k_successes=self.cfg.failover_k_successes,
                metrics=self.metrics,
                telemetry=self.telemetry,
            )
        # runtime knob registry (broker/knobs.py): every device/batcher
        # kill-switch bound to its live object with provenance — the
        # autotuner's single read/write seam and /api/v1/routing/knobs.
        # Binding is read-only; building it changes no behavior.
        from rmqtt_tpu.broker.knobs import build_registry

        self.knobs = build_registry(router, self.routing, self.cfg)
        # device-plane autotuner (broker/autotune.py): constructed
        # unconditionally (like overload/slo) so /api/v1/autotune and the
        # gauges stay shape-stable; disabled = no task, no knob writes
        from rmqtt_tpu.broker.autotune import AutotuneService

        self.autotune = AutotuneService(
            self.knobs,
            enabled=self.cfg.autotune_enable,
            interval_s=self.cfg.autotune_interval_s,
            canary_k=self.cfg.autotune_canary_k,
            cooldown_s=self.cfg.autotune_cooldown_s,
            p99_guard=self.cfg.autotune_p99_guard,
            confirm_ticks=self.cfg.autotune_confirm_ticks,
            journal_max=self.cfg.autotune_journal_max,
            routing=self.routing,
            router=router,
            telemetry=self.telemetry,
            metrics=self.metrics,
            node_id=self.cfg.node_id,
        )
        # device-plane profiler + flight recorder (broker/devprof.py):
        # process-global like the failpoint registry (the jit caches it
        # models are process-global); the last-constructed context owns the
        # telemetry ring / HBM provider wiring. Enabling also turns on the
        # matcher's per-stage wall attribution (PR9 stage_timing) so the
        # routing_stage_* gauges and flight records carry stage deltas.
        from rmqtt_tpu.broker.devprof import DEVPROF

        DEVPROF.configure(
            enabled=self.cfg.device_profile,
            ring=self.cfg.device_ring,
            storm_n=self.cfg.device_storm_n,
            storm_window=self.cfg.device_storm_window,
            rollup_max=self.cfg.device_rollup_max,
            telemetry=self.telemetry,
            hbm_provider=getattr(router, "device_hbm", None),
        )
        if self.cfg.device_profile:
            rmatcher = getattr(router, "matcher", None)
            if rmatcher is not None and hasattr(rmatcher, "stage_timing"):
                rmatcher.stage_timing = True
        # host-plane profiler (broker/hostprof.py): process-global like
        # devprof (the event loop / GC / fd table it observes are
        # process-global); the last-constructed context owns the telemetry
        # ring + dispatch-probe wiring. The probe feeds the gc-during-
        # dispatch correlation (how many routing batches were in flight
        # when the collector stopped the world).
        from rmqtt_tpu.broker.hostprof import HOSTPROF

        routing = self.routing

        def _host_dispatch_probe(_r=routing) -> int:
            return _r.inflight + _r._q.qsize()

        self._host_dispatch_probe = _host_dispatch_probe
        self._hostprof_started = False
        HOSTPROF.configure(
            enabled=self.cfg.host_profile,
            block_ms=self.cfg.host_block_ms,
            lag_storm_n=self.cfg.host_lag_storm_n,
            lag_storm_window=self.cfg.host_lag_storm_window,
            rollup_max=self.cfg.host_rollup_max,
            telemetry=self.telemetry,
            dispatch_probe=_host_dispatch_probe,
        )
        # hot-key attribution plane (broker/hotkeys.py): streaming
        # heavy-hitter sketches over topics/clients/prefixes. Constructed
        # before the history plane (the collector samples its shares);
        # the routing seam is wired as an attribute so the disabled cost
        # on the dispatch path is literally one None test.
        from rmqtt_tpu.broker.hotkeys import HotkeysService

        self.hotkeys = HotkeysService(self, self.cfg)
        routing.hotkeys = self.hotkeys if self.hotkeys.enabled else None
        # telemetry-history plane (broker/history.py): the cross-plane
        # timeline collector. Constructed last so its collector sees every
        # other plane wired; recovery (history_dir set) runs here,
        # synchronously, so a restarted broker serves its pre-restart
        # timeline before the first new sample lands.
        from rmqtt_tpu.broker.history import HistoryService

        self.history = HistoryService(self, self.cfg)

    @property
    def handshaking(self) -> int:
        """In-flight handshakes across all listeners (executor active count)."""
        return self.hs_executor.active_count()

    def is_busy(self) -> bool:
        """Overload check before accepting a handshake (context.rs:400-406,
        node.rs:212-239): a busy handshake executor (ANY port above 35% of
        its worker bound — executor.rs:100-106,137 aggregates across ports
        the same way), handshake-rate cap, or 1-minute loadavg per cpu
        above threshold. Admission itself is the executor's job."""
        cfg = self.cfg
        if self.hs_executor.is_busy():
            return True
        if cfg.max_handshake_rate and self.handshake_rate.rate() > cfg.max_handshake_rate:
            return True
        if cfg.busy_loadavg:
            import os

            try:
                load1 = os.getloadavg()[0] / (os.cpu_count() or 1)
            except OSError:
                return False
            if load1 > cfg.busy_loadavg:
                return True
        return False

    # ------------------------------------------------------ store sweeping
    def add_store(self, store) -> None:
        """Register a TTL'd store for the periodic expire sweep (plugins
        and the durability plane call this; idempotent)."""
        if store not in self._stores:
            self._stores.append(store)

    def remove_store(self, store) -> None:
        if store in self._stores:
            self._stores.remove(store)

    async def sweep_stores_once(self) -> int:
        """Reap expired rows from every registered store (executor-hopped:
        network backends must not run socket RTTs on the loop). Returns
        rows reaped; failures skip to the next store — a dead backend must
        not starve the others."""
        import logging as _logging

        loop = asyncio.get_running_loop()
        reaped = 0
        for store in list(self._stores):
            try:
                reaped += int(await loop.run_in_executor(
                    None, store.expire_sweep) or 0)
            except Exception:
                _logging.getLogger("rmqtt_tpu.broker").warning(
                    "store expire sweep failed", exc_info=True)
        if reaped:
            self.metrics.inc("storage.expired_reaped", reaped)
        return reaped

    async def _store_sweep_loop(self) -> None:
        while True:
            await asyncio.sleep(STORE_SWEEP_INTERVAL_S)
            await self.sweep_stores_once()

    def start(self) -> None:
        self.routing.start()
        self.delayed.start()
        self.overload.start()
        self.slo.start()
        self.autotune.start()  # no-op while [routing] autotune = false
        self.hotkeys.start()  # no-op while [observability] hotkeys = false
        self.history.start()  # no-op while [observability] history = false
        # host-plane profiler: refcounted process-global start (a second
        # in-process broker shares the one sampler); no-op when disabled
        from rmqtt_tpu.broker.hostprof import HOSTPROF

        if HOSTPROF.enabled and not self._hostprof_started:
            HOSTPROF.start()
            self._hostprof_started = True
        if self.durability is not None:
            self.durability.start()
        if self.keepalive_wheel is not None:
            self.keepalive_wheel.start()
        if self._store_sweep_task is None:
            self._store_sweep_task = asyncio.get_running_loop().create_task(
                self._store_sweep_loop(), name="store-sweep")

    async def stop(self) -> None:
        # history first: its collector reads every other plane, so it must
        # stop (and close its open segment cleanly) before they do
        await self.history.stop()
        await self.hotkeys.stop()
        if self.fabric is not None:
            await self.fabric.stop()
        if self._store_sweep_task is not None:
            self._store_sweep_task.cancel()
            try:
                await self._store_sweep_task
            except asyncio.CancelledError:
                pass
            self._store_sweep_task = None
        if self.durability is not None:
            await self.durability.stop()
        if self.keepalive_wheel is not None:
            await self.keepalive_wheel.stop()
        await self.autotune.stop()
        await self.slo.stop()
        await self.overload.stop()
        await self.routing.stop()
        await self.delayed.stop()
        # unhook THIS context from the process-global profiler: a bound
        # hbm_provider would otherwise pin the router (and its whole match
        # table / device arrays) for the process lifetime and keep serving
        # a dead broker's HBM occupancy on /metrics scrapes
        from rmqtt_tpu.broker.devprof import DEVPROF

        if DEVPROF.telemetry is self.telemetry:
            DEVPROF.configure(telemetry=None)
        hp = DEVPROF.hbm_provider
        if hp is not None and getattr(hp, "__self__", None) is self.router:
            DEVPROF.configure(hbm_provider=None)
        # same unhook discipline for the host profiler: release this
        # context's refcount and drop closures that would pin the broker
        from rmqtt_tpu.broker.hostprof import HOSTPROF

        if self._hostprof_started:
            self._hostprof_started = False
            await HOSTPROF.stop()
        if HOSTPROF.telemetry is self.telemetry:
            HOSTPROF.configure(telemetry=None)
        if HOSTPROF.dispatch_probe is self._host_dispatch_probe:
            HOSTPROF.configure(dispatch_probe=None)

    def stats(self) -> Stats:
        s = Stats()
        s.connections = self.registry.connected_count()
        s.sessions = self.registry.session_count()
        s.subscriptions = self.router.routes_count()
        s.retaineds = self.retain.count()
        s.delayed_publishs = len(self.delayed)
        s.topics = self.router.topics_count()
        s.routes = self.router.routes_count()
        s.handshakings = self.metrics.get("connections.established")
        s.handshakings_active = self.hs_executor.active_count()
        s.handshakings_rate = int(self.handshake_rate.rate() * 100)
        s.forwards = self.metrics.get("cluster.forwards")
        s.message_storages = self.metrics.get("storage.messages_stored")
        s.subscriptions_shared = self.router.shared_groups_count()
        for sess in self.registry.sessions():
            s.message_queues += len(sess.deliver_queue)
            s.out_inflights += len(sess.out_inflight)
            s.in_inflights += len(sess.in_qos2)
        # routing-service gauges (per-exec stats parity, context.rs:506-555)
        for k, v in self.routing.stats().items():
            setattr(s, k, v)
        # overload gauges (broker/overload.py): state + breaker health
        s.overload_state = int(self.overload.state)
        s.overload_transitions = self.overload.transitions
        s.overload_open_breakers = sum(
            1 for b in self.overload.breakers.values()
            if b.state != b.CLOSED
        )
        # SLO gauges (broker/slo.py): worst objective state + transitions
        s.slo_state = int(self.slo.worst_state)
        s.slo_transitions = self.slo.transitions
        # autotuner gauges (broker/autotune.py): decision/commit/rollback
        # counters (summable in /stats/sum); zeros while disabled
        s.autotune_decisions = self.autotune.decisions
        s.autotune_commits = self.autotune.commits
        s.autotune_rollbacks = self.autotune.rollbacks
        # cluster membership + partition-healing gauges
        # (cluster/membership.py); the counters exist (zero) on single-node
        # brokers too, so dashboards keep one shape
        cluster = getattr(self.registry, "cluster", None)
        ms = getattr(cluster, "membership", None)
        if ms is not None:
            counts = ms.state_counts()
            s.cluster_peers_alive = counts["alive"]
            s.cluster_peers_suspect = counts["suspect"]
            s.cluster_peers_dead = counts["dead"]
        s.cluster_membership_transitions = self.metrics.get(
            "cluster.membership.transitions")
        s.cluster_retain_sync_dropped = self.metrics.get(
            "messages.dropped.retain_sync")
        s.cluster_fence_kicks = self.metrics.get("cluster.fence_kicks")
        s.cluster_anti_entropy_runs = self.metrics.get(
            "cluster.anti_entropy.runs")
        # syscall-batched data plane gauges (broker/egress.py): how many
        # frames the coalescer absorbed vs how many vectored writes it
        # issued (frames/flushes ≈ syscalls saved), plus wheel occupancy
        s.net_egress_frames = self.metrics.get("net.egress_frames")
        s.net_egress_flushes = self.metrics.get("net.egress_flushes")
        s.net_egress_bytes = self.metrics.get("net.egress_bytes")
        s.net_egress_coalesced = self.metrics.get("net.egress_coalesced")
        s.net_egress_drains = self.metrics.get("net.egress_drains")
        wheel = self.keepalive_wheel
        if wheel is not None:
            s.net_wheel_sessions = wheel.sessions
            s.net_wheel_timeouts = wheel.timeouts
        # device-plane profiler gauges (broker/devprof.py): jit registry
        # totals + retrace storms + modeled HBM residency (fleet-summable)
        from rmqtt_tpu.broker.devprof import DEVPROF

        s.device_jit_traces = DEVPROF.traces
        s.device_jit_cache_hits = DEVPROF.cache_hits
        s.device_retrace_storms = DEVPROF.storms
        # host-plane profiler gauges (broker/hostprof.py): loop-lag p99 +
        # laggy/storm/blocked/gc counters; zeros while host_profile is off
        # (the live /proc probes are skipped too — disabled costs nothing)
        from rmqtt_tpu.broker.hostprof import HOSTPROF

        if HOSTPROF.enabled:
            s.host_loop_lag_p99_ms = round(
                HOSTPROF.lag_hist.quantile(0.99) / 1e6, 3)
            s.host_loop_laggy_ticks = HOSTPROF.laggy_ticks
            s.host_lag_storms = HOSTPROF.lag_storms
            s.host_blocked_calls = HOSTPROF.blocked_calls
            s.host_gc_pauses = sum(HOSTPROF.gc_pauses.values())
            s.host_gc_pause_ms_total = round(
                sum(HOSTPROF.gc_pause_ns.values()) / 1e6, 3)
            from rmqtt_tpu.broker.hostprof import _fd_count
            import threading as _threading

            s.host_open_fds = _fd_count()
            s.host_threads = _threading.active_count()
        hbm = getattr(self.router, "device_hbm", None)
        if callable(hbm):
            try:
                s.device_hbm_modeled_mb = round(
                    (hbm() or {}).get("total_bytes", 0) / 2**20, 3)
            except Exception:
                pass
        # durability-plane gauges (broker/durability.py): journal health +
        # what the last cold-start recovery replayed; zeros while disabled
        dur = self.durability
        if dur is not None:
            s.durability_enabled = 1
            s.durability_journal_len = max(
                0, dur._committed - dur._snapshot_seq)
            s.durability_appends = dur.appends
            s.durability_commits = dur.commits
            s.durability_compactions = dur.compactions
            s.durability_recovered_retained = dur.recovered["retained"]
            s.durability_recovered_sessions = dur.recovered["sessions"]
            s.durability_recovered_subs = dur.recovered["subs"]
            s.durability_recovered_inflight = dur.recovered["inflight"]
            s.durability_recovery_ms = dur.recovery_ms
        # telemetry-history gauges (broker/history.py); zeros while the
        # collector is disabled so the surface stays shape-stable
        hist = self.history.snapshot()
        s.history_samples = hist["samples"]
        s.history_anomalies = hist["anomalies"]
        s.history_segments = hist["segments"]
        s.history_recovered_rows = hist["recovered_rows"]
        # hot-key attribution gauges (broker/hotkeys.py); zeros while
        # disabled. Tracked-key counts + counters only — the top-1 SHARE
        # stays off this surface (/stats/sum sums plain gauges; a summed
        # ratio lies) and rides the scrape/history instead
        for k, v in self.hotkeys.stats_block().items():
            setattr(s, k, v)
        # process RSS (utils/sysmon.py — same probe the overload sampler
        # uses); sums to a cluster memory total in /stats/sum
        from rmqtt_tpu.utils.sysmon import rss_mb

        s.rss_mb = rss_mb()
        return s
