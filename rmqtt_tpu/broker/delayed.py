"""Delayed publish: the ``$delayed/<secs>/<topic>`` scheme.

Mirrors `/root/reference/rmqtt/src/delayed.rs`: parse (:151-167), a bounded
min-heap of pending publishes drained by a background task (:103-129) that
re-injects them into the normal forward path when due; overflow is refused
(cap ``mqtt_delayed_publish_max``, context.rs:140).
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import time
from typing import Awaitable, Callable, List, Optional, Tuple

from rmqtt_tpu.broker.types import Message

PREFIX = "$delayed/"


def parse_delayed(topic: str) -> Tuple[Optional[int], str]:
    """``$delayed/5/a/b`` → ``(5, "a/b")``; non-delayed topics pass through."""
    if not topic.startswith(PREFIX):
        return None, topic
    rest = topic[len(PREFIX) :]
    idx = rest.find("/")
    if idx <= 0:
        raise ValueError(f"malformed $delayed topic: {topic!r}")
    try:
        secs = int(rest[:idx])
    except ValueError as e:
        raise ValueError(f"malformed $delayed interval in {topic!r}") from e
    target = rest[idx + 1 :]
    if not target or secs < 0:
        raise ValueError(f"malformed $delayed topic: {topic!r}")
    return secs, target


class DelayedSender:
    """Heap of pending delayed publishes + drain task (delayed.rs:103-129)."""

    def __init__(
        self,
        forward: Callable[[Message], Awaitable[None]],
        max_pending: int = 100_000,
    ) -> None:
        self._forward = forward
        self.max_pending = max_pending
        self._heap: List[Tuple[float, int, Message, int]] = []
        self._seq = itertools.count()
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        # durability seam (broker/durability.py): called as on_fired(did)
        # AFTER a journaled entry's forward completes, resolving its
        # durable record (a crash in between replays the fire — the
        # delayed path is at-least-once across kill -9, like QoS1)
        self.on_fired: Optional[Callable[[int], None]] = None

    def __len__(self) -> int:
        return len(self._heap)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def push(self, delay_secs: float, msg: Message, did: int = 0) -> bool:
        """Schedule; False if the pending cap is hit (message dropped).
        ``did`` is the durable journal id riding a journaled entry (0 =
        not journaled); it feeds ``on_fired`` after delivery."""
        if len(self._heap) >= self.max_pending:
            return False
        heapq.heappush(
            self._heap,
            (time.monotonic() + delay_secs, next(self._seq), msg, did))
        self._wake.set()
        return True

    async def _run(self) -> None:
        while True:
            if not self._heap:
                self._wake.clear()
                await self._wake.wait()
            due, _, msg, did = self._heap[0]
            delay = due - time.monotonic()
            if delay > 0:
                try:
                    await asyncio.wait_for(self._wake.wait(), timeout=delay)
                    self._wake.clear()
                    continue  # new earlier item may have arrived
                except asyncio.TimeoutError:
                    pass
            heapq.heappop(self._heap)
            if not msg.is_expired():
                await self._forward(msg)
            if did and self.on_fired is not None:
                # resolve the durable record only after the forward (whose
                # own enq records precede this in the journal) completed
                self.on_fired(did)
