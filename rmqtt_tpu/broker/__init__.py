"""Host data plane: the broker around the TPU routing core.

The equivalent of the reference's broker core crate (`/root/reference/rmqtt/`),
re-designed for the asyncio host + TPU-matcher split: listeners, the MQTT
v3.1/v3.1.1/v5 codec, per-connection session state machines, the shared
session registry and fan-out, retained/delayed/will messages, hooks, ACL —
with `Router::matches()` served by a micro-batched routing service
(`rmqtt_tpu.broker.routing`) instead of an inline trie walk.
"""
