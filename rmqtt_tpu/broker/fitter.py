"""Per-connection negotiated limits.

Mirrors the reference ``Fitter`` (`/root/reference/rmqtt/src/fitter.rs`):
keepalive clamping with backoff factor (:127-163), max message-queue length
(:166), max inflight window min'd with the client's v5 Receive-Maximum
(:176-188), session expiry from v5 properties capped by server config
(:191-215), message expiry cap (:218-226), and topic-alias maxima (:229-244).
"""

from __future__ import annotations

from dataclasses import dataclass

from rmqtt_tpu.broker.codec import packets as pk, props as P
from rmqtt_tpu.broker.types import ConnectInfo


@dataclass
class Limits:
    keepalive: int
    server_keepalive: bool  # True if the server overrode the client's value
    max_inflight: int
    max_mqueue: int
    session_expiry: float
    max_message_expiry: float
    max_topic_aliases_in: int
    max_topic_aliases_out: int
    max_packet_size: int


@dataclass
class FitterConfig:
    max_keepalive: int = 0  # 0 = no clamp
    min_keepalive: int = 0
    keepalive_backoff: float = 0.75  # timeout factor: keepalive * backoff * 2
    max_inflight: int = 16
    max_mqueue: int = 1000
    max_session_expiry: float = 2 * 3600.0
    default_session_expiry: float = 2 * 3600.0  # for v3 clean_session=0
    max_message_expiry: float = 5 * 60.0
    max_topic_aliases: int = 32
    max_packet_size: int = 1024 * 1024


class Fitter:
    def __init__(self, cfg: FitterConfig) -> None:
        self.cfg = cfg

    def fit(self, ci: ConnectInfo) -> Limits:
        cfg = self.cfg
        keepalive = ci.keepalive
        server_keepalive = False
        if cfg.max_keepalive and keepalive > cfg.max_keepalive:
            keepalive, server_keepalive = cfg.max_keepalive, True
        if cfg.min_keepalive and 0 < keepalive < cfg.min_keepalive:
            keepalive, server_keepalive = cfg.min_keepalive, True

        recv_max = ci.properties.get(P.RECEIVE_MAXIMUM)
        max_inflight = cfg.max_inflight
        if recv_max:
            max_inflight = min(max_inflight, int(recv_max)) or 1

        if ci.protocol == pk.V5:
            expiry = float(ci.properties.get(P.SESSION_EXPIRY_INTERVAL, 0))
            if expiry == 0xFFFFFFFF:
                expiry = cfg.max_session_expiry
            session_expiry = min(expiry, cfg.max_session_expiry)
        else:
            session_expiry = 0.0 if ci.clean_start else cfg.default_session_expiry

        alias_out = int(ci.properties.get(P.TOPIC_ALIAS_MAXIMUM, 0))
        return Limits(
            keepalive=keepalive,
            server_keepalive=server_keepalive,
            max_inflight=max_inflight,
            max_mqueue=cfg.max_mqueue,
            session_expiry=session_expiry,
            max_message_expiry=cfg.max_message_expiry,
            max_topic_aliases_in=cfg.max_topic_aliases if ci.protocol == pk.V5 else 0,
            max_topic_aliases_out=min(alias_out, cfg.max_topic_aliases),
            max_packet_size=cfg.max_packet_size,
        )

    def keepalive_timeout(self, keepalive: int) -> float:
        """Socket-idle deadline, always > keepalive (fitter.rs:158-163:
        small keepalives get +3s slack, otherwise keepalive * backoff * 2 —
        1.5x with the default backoff of 0.75)."""
        if keepalive == 0:
            return 0.0
        if keepalive < 6:
            return float(keepalive + 3)
        return keepalive * self.cfg.keepalive_backoff * 2
