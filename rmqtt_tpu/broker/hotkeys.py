"""Hot-key attribution plane: streaming heavy-hitter sketches.

Every observability plane so far (telemetry, SLO, devprof, hostprof,
history) reports **global aggregates** — at 10M subscriptions nobody can
answer "*which* topic is melting the broker, *which* client is the top
talker, *which* filter prefix is driving automaton retraces", because
per-entity counters would be unbounded cardinality. This plane answers
those questions in O(k) memory with streaming sketches, the structure
the IoT-broker benchmarking literature motivates: production MQTT key
distributions are zipf-skewed, so a tiny summary captures the keys that
matter.

Two sketches per key space, both **mergeable** (the cluster /sum path
depends on it):

- **Space-Saving top-k** (Metwally et al.): at most ``k`` tracked keys;
  a new key evicts the current minimum and inherits its count as its
  per-entry error bound, so every reported count ``c`` with error ``e``
  brackets the true count in ``[c - e, c]`` and ``e <= N/k``. Two
  summaries merge via the Agarwal et al. mergeable-summaries rule
  (absent keys contribute the donor's floor to both count and error),
  preserving the bracket fleet-wide.
- **Count-Min** (Cormode/Muthukrishnan): point queries for keys that
  fell out of the top-k, merged cell-wise. Hashing is ``zlib.crc32``
  with per-row seeds — deliberately NOT the builtin ``hash()``, whose
  per-process salt (PYTHONHASHSEED) would make cross-node merges
  meaningless.

Four key spaces (+ bytes and drops views): publish topics by count AND
payload bytes, publishing clients, delivering subscriber clients, and
first-segment/namespace filter prefixes — the future tenant key
(ROADMAP item 6), recorded at RoutingService dispatch so automaton work
is attributable to a prefix. Reason-labeled drops gain a hot-key
dimension (``reason:key`` composite space). Distinct-key cardinality
rides a linear-counting bitmap (OR-mergeable) per space.

"Hot *now*", not since boot: every space keeps an epoch-rotated
**pair** of windows (current + previous); queries merge the pair, so
answers cover between one and two windows of history and an idle key
ages out after two rotations.

When the merged top-1 share of a space crosses ``hotkeys_alert_share``
(the "one tenant is 40% of the broker" page), the plane lands a
``hotkeys.alert`` row on the shared slow-op ring and fires the
``SERVER_HOOK``-family ``SERVER_HOTKEY`` hook — transition-edged like
the overload/SLO planes, so one hot episode is one page.

House pattern: ``[observability] hotkeys*`` knobs, default ON;
``hotkeys = false`` costs one attribute check per seam and every
surface stays shape-stable.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from rmqtt_tpu.broker.hooks import HookType
from rmqtt_tpu.utils.failpoints import FAILPOINTS

log = logging.getLogger("rmqtt_tpu.hotkeys")

SCHEMA = "rmqtt_tpu.hotkeys/1"

_FP_ROTATE = FAILPOINTS.register("hotkeys.rotate")

#: the attribution spaces every surface iterates, in render order.
#: topic_bytes shares the topics key space (weighted by payload size);
#: drops is the ``reason:key`` composite the drop seams feed.
SPACES = ("topics", "topic_bytes", "publishers", "subscribers",
          "prefixes", "drops")

#: spaces the top-1-share alert watches (byte-weighted and drop views
#: are diagnostic, not paging signals)
ALERT_SPACES = ("topics", "publishers", "subscribers", "prefixes")

#: a window must have seen at least this many events before its top-1
#: share can alert — a 2-event window where one key is "50%" is noise,
#: not a noisy neighbor
ALERT_MIN_EVENTS = 50

#: entries per space exported to Prometheus (<= k by construction; the
#: full top-k rides the JSON endpoints — the scrape stays cardinality-
#: bounded at len(SPACES) * _EXPORT_TOP rows)
_EXPORT_TOP = 8

#: linear-counting bitmap size in bits (power of two; ~2% distinct-count
#: error up to ~2800 distinct keys per window, saturating gracefully)
_LC_BITS = 4096

#: per-row CMS hash seeds are derived from this odd constant; crc32
#: accepts an initial value, giving d independent-enough hash functions
_SEED_MULT = 0x9E3779B9

#: hot-path seams only APPEND to pending buffers; a buffer reaching this
#: size drains inline, bounding memory between rotator ticks
_PENDING_MAX = 16384


def first_segment(topic: str) -> str:
    """The namespace/tenant key: everything before the first ``/``.
    A leading-slash topic's first segment is empty — map it to ``/`` so
    the sketch key is never the empty string."""
    seg = topic.split("/", 1)[0]
    return seg if seg else "/"


class SpaceSaving:
    """Bounded top-k with per-entry error: ``counts[key]`` overestimates
    the true count by at most ``errs[key]`` (the evicted minimum the
    entry inherited), and any untracked key's true count is <= the
    current floor. O(k) on the eviction path only; hits are one dict op."""

    __slots__ = ("k", "counts", "errs")

    def __init__(self, k: int) -> None:
        self.k = max(1, int(k))
        self.counts: Dict[str, int] = {}
        self.errs: Dict[str, int] = {}

    def offer(self, key: str, inc: int = 1) -> None:
        c = self.counts
        v = c.get(key)
        if v is not None:
            c[key] = v + inc
            return
        if len(c) < self.k:
            c[key] = inc
            self.errs[key] = 0
            return
        victim = min(c, key=c.get)
        floor = c.pop(victim)
        self.errs.pop(victim, None)
        c[key] = floor + inc
        self.errs[key] = floor

    def floor(self) -> int:
        """Upper bound on any UNTRACKED key's count (0 until full)."""
        if len(self.counts) < self.k:
            return 0
        return min(self.counts.values()) if self.counts else 0

    def entries(self) -> List[dict]:
        return [
            {"key": k, "count": c, "err": self.errs.get(k, 0)}
            for k, c in sorted(self.counts.items(),
                               key=lambda kv: kv[1], reverse=True)
        ]


def merge_topk(a: List[dict], a_floor: int, b: List[dict], b_floor: int,
               k: int) -> Tuple[List[dict], int]:
    """Mergeable-summaries rule over entry lists: a key absent from one
    side contributes that side's floor to BOTH count and error (its true
    count there is somewhere in [0, floor]), so the merged bracket
    ``[count - err, count]`` still contains the true combined count.
    Returns the top-k of the union plus the merged floor."""
    cand: Dict[str, List[int]] = {}
    for ent in a:
        cand[ent["key"]] = [int(ent["count"]), int(ent.get("err", 0))]
    for ent in b:
        cur = cand.get(ent["key"])
        if cur is None:
            cand[ent["key"]] = [int(ent["count"]) + a_floor,
                                int(ent.get("err", 0)) + a_floor]
        else:
            cur[0] += int(ent["count"])
            cur[1] += int(ent.get("err", 0))
    b_keys = {ent["key"] for ent in b}
    for key, cur in cand.items():
        if key not in b_keys:
            cur[0] += b_floor
            cur[1] += b_floor
    top = sorted(cand.items(), key=lambda kv: kv[1][0], reverse=True)[:k]
    return ([{"key": key, "count": c, "err": e} for key, (c, e) in top],
            a_floor + b_floor)


class CountMin:
    """d x w counter matrix; point estimate = min over rows (always an
    overestimate, off by at most eN/w with probability 1 - delta^d).
    Deterministic crc32-per-row hashing keeps two nodes' sketches
    cell-compatible; merge is element-wise addition."""

    __slots__ = ("width", "depth", "rows")

    def __init__(self, width: int, depth: int) -> None:
        self.width = max(8, int(width))
        self.depth = max(1, int(depth))
        self.rows: List[List[int]] = [
            [0] * self.width for _ in range(self.depth)]

    def add_data(self, data: bytes, inc: int = 1) -> None:
        w = self.width
        for r, row in enumerate(self.rows):
            row[zlib.crc32(data, (_SEED_MULT * (r + 1)) & 0xFFFFFFFF) % w] \
                += inc

    def query(self, key: str) -> int:
        data = key.encode("utf-8", "surrogatepass")
        w = self.width
        return min(
            row[zlib.crc32(data, (_SEED_MULT * (r + 1)) & 0xFFFFFFFF) % w]
            for r, row in enumerate(self.rows))

    def merge(self, other: "CountMin") -> None:
        if other.width != self.width or other.depth != self.depth:
            raise ValueError("CMS shape mismatch")
        for row, orow in zip(self.rows, other.rows):
            for i, v in enumerate(orow):
                if v:
                    row[i] += v


class _Window:
    """One epoch of one key space: Space-Saving + (optional) Count-Min +
    linear-counting distinct bitmap + event total."""

    __slots__ = ("ss", "cms", "bitmap", "total", "t0")

    def __init__(self, k: int, width: int, depth: int, now: float,
                 cms: bool = True) -> None:
        self.ss = SpaceSaving(k)
        self.cms = CountMin(width, depth) if cms else None
        self.bitmap = bytearray(_LC_BITS >> 3)
        self.total = 0
        self.t0 = now

    def offer(self, key: str, inc: int = 1) -> None:
        self.total += inc
        self.ss.offer(key, inc)
        data = key.encode("utf-8", "surrogatepass")
        if self.cms is not None:
            self.cms.add_data(data, inc)
        h = zlib.crc32(data) % _LC_BITS
        self.bitmap[h >> 3] |= 1 << (h & 7)

    def distinct_est(self) -> int:
        zeros = sum(_ZERO_BITS[b] for b in self.bitmap)
        if zeros == 0:  # saturated: the estimator diverges; report cap
            return _LC_BITS
        return int(round(-_LC_BITS * math.log(zeros / _LC_BITS)))


#: zero-bit count per byte value, for the linear-counting estimator
_ZERO_BITS = [8 - bin(i).count("1") for i in range(256)]


def _union_distinct(a: bytearray, b: bytearray) -> int:
    zeros = sum(_ZERO_BITS[x | y] for x, y in zip(a, b))
    if zeros == 0:
        return _LC_BITS
    return int(round(-_LC_BITS * math.log(zeros / _LC_BITS)))


class _Space:
    """One attribution dimension: an epoch-rotated pair of windows.
    Queries merge (cur, prev) so the answer always covers at least one
    full window — "hot now", with keys aging out after two rotations."""

    __slots__ = ("name", "k", "width", "depth", "has_cms",
                 "cur", "prev", "alerting")

    def __init__(self, name: str, k: int, width: int, depth: int,
                 now: float, cms: bool = True) -> None:
        self.name = name
        self.k = k
        self.width = width
        self.depth = depth
        self.has_cms = cms
        self.cur = _Window(k, width, depth, now, cms)
        self.prev = _Window(k, width, depth, now, cms)
        self.alerting = False

    def offer(self, key: str, inc: int = 1) -> None:
        self.cur.offer(key, inc)

    def rotate(self, now: float) -> None:
        self.prev = self.cur
        self.cur = _Window(self.k, self.width, self.depth, now,
                           self.has_cms)

    def total(self) -> int:
        return self.cur.total + self.prev.total

    def merged_top(self) -> Tuple[List[dict], int]:
        return merge_topk(self.cur.ss.entries(), self.cur.ss.floor(),
                          self.prev.ss.entries(), self.prev.ss.floor(),
                          self.k)

    def point(self, key: str) -> int:
        """CMS point estimate over the live pair (windows see disjoint
        sub-streams, so the upper-bound estimates add)."""
        if not self.has_cms:
            return 0
        return self.cur.cms.query(key) + self.prev.cms.query(key)

    def view(self) -> dict:
        top, floor = self.merged_top()
        total = self.total()
        for ent in top:
            ent["share"] = round(ent["count"] / total, 4) if total else 0.0
        return {
            "total": total,
            "distinct_est": _union_distinct(self.cur.bitmap,
                                            self.prev.bitmap),
            "floor": floor,
            "top": top,
            "alerting": self.alerting,
        }


class HotkeysService:
    """The attribution plane: four-plus-two sketched key spaces, window
    rotation, the top-1-share alert, and every admin surface. Constructed
    unconditionally by ``ServerContext`` (shape-stable surfaces); the
    hot-path seams guard on one ``enabled`` attribute check."""

    def __init__(self, ctx, cfg) -> None:
        self.ctx = ctx
        self.enabled = bool(cfg.hotkeys_enable)
        self.k = max(8, int(cfg.hotkeys_k))
        self.width = max(8, int(cfg.hotkeys_cms_width))
        self.depth = max(1, int(cfg.hotkeys_cms_depth))
        self.window_s = max(0.05, float(cfg.hotkeys_window_s))
        self.alert_share = min(1.0, max(0.01,
                                        float(cfg.hotkeys_alert_share)))
        now = time.time()
        # the byte-weighted and drop views skip the CMS (same key space
        # as topics / diagnostic-only): halves the per-publish hash work
        self.spaces: Dict[str, _Space] = {
            name: _Space(name, self.k, self.width, self.depth, now,
                         cms=name not in ("topic_bytes", "drops"))
            for name in SPACES
        }
        self.rotations = 0
        self.alerts_total = 0
        self.alerts_by_space: Dict[str, int] = {s: 0 for s in ALERT_SPACES}
        self._task: Optional[asyncio.Task] = None
        # pending seam events, folded into the sketches by drain()
        self._pend_pub: List[Tuple[str, str, int]] = []
        self._pend_disp: List[str] = []
        self._pend_sub: List[str] = []
        self._pend_drop: List[str] = []

    # ------------------------------------------------------------ hot seams
    # Each seam is one method call behind one `enabled` check at the call
    # site, and the body is ONE list append — the crc32/dict sketch work
    # runs in drain(), amortized per DISTINCT buffered key (zipf-skewed
    # traffic collapses thousands of events into tens of offers). Every
    # query and the rotator tick drain first, so answers stay exact.

    def on_publish(self, topic: str, client_id: str, nbytes: int) -> None:
        """Session publish ingress: topic by count AND bytes, publisher."""
        buf = self._pend_pub
        buf.append((topic, client_id, nbytes))
        if len(buf) >= _PENDING_MAX:
            self.drain()

    def on_dispatch(self, topic: str) -> None:
        """RoutingService dispatch: attribute automaton work to the
        first-segment/namespace prefix (the future tenant key)."""
        buf = self._pend_disp
        buf.append(topic)
        if len(buf) >= _PENDING_MAX:
            self.drain()

    def on_dispatch_items(self, items) -> None:
        """Bulk dispatch seam: one call per routed batch of
        ``(fid, topic)`` items (what ``RoutingService._dispatch_one``
        hands the fabric) instead of one per item."""
        buf = self._pend_disp
        buf.extend(t for _f, t in items)
        if len(buf) >= _PENDING_MAX:
            self.drain()

    def on_deliver(self, client_id: str) -> None:
        """Delivery send: the subscriber actually receiving bytes."""
        buf = self._pend_sub
        buf.append(client_id)
        if len(buf) >= _PENDING_MAX:
            self.drain()

    def on_drop(self, reason: str, key: str) -> None:
        """Reason-labeled drop sites gain a hot-key dimension: which
        client/topic is behind the queue_full (etc.) counters."""
        buf = self._pend_drop
        buf.append(reason + ":" + key)
        if len(buf) >= _PENDING_MAX:
            self.drain()

    def drain(self) -> None:
        """Fold the buffered seam events into the sketches, aggregating
        per distinct key first so the hash work scales with key
        cardinality, not event volume."""
        sp = self.spaces
        pubs, self._pend_pub = self._pend_pub, []
        if pubs:
            tc: Dict[str, int] = {}
            tb: Dict[str, int] = {}
            pc: Dict[str, int] = {}
            for topic, cid, nbytes in pubs:
                tc[topic] = tc.get(topic, 0) + 1
                if nbytes > 0:
                    tb[topic] = tb.get(topic, 0) + nbytes
                pc[cid] = pc.get(cid, 0) + 1
            offer = sp["topics"].offer
            for key, n in tc.items():
                offer(key, n)
            offer = sp["topic_bytes"].offer
            for key, n in tb.items():
                offer(key, n)
            offer = sp["publishers"].offer
            for key, n in pc.items():
                offer(key, n)
        disp, self._pend_disp = self._pend_disp, []
        if disp:
            fc: Dict[str, int] = {}
            for topic in disp:
                fc[topic] = fc.get(topic, 0) + 1
            pf: Dict[str, int] = {}
            for topic, n in fc.items():
                seg = first_segment(topic)
                pf[seg] = pf.get(seg, 0) + n
            offer = sp["prefixes"].offer
            for key, n in pf.items():
                offer(key, n)
        subs, self._pend_sub = self._pend_sub, []
        if subs:
            sc: Dict[str, int] = {}
            for cid in subs:
                sc[cid] = sc.get(cid, 0) + 1
            offer = sp["subscribers"].offer
            for key, n in sc.items():
                offer(key, n)
        drops, self._pend_drop = self._pend_drop, []
        if drops:
            dc: Dict[str, int] = {}
            for key in drops:
                dc[key] = dc.get(key, 0) + 1
            offer = sp["drops"].offer
            for key, n in dc.items():
                offer(key, n)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        """Arm the rotation/alert task on the RUNNING loop (sync, like
        every plane armed from ``ServerContext.start``)."""
        if not self.enabled:
            return
        if self._task is not None and not self._task.done():
            return
        self._task = asyncio.get_running_loop().create_task(
            self._run(), name="hotkeys-rotator")

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass

    async def _run(self) -> None:
        # alert check at half-window cadence (an episode is noticed
        # within window_s/2), rotation on the full window
        half = self.window_s / 2.0
        while True:
            await asyncio.sleep(half)
            try:
                self.check_alerts()
                if time.time() - self.spaces["topics"].cur.t0 \
                        >= self.window_s:
                    self.rotate()
            except Exception:
                log.exception("hotkeys rotation failed")

    def rotate(self) -> None:
        """Epoch rotation: cur -> prev, fresh cur. Public and
        synchronous so tests and drills drive epochs directly."""
        self.drain()
        if _FP_ROTATE.action is not None:  # chaos seam: a provokable
            _FP_ROTATE.fire_sync()         # rotation stall/fault
        now = time.time()
        for space in self.spaces.values():
            space.rotate(now)
        self.rotations += 1

    # -------------------------------------------------------------- alerts
    def check_alerts(self) -> List[dict]:
        """Transition-edged top-1-share watchdog over the alert spaces:
        entering an episode lands ONE ``hotkeys.alert`` slow-ring row and
        ONE ``SERVER_HOTKEY`` hook fire; the flag clears when the share
        falls back under the threshold. Returns the rows fired (tests)."""
        fired: List[dict] = []
        if not self.enabled:
            return fired
        self.drain()
        for name in ALERT_SPACES:
            space = self.spaces[name]
            total = space.total()
            if total < ALERT_MIN_EVENTS:
                space.alerting = False
                continue
            top, _floor = space.merged_top()
            if not top:
                space.alerting = False
                continue
            share = top[0]["count"] / total
            if share < self.alert_share:
                space.alerting = False
                continue
            if space.alerting:
                continue  # already inside this episode
            space.alerting = True
            self.alerts_total += 1
            self.alerts_by_space[name] = self.alerts_by_space.get(name, 0) + 1
            row = {
                "space": name,
                "key": top[0]["key"],
                "share": round(share, 4),
                "count": top[0]["count"],
                "total": total,
                "threshold": self.alert_share,
            }
            fired.append(row)
            self._fire(name, row)
        return fired

    def _fire(self, space: str, row: dict) -> None:
        """Slow-op ring row + SERVER_HOTKEY hook — the exact transition
        idiom of slo.py/overload.py/history.py, so hot-key episodes join
        the shared correlation timeline ops_doctor renders."""
        tele = getattr(self.ctx, "telemetry", None)
        if tele is not None and getattr(tele, "enabled", False):
            tele.slow_ops.append({
                "op": "hotkeys.alert", "ms": 0.0,
                "ts": round(time.time(), 3),
                "detail": dict(row),
            })
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # check_alerts() driven synchronously in tests
        loop.create_task(self.ctx.hooks.fire(
            HookType.SERVER_HOTKEY, space, row["key"], row))

    # ------------------------------------------------------------- queries
    def point(self, space: str, key: str) -> int:
        """CMS point estimate for any key, tracked or not (0 for spaces
        without a CMS and unknown space names — never raises)."""
        self.drain()
        sp = self.spaces.get(space)
        return sp.point(key) if sp is not None else 0

    def snapshot(self) -> dict:
        """The `/api/v1/hotkeys` body. Shape-stable when disabled: same
        keys, empty tops, zero totals."""
        self.drain()
        return {
            "schema": SCHEMA,
            "enabled": self.enabled,
            "node": getattr(self.ctx.cfg, "node_id", 0),
            "k": self.k,
            "window_s": self.window_s,
            "alert_share": self.alert_share,
            "rotations": self.rotations,
            "alerts_total": self.alerts_total,
            "spaces": {name: self.spaces[name].view() for name in SPACES},
        }

    @staticmethod
    def merge_snapshots(base: dict, others: List[dict]) -> dict:
        """Cluster merge (`/api/v1/hotkeys/sum`): per space, fold the
        node top-k lists under the mergeable-summaries rule (floors
        substitute for absent keys, so the error bracket survives the
        merge); totals and alert counters sum; distinct estimates sum
        (an upper bound — per-node bitmaps are not shipped)."""
        snaps = [base, *list(others)]
        k = max(int(s.get("k") or 1) for s in snaps)
        spaces: Dict[str, Any] = {}
        for name in SPACES:
            top: List[dict] = []
            floor = 0
            total = 0
            distinct = 0
            alerting = False
            for snap in snaps:
                sv = (snap.get("spaces") or {}).get(name) or {}
                top, floor = merge_topk(
                    top, floor,
                    list(sv.get("top") or ()), int(sv.get("floor") or 0),
                    k)
                total += int(sv.get("total") or 0)
                distinct += int(sv.get("distinct_est") or 0)
                alerting = alerting or bool(sv.get("alerting"))
            for ent in top:
                ent["share"] = (round(ent["count"] / total, 4)
                                if total else 0.0)
            spaces[name] = {
                "total": total,
                "distinct_est": distinct,
                "floor": floor,
                "top": top,
                "alerting": alerting,
            }
        return {
            "schema": SCHEMA,
            "nodes": len(snaps),
            "enabled": any(s.get("enabled") for s in snaps),
            "k": k,
            "rotations": sum(int(s.get("rotations") or 0) for s in snaps),
            "alerts_total": sum(int(s.get("alerts_total") or 0)
                                for s in snaps),
            "spaces": spaces,
        }

    # ------------------------------------------------------------- surfaces
    def stats_block(self) -> Dict[str, int]:
        """Small gauge block for ``ServerContext.stats()``. Tracked-key
        counts and event counters only — the top-1 SHARE deliberately
        stays off this surface (/stats/sum SUMS plain gauges; a summed
        ratio is a lie) and rides prometheus_lines/history instead."""
        self.drain()
        sp = self.spaces
        return {
            "hotkeys_topics_tracked": len(sp["topics"].cur.ss.counts),
            "hotkeys_publishers_tracked": len(sp["publishers"].cur.ss.counts),
            "hotkeys_subscribers_tracked": len(
                sp["subscribers"].cur.ss.counts),
            "hotkeys_prefixes_tracked": len(sp["prefixes"].cur.ss.counts),
            "hotkeys_rotations": self.rotations,
            "hotkeys_alerts": self.alerts_total,
        }

    def history_summary(self) -> Dict[str, float]:
        """Per-sample block for the history collector: top-1/top-8 share
        + distinct estimate per alert space, plus the headline
        ``top1_share`` (the max across spaces — the earliest
        noisy-neighbor signal the anomaly annotator watches)."""
        self.drain()
        out: Dict[str, float] = {}
        headline = 0.0
        for name in ALERT_SPACES:
            space = self.spaces[name]
            total = space.total()
            top, _floor = space.merged_top()
            top1 = (top[0]["count"] / total) if total and top else 0.0
            top8 = (sum(e["count"] for e in top[:8]) / total
                    if total and top else 0.0)
            out[f"{name}.top1_share"] = round(top1, 4)
            out[f"{name}.top8_share"] = round(min(top8, 1.0), 4)
            out[f"{name}.distinct"] = _union_distinct(
                space.cur.bitmap, space.prev.bitmap)
            headline = max(headline, top1)
        out["top1_share"] = round(headline, 4)
        return out

    def prometheus_lines(self, labels: str) -> List[str]:
        """Bounded exposition: at most ``_EXPORT_TOP`` keys per space in
        the ``rmqtt_hotkeys_topk`` gauge family (<= k by construction),
        label values escaped per the exposition grammar and truncated —
        topic/client names are attacker-chosen bytes."""
        self.drain()
        out = ["# TYPE rmqtt_hotkeys_topk gauge"]
        for name in SPACES:
            view = self.spaces[name].view()
            for ent in view["top"][:_EXPORT_TOP]:
                out.append(
                    f'rmqtt_hotkeys_topk{{{labels},space="{name}",'
                    f'key="{_label_escape(ent["key"])}"}} {ent["count"]}')
        out.append("# TYPE rmqtt_hotkeys_top1_share gauge")
        for name in ALERT_SPACES:
            space = self.spaces[name]
            total = space.total()
            top, _floor = space.merged_top()
            share = (top[0]["count"] / total) if total and top else 0.0
            out.append(
                f'rmqtt_hotkeys_top1_share{{{labels},space="{name}"}} '
                f"{round(share, 4)}")
        out.append("# TYPE rmqtt_hotkeys_distinct_keys gauge")
        for name in ALERT_SPACES:
            space = self.spaces[name]
            out.append(
                f'rmqtt_hotkeys_distinct_keys{{{labels},space="{name}"}} '
                f"{_union_distinct(space.cur.bitmap, space.prev.bitmap)}")
        out.append("# TYPE rmqtt_hotkeys_alerts_total counter")
        for name in ALERT_SPACES:
            out.append(
                f'rmqtt_hotkeys_alerts_total{{{labels},space="{name}"}} '
                f"{self.alerts_by_space.get(name, 0)}")
        out.append("# TYPE rmqtt_hotkeys_rotations_total counter")
        out.append(
            f"rmqtt_hotkeys_rotations_total{{{labels}}} {self.rotations}")
        return out

    def sys_payloads(self) -> Dict[str, dict]:
        """The three ``$SYS/brokers/<n>/hotkeys/{topics,clients,
        prefixes}`` bodies (top-8 each, bounded like the scrape)."""
        self.drain()

        def brief(name: str) -> dict:
            v = self.spaces[name].view()
            return {"total": v["total"], "distinct_est": v["distinct_est"],
                    "top": v["top"][:_EXPORT_TOP]}

        return {
            "topics": {"by_count": brief("topics"),
                       "by_bytes": brief("topic_bytes")},
            "clients": {"publishers": brief("publishers"),
                        "subscribers": brief("subscribers")},
            "prefixes": {**brief("prefixes"),
                         "drops": brief("drops")},
        }


def _label_escape(value: str, max_len: int = 120) -> str:
    """Prometheus label-value escaping (backslash, quote, newline) +
    length bound. Sketch keys are raw wire bytes (topics, client ids) —
    they must never be able to break the exposition grammar."""
    if len(value) > max_len:
        value = value[:max_len] + "..."
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))
