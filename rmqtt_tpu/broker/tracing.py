"""Distributed per-publish tracing: where did THIS publish go, and why
was it slow.

PR 2's latency telemetry (`broker/telemetry.py`) answers aggregate
questions; this layer answers per-message ones. A publish entering the
broker gets a 128-bit trace id and a per-hop span buffer; every stage the
telemetry layer already times appends a span *reusing the same
``perf_counter_ns`` reads* (the tracer converts them to wall-clock through
a per-process epoch anchor), so tracing adds allocations but no extra
clock reads on the shared stages. The context crosses the cluster as an
optional ``trace`` field on the FORWARDS / FORWARDS_TO wire bodies
(`cluster/messages.py trace_to_wire`) — spans recorded on the remote node
carry the same trace id and are stitched back together by the trace API
(`/api/v1/traces/<id>`, a ``what=traces`` DATA query per peer) — and
exits through the kafka/nats/pulsar bridge producers as an
``mqtt_trace_id`` message header.

Sampling is HEAD probabilistic plus ALWAYS-RECORD-ON-SLOW:

- a head-SAMPLED publish (probability ``trace_sample``) buffers every span
  and commits at finish;
- an UNSAMPLED publish carries only an armed context: each ``add`` is one
  threshold compare and a drop — no tuple, no id, no epoch math (the
  cfg7 overhead bound is won or lost on this path). The moment a span
  meets the shared ``[observability] slow_ms`` threshold the trace flips
  to recording: the slow span and everything after it (including the
  closing ingress span and any late delivery/ack spans) are kept and the
  trace commits — so "why was that publish slow" is answerable even at
  sample = 0, at the price of the fast spans that preceded the stall.
- the slow-op ring (`telemetry.py`) stamps the active trace id onto its
  entries, joining the two views.
- trace ids are LAZY: generated on first use (commit, cluster wire,
  bridge header, slow-ring stamp) so a fast unsampled publish never pays
  the 128-bit draw.

Disabled mode (``[observability] enable = false``): ``begin`` returns
``None`` and every call site guards on it — no trace ids, no span tuples,
no timestamps, nothing allocated (pinned by test).

The store is bounded two ways: ``trace_max_traces`` committed traces
(FIFO eviction → ``traces_dropped``) and ``trace_max_spans`` spans per
trace (overflow → ``spans_dropped``), so a hot broker can keep tracing at
100% sampling without unbounded growth.
"""

from __future__ import annotations

import contextvars
import random
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

# The active trace for the current asyncio task (set around the publish
# ingress pipeline and the cluster-RPC delivery handlers). Code that runs
# in OTHER tasks (deliver loops, ack handling) gets the trace as an
# explicit reference on DeliverItem/OutEntry instead.
CURRENT_TRACE: contextvars.ContextVar[Optional["Trace"]] = contextvars.ContextVar(
    "rmqtt_trace", default=None
)

# trace lifecycle states
_OPEN = 0       # spans buffering; finish() not yet decided
_COMMITTED = 1  # in the store; late spans append to the stored record
_DROPPED = 2    # sampled out; late SLOW spans can still promote


def new_trace_id() -> str:
    """128-bit trace id as 32 lowercase hex chars (W3C trace-id shape)."""
    return "%032x" % random.getrandbits(128)


class Trace:
    """One publish's span buffer. Cheap on purpose: a handful of slots,
    spans as 4-tuples (dict records only built at commit), id drawn
    lazily; an unsampled-and-not-slow trace drops spans with ONE compare
    — the cfg7 overhead bound is won or lost right there."""

    __slots__ = ("_tid", "sampled", "slow", "topic", "spans", "state",
                 "_tracer", "_record")

    def __init__(self, tracer: "Tracer", tid: Optional[str], sampled: bool,
                 topic: Optional[str] = None) -> None:
        self._tracer = tracer
        self._tid = tid
        self.sampled = sampled
        self.slow = False
        self.topic = topic
        self.spans: List[tuple] = []  # (name, start_epoch_ns, dur_ns, detail)
        self.state = _OPEN
        self._record: Optional[dict] = None

    @property
    def tid(self) -> str:
        """Trace id, drawn on first use (commit / cluster wire / bridge
        header / slow-ring stamp) — fast unsampled publishes never pay the
        128-bit draw."""
        t = self._tid
        if t is None:
            t = self._tid = new_trace_id()
        return t

    def add(self, name: str, t0_perf: int, dur_ns: int, detail: Any = None) -> None:
        """Record a span from a ``perf_counter_ns`` pair ALREADY taken by a
        telemetry stage — tracing never adds clock reads to shared stages.
        Unsampled traces keep nothing until a span crosses the slow
        threshold; from that span on everything is kept (the slow span and
        its aftermath are what make "why was it slow" answerable)."""
        tr = self._tracer
        if dur_ns < tr.slow_ns:
            if not (self.sampled or self.slow):
                return  # unsampled fast span: the hot-path early-out
        else:
            self.slow = True
        self._buffer(name, tr._epoch0 + (t0_perf - tr._perf0), dur_ns, detail)

    def add_wall(self, name: str, dur_ns: int, detail: Any = None) -> None:
        """Span whose only timing is a duration (ack RTT measured off the
        inflight entry's monotonic stamp): start = now - dur."""
        if dur_ns < self._tracer.slow_ns:
            if not (self.sampled or self.slow):
                return
        else:
            self.slow = True
        self._buffer(name, time.time_ns() - dur_ns, dur_ns, detail)

    def _buffer(self, name: str, start_ns: int, dur_ns: int, detail: Any) -> None:
        tr = self._tracer
        if self.state == _COMMITTED:
            # late span (deliver loop / ack, after finish): straight into
            # the stored record so cross-task stages still land — and a
            # late SLOW span must flip the stored flag too, or the trace
            # stays invisible to the slow-only listings
            rec = self._record
            if rec is not None and self.slow:
                rec["slow"] = True
            tr._append_span(rec, name, start_ns, dur_ns, detail)
            return
        if len(self.spans) >= tr.max_spans:
            tr.spans_dropped += 1
            return
        self.spans.append((name, start_ns, dur_ns, detail))
        if self.state == _DROPPED and self.slow:
            # always-record-on-slow, tail edition: a slow span arriving
            # after the sampled-out finish resurrects the trace
            tr.commit(self)


class Tracer:
    """Per-node trace registry: sampling policy + the bounded span store."""

    __slots__ = ("enabled", "sample", "max_traces", "max_spans", "slow_ns",
                 "node_id", "store", "_epoch0", "_perf0", "_rand",
                 "traces_recorded", "traces_sampled_out", "traces_dropped",
                 "spans_recorded", "spans_dropped")

    def __init__(
        self,
        enabled: bool = True,
        sample: float = 0.01,
        max_traces: int = 512,
        max_spans: int = 64,
        slow_ms: float = 100.0,
        node_id: int = 1,
    ) -> None:
        self.enabled = enabled
        self.sample = max(0.0, min(1.0, float(sample)))
        self.max_traces = max(1, int(max_traces))
        self.max_spans = max(1, int(max_spans))
        self.slow_ns = int(slow_ms * 1e6)
        self.node_id = node_id
        self.store: "OrderedDict[str, dict]" = OrderedDict()
        # epoch anchor: span starts come in as perf_counter_ns stamps (the
        # telemetry t0s); one wall/perf pair taken at construction converts
        # them to epoch ns without per-span wall reads. Cross-node span
        # alignment therefore inherits host NTP quality, like any
        # distributed tracer.
        self._epoch0 = time.time_ns()
        self._perf0 = time.perf_counter_ns()
        self._rand = random.random
        self.traces_recorded = 0
        self.traces_sampled_out = 0
        self.traces_dropped = 0  # store evictions (FIFO over max_traces)
        self.spans_recorded = 0
        self.spans_dropped = 0  # per-trace max_spans overflow

    # ---------------------------------------------------------------- begin
    def begin(self, topic: str) -> Optional[Trace]:
        """New trace at publish ingress; None when disabled (the disabled
        contract: no id, no allocation, and call sites take no timestamps).
        The id is drawn lazily (Trace.tid) — begin costs one random() and
        one small object."""
        if not self.enabled:
            return None
        return Trace(self, None, self._rand() < self.sample, topic)

    def from_wire(self, tw, topic: Optional[str] = None) -> Optional[Trace]:
        """Adopt a trace context that rode a cluster wire body
        (``messages.trace_to_wire`` shape: ``[tid, sampled]``); None for
        untraced publishes and frames from older nodes."""
        if not self.enabled or not tw:
            return None
        return Trace(self, str(tw[0]), bool(tw[1]), topic)

    # --------------------------------------------------------------- finish
    def finish(self, trace: Trace) -> None:
        """Head-sampled or slow → commit; otherwise drop (late slow spans
        can still promote, see Trace._add)."""
        if trace.state != _OPEN:
            return
        if trace.sampled or trace.slow:
            self.commit(trace)
        else:
            trace.state = _DROPPED
            self.traces_sampled_out += 1

    def commit(self, trace: Trace) -> None:
        rec = self.store.get(trace.tid)
        if rec is None:
            rec = {
                "trace_id": trace.tid,
                "node": self.node_id,
                "topic": trace.topic,
                "sampled": trace.sampled,
                "slow": False,
                "spans": [],
            }
            self.store[trace.tid] = rec
            self.traces_recorded += 1
            while len(self.store) > self.max_traces:
                self.store.popitem(last=False)
                self.traces_dropped += 1
        else:
            # same id committed twice on one node (e.g. a broadcast
            # FORWARDS and a targeted FORWARDS_TO for the same publish):
            # merge into one record
            self.store.move_to_end(trace.tid)
            rec["topic"] = rec["topic"] or trace.topic
        rec["slow"] = rec["slow"] or trace.slow
        for name, start_ns, dur_ns, detail in trace.spans:
            self._append_span(rec, name, start_ns, dur_ns, detail)
        trace.spans = []
        trace.state = _COMMITTED
        trace._record = rec

    def _append_span(self, rec: Optional[dict], name: str, start_ns: int,
                     dur_ns: int, detail: Any) -> None:
        if rec is None:
            return
        spans = rec["spans"]
        if len(spans) >= self.max_spans:
            self.spans_dropped += 1
            return
        spans.append({
            "name": name,
            "node": self.node_id,
            "start_ns": start_ns,
            "dur_ns": dur_ns,
            "detail": detail,
        })
        self.spans_recorded += 1

    # ---------------------------------------------------------------- reads
    @staticmethod
    def _bounds(rec: dict):
        spans = rec["spans"]
        if not spans:
            return 0, 0
        start = min(s["start_ns"] for s in spans)
        end = max(s["start_ns"] + s["dur_ns"] for s in spans)
        return start, end

    def _export(self, rec: dict) -> dict:
        """Full trace body: spans time-sorted, envelope recomputed."""
        start, end = self._bounds(rec)
        return {
            "trace_id": rec["trace_id"],
            "topic": rec["topic"],
            "sampled": rec["sampled"],
            "slow": rec["slow"],
            "nodes": sorted({s["node"] for s in rec["spans"]}),
            "ts": round(start / 1e9, 6),
            "dur_ms": round((end - start) / 1e6, 3),
            "spans": sorted(rec["spans"], key=lambda s: s["start_ns"]),
        }

    def _summary(self, rec: dict) -> dict:
        start, end = self._bounds(rec)
        return {
            "trace_id": rec["trace_id"],
            "topic": rec["topic"],
            "sampled": rec["sampled"],
            "slow": rec["slow"],
            "nodes": sorted({s["node"] for s in rec["spans"]}),
            "ts": round(start / 1e9, 6),
            "dur_ms": round((end - start) / 1e6, 3),
            "spans": len(rec["spans"]),
        }

    def get(self, tid: str) -> Optional[dict]:
        rec = self.store.get(tid)
        return self._export(rec) if rec is not None else None

    def recent(self, limit: int = 50) -> List[dict]:
        """Newest-first summaries of the committed traces."""
        out = []
        for rec in reversed(self.store.values()):
            if len(out) >= limit:
                break
            out.append(self._summary(rec))
        return out

    def slow_traces(self, limit: int = 50) -> List[dict]:
        out = []
        for rec in reversed(self.store.values()):
            if len(out) >= limit:
                break
            if rec["slow"]:
                out.append(self._summary(rec))
        return out

    @staticmethod
    def merge_traces(parts: List[dict]) -> dict:
        """Stitch one trace's per-node exports (`/api/v1/traces/<id>`
        cluster fetch): union of spans sorted on the shared timeline."""
        spans: List[dict] = []
        nodes: set = set()
        topic = None
        slow = sampled = False
        for p in parts:
            spans.extend(p.get("spans", []))
            nodes.update(p.get("nodes", []))
            topic = topic or p.get("topic")
            slow = slow or bool(p.get("slow"))
            sampled = sampled or bool(p.get("sampled"))
        spans.sort(key=lambda s: s["start_ns"])
        start = min((s["start_ns"] for s in spans), default=0)
        end = max((s["start_ns"] + s["dur_ns"] for s in spans), default=0)
        return {
            "trace_id": parts[0]["trace_id"],
            "topic": topic,
            "sampled": sampled,
            "slow": slow,
            "nodes": sorted(nodes),
            "ts": round(start / 1e9, 6),
            "dur_ms": round((end - start) / 1e6, 3),
            "spans": spans,
        }

    @staticmethod
    def dedup_summaries(rows: List[dict]) -> List[dict]:
        """Collapse per-node summaries of the same trace (cluster-merged
        recent/slow listings): union nodes, sum span counts, keep the
        earliest start."""
        by_id: Dict[str, dict] = {}
        for r in rows:
            cur = by_id.get(r["trace_id"])
            if cur is None:
                by_id[r["trace_id"]] = dict(r)
                continue
            cur["spans"] += r["spans"]
            cur["nodes"] = sorted(set(cur["nodes"]) | set(r["nodes"]))
            cur["slow"] = cur["slow"] or r["slow"]
            cur["sampled"] = cur["sampled"] or r["sampled"]
            cur["topic"] = cur["topic"] or r.get("topic")
            if r["ts"] and (not cur["ts"] or r["ts"] < cur["ts"]):
                cur["ts"] = r["ts"]
            cur["dur_ms"] = max(cur["dur_ms"], r["dur_ms"])
        return sorted(by_id.values(), key=lambda r: r["ts"], reverse=True)

    # ------------------------------------------------------------- surfaces
    def snapshot(self) -> dict:
        """Counters + store gauge for $SYS and the trace API envelope;
        shape-stable whether or not tracing has seen traffic."""
        return {
            "enabled": self.enabled,
            "sample": self.sample,
            "stored_traces": len(self.store),
            "max_traces": self.max_traces,
            "max_spans": self.max_spans,
            "traces_recorded": self.traces_recorded,
            "traces_sampled_out": self.traces_sampled_out,
            "traces_dropped": self.traces_dropped,
            "spans_recorded": self.spans_recorded,
            "spans_dropped": self.spans_dropped,
        }

    def prometheus_lines(self, labels: str) -> List[str]:
        """Exposition lines for the scrape endpoint: monotonic counters
        (conventional ``_total`` suffix) + the store-size gauge."""
        counters = (
            ("rmqtt_tracing_traces_recorded_total", self.traces_recorded),
            ("rmqtt_tracing_traces_sampled_out_total", self.traces_sampled_out),
            ("rmqtt_tracing_traces_dropped_total", self.traces_dropped),
            ("rmqtt_tracing_spans_recorded_total", self.spans_recorded),
            ("rmqtt_tracing_spans_dropped_total", self.spans_dropped),
        )
        out: List[str] = []
        for name, v in counters:
            out.append(f"# TYPE {name} counter")
            out.append(f"{name}{{{labels}}} {v}")
        out.append("# TYPE rmqtt_tracing_stored_traces gauge")
        out.append(f"rmqtt_tracing_stored_traces{{{labels}}} {len(self.store)}")
        return out
